#!/usr/bin/env bash
# Tier-2 ingest byte-plane gate (ISSUE 11): publish-side topic prep on
# the topic-diversity corpus, asserting the byte-plane contract:
#   1. batched byte-plane prep (TopicBytes pack + native/numpy tokenize)
#      is >=10x the per-message python-loop path at batch >= 1024,
#   2. EXACT three-way parity — python loop ≡ vectorized numpy ≡ native
#      C++ ≡ device kernel (interpret on CPU) — on adversarial topics,
#   3. the profiler split attributes a `tokenize` stage on every device
#      batch served through the matcher (sync and async legs).
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${INGEST_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import asyncio, os, time

import numpy as np

from bifromq_tpu import workloads
from bifromq_tpu.models import bytetok
from bifromq_tpu.models.automaton import tokenize
from bifromq_tpu.models.bytetok import TopicBytes
from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.obs import OBS
from bifromq_tpu.types import RouteMatcher

BATCH = int(os.environ.get("INGEST_CHECK_BATCH", "2048"))
SPEEDUP_MIN = float(os.environ.get("INGEST_CHECK_SPEEDUP", "10"))
assert BATCH >= 1024, "the gate bar is defined at batch >= 1024"

corpus = workloads.diverse_topics(BATCH * 4, seed=7)
batches = [corpus[i * BATCH:(i + 1) * BATCH] for i in range(4)]
roots = [0] * BATCH

# ---- 1. throughput: byte plane vs per-message python loop -------------
# best-of-N: the byte plane's MT hash halves under a busy sibling core
# on a 2-core CI box (the single-threaded python baseline doesn't), so
# a transient background load would fail the ratio spuriously; more
# reps + a settle pause let at least one rep run uncontended
time.sleep(float(os.environ.get("INGEST_CHECK_SETTLE_S", "2")))

def timed(fn, legs, reps=5):
    fn(0)
    best = 0.0
    for _ in range(reps):
        s = time.perf_counter()
        for it in range(legs):
            fn(it)
        best = max(best, BATCH * legs / (time.perf_counter() - s))
    return best

def py_leg(it):
    for t in batches[it % 4]:
        tokenize([t], roots[:1], max_levels=16, salt=0, native=False)

py_rate = timed(py_leg, legs=1, reps=2)
byte_rate = timed(lambda it: tokenize(
    TopicBytes.from_topics(batches[it % 4]), roots, max_levels=16,
    salt=0), legs=8)
speedup = byte_rate / max(1e-9, py_rate)
print(f"prep: python-loop {py_rate:,.0f}/s, byte-plane "
      f"{byte_rate:,.0f}/s -> {speedup:.1f}x (bar {SPEEDUP_MIN}x)")
assert speedup >= SPEEDUP_MIN, \
    f"byte-plane prep only {speedup:.1f}x the python loop"

# ---- 2. exact multi-way parity on adversarial topics ------------------
adversarial = corpus[:512] + [
    "", "/", "//", "a//b", "$SYS/health", "$share/g/dev/1",
    "héllo/wörld/日本語", "x" * 200 + "/" + "y" * 300,
    "a/" * 20 + "deep", "trailing/", "/leading",
]
n = len(adversarial)
tb = TopicBytes.from_topics(adversarial)
rts = list(range(n))
py = tokenize(adversarial, rts, max_levels=16, salt=3, native=False)
nat = tokenize(tb, rts, max_levels=16, salt=3)
h1, h2, ln, rv, sm = bytetok.tokenize_bytes(tb, rts, max_levels=16,
                                            salt=3)
for name, a, b in (("native.h1", py.tok_h1, nat.tok_h1),
                   ("native.h2", py.tok_h2, nat.tok_h2),
                   ("native.len", py.lengths, nat.lengths),
                   ("numpy.h1", py.tok_h1, h1),
                   ("numpy.h2", py.tok_h2, h2),
                   ("numpy.len", py.lengths, ln),
                   ("numpy.sys", py.sys_mask, sm)):
    assert np.array_equal(a, b), f"parity break: {name}"
from bifromq_tpu.ops.tokenize import device_tokenize
mirror, probes = device_tokenize(tb, rts, max_levels=16, salt=3)
sup = mirror.lengths[:n] >= 0
dh1 = np.asarray(probes.tok_h1)[:n]
dh2 = np.asarray(probes.tok_h2)[:n]
assert np.array_equal(dh1[sup], py.tok_h1[:n][sup]), "device h1 parity"
assert np.array_equal(dh2[sup], py.tok_h2[:n][sup]), "device h2 parity"
assert sup.sum() >= n - 2, "device path rejected too many rows"
print(f"parity: python ≡ native ≡ numpy ≡ device "
      f"({int(sup.sum())}/{n} device-supported rows)")

# ---- 3. tokenize stage attributed on every device batch ---------------
def mk(tf, rid):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=rid, deliverer_key="d0", incarnation=1)

m = TpuMatcher(auto_compact=False, match_cache=None)
for i in range(64):
    m.add_route("tenant0", mk(f"dev/{i}/+", f"r{i}"))
m.refresh()
b0 = OBS.profiler.batches_total
m.match_batch([("tenant0", f"dev/{i}/x") for i in range(32)])

async def run():
    for i in range(4):
        await m.match_batch_async(
            [("tenant0", f"dev/{j}/y{i}") for j in range(16)])
asyncio.run(run())
n_new = OBS.profiler.batches_total - b0
assert n_new > 0, "no device batches recorded in the gate window"
recs = OBS.profiler.records()[-n_new:]
assert recs, "no device batches recorded"
assert all(r.tokenize_s > 0 for r in recs if r.kernel != "oracle"), \
    "a device batch lacked tokenize attribution"
split = OBS.profiler.split_snapshot(probe=False)
assert "tokenize_ms_p50" in split, split.keys()
from bifromq_tpu.utils.metrics import STAGES
assert "tokenize" in STAGES.snapshot(), "tokenize stage histogram empty"
print(f"profiler: tokenize stage on all {len(recs)} device batches "
      f"(p50 {split['tokenize_ms_p50']}ms)")
print("INGEST CHECK PASSED")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "INGEST CHECK FAILED (rc=$rc)" >&2
fi
exit $rc
