#!/usr/bin/env bash
# Tier-2 capacity & continuous-profiling gate (ISSUE 8). Asserts:
#   1. the capacity model's predicted device bytes match the live jax
#      buffer bytes within 10% (CPU backend — the acceptance bar),
#   2. the planner's fits() reproduces the fused-kernel VMEM gate
#      verdict for the 1M-sub table WITHOUT dispatching anything,
#   3. a pipelined serving run leaves a live profiler ledger (rtt/kernel
#      split, padding waste, compile events) and bench.py stamps the
#      same snapshot into its record (code-path probed directly),
#   4. the segment store survives a simulated process restart with
#      retention enforced,
#   5. BIFROMQ_OBS_FORMAT=otlp output validates against the checked-in
#      scripts/otlp_schema.json.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the sibling gates.
set -o pipefail

cd "$(dirname "$0")/.."

STORE_DIR="$(mktemp -d /tmp/profile_check_XXXX)"
trap 'rm -rf "$STORE_DIR"' EXIT

timeout -k 10 "${PROFILE_CHECK_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu \
        BIFROMQ_OBS_STORE="$STORE_DIR/segs" \
        BIFROMQ_OBS_STORE_SEGMENT_BYTES=4096 \
        BIFROMQ_OBS_STORE_SEGMENTS=4 \
    python - <<'EOF'
import asyncio, json, os, sys, time

def check(cond, msg):
    assert cond, msg
    print(f"OK {msg}")

async def main():
    from bifromq_tpu.models.matcher import TpuMatcher
    from bifromq_tpu.models.oracle import Route
    from bifromq_tpu.obs import OBS, SegmentStore
    from bifromq_tpu.obs import capacity as cap
    from bifromq_tpu.types import RouteMatcher

    def mk(tf, rid):
        return Route(matcher=RouteMatcher.from_topic_filter(tf),
                     broker_id=0, receiver_id=rid, deliverer_key="d")

    # ---- 1. model-vs-live parity --------------------------------------
    m = TpuMatcher(auto_compact=False)
    for i in range(500):
        m.add_route("T", mk(f"gate/{i}/+", f"r{i}"))
    m.refresh()
    rep = cap.measure(m)
    check(rep["installed"] and rep["parity_error"] < 0.10,
          f"capacity parity {rep['parity_error']:.4f} < 10% "
          f"({rep['measured_device_bytes']} bytes live)")

    # ---- 2. the 1M-sub fused-VMEM verdict, no dispatch ----------------
    from bifromq_tpu.models.kernels import (fused_fits_vmem,
                                            fused_vmem_budget_bytes)
    verdict = cap.default_planner([m]).fits(1_000_000)
    fv = verdict["fused_vmem"]
    check(fv["budget_bytes"] == fused_vmem_budget_bytes()
          and fv["fits"] is fused_fits_vmem(fv["table_bytes"])
          and fv["fits"] is False,
          f"planner 1M-sub VMEM verdict: {fv['table_bytes']>>20}MB > "
          f"{fv['budget_bytes']>>20}MB budget (gate-identical compare)")
    small = cap.default_planner([m]).fits(200)
    check(small["fused_vmem"]["fits"] is True,
          "planner small-table VMEM verdict fits")

    # ---- 3. pipelined serving fills the profiler + bench stamps it ----
    for i in range(40):
        await m.match_batch_async([("T", ["gate", str(i % 7), "x"])])
    prof = OBS.profiler.snapshot(brief=True)
    check(prof["batches"] >= 1
          and "dispatch_ms_p50" in prof["split"]
          and "device_kernel_ms_est" in prof["split"],
          f"profiler split live ({prof['split']['window_batches']} "
          f"batches, rtt={prof['split']['tunnel_rtt_ms']}ms)")
    check(prof["compile_ledger"]["total"] >= 1
          and prof["compile_ledger"]["events"],
          f"compile ledger attributed "
          f"({prof['compile_ledger']['total']} events, last reason="
          f"{prof['compile_ledger']['events'][-1]['reason']})")
    check(prof["cache_bypass_rate"] > 0,
          f"cache bypasses profiled (rate="
          f"{prof['cache_bypass_rate']})")
    # the bench stamps THIS snapshot into every record — probe the same
    # code path bench.py runs (a full bench is a different gate's job)
    src = open("bench.py").read()
    check('record["profile"]' in src and 'record["capacity"]' in src,
          "bench.py stamps profile + capacity snapshots")

    # ---- 4. segment store: restart survival + retention ---------------
    check(OBS.start_persistence(), "segment store armed from env")
    for _ in range(30):                   # force rotations past 4 segs
        OBS.profiler.record_batch(n_queries=4, batch=16, kernel="lax",
                                  dispatch_s=0.001, ready_s=0.002,
                                  fetch_s=0.001)
        OBS.persist_now()
    snap1 = OBS.store.snapshot()
    OBS.stop_persistence(final_flush=False)
    st2 = SegmentStore(os.environ["BIFROMQ_OBS_STORE"],
                       max_segment_bytes=4096, max_segments=4)
    snap2 = st2.snapshot()
    recs = st2.read()
    check(recs and snap2["segments"] <= 4
          and snap2["active_seq"] == snap1["active_seq"],
          f"store survives restart ({len(recs)} records, "
          f"{snap2['segments']} segments retained, "
          f"{snap1['segments_dropped']} dropped)")
    kinds = {r.get("type") for r in recs}
    check("profile" in kinds and "profile_summary" in kinds,
          f"store record types {sorted(k for k in kinds if k)}")

    # ---- 5. OTLP output validates against the checked-in schema -------
    from bifromq_tpu import trace
    from bifromq_tpu.obs import FileSink, TelemetryExporter
    otlp_path = os.path.join(os.path.dirname(
        os.environ["BIFROMQ_OBS_STORE"]), "otlp.jsonl")
    old_slow, trace.TRACER.slow_ms = trace.TRACER.slow_ms, 0.0001
    try:
        with trace.span("pub.ingest", tenant="gate"):
            time.sleep(0.002)
        exp = TelemetryExporter(
            FileSink(otlp_path), interval_s=60, framing="otlp",
            snapshot_fn=lambda: OBS.profiler.snapshot(brief=True),
            resource=OBS.resource_envelope())
        exp.enqueue({"type": "profile", "ts": time.time(),
                     **OBS.profiler.snapshot(brief=True)})
        await exp._flush_once()
    finally:
        trace.TRACER.slow_ms = old_slow

    schema = json.load(open("scripts/otlp_schema.json"))

    def validate(obj, sch, path="$"):
        """Subset JSON-Schema validator: type, required, properties,
        items, minItems, oneOf."""
        if "oneOf" in sch:
            errs = []
            for i, branch in enumerate(sch["oneOf"]):
                try:
                    validate(obj, branch, f"{path}<{i}>")
                    return
                except AssertionError as e:
                    errs.append(str(e))
            raise AssertionError(f"{path}: no oneOf branch matched: "
                                 + " | ".join(errs))
        t = sch.get("type")
        if t:
            pytype = {"object": dict, "array": list, "string": str,
                      "number": (int, float), "boolean": bool}[t]
            assert isinstance(obj, pytype), f"{path}: not {t}"
        for req in sch.get("required", ()):
            assert req in obj, f"{path}: missing {req!r}"
        for k, sub in sch.get("properties", {}).items():
            if isinstance(obj, dict) and k in obj:
                validate(obj[k], sub, f"{path}.{k}")
        if "items" in sch and isinstance(obj, list):
            assert len(obj) >= sch.get("minItems", 0), \
                f"{path}: fewer than minItems"
            for i, el in enumerate(obj):
                validate(el, sch["items"], f"{path}[{i}]")

    lines = [ln for ln in open(otlp_path).read().splitlines() if ln]
    assert lines, "otlp exporter wrote nothing"
    kinds = set()
    for ln in lines:
        obj = json.loads(ln)
        validate(obj, schema)
        kinds |= set(obj.keys())
    check({"resourceSpans", "resourceMetrics", "resourceLogs"} <= kinds,
          f"{len(lines)} OTLP lines validate against "
          f"scripts/otlp_schema.json ({sorted(kinds)})")

asyncio.run(main())
print("profile_check PASSED")
EOF
rc=$?
if [ $rc -eq 124 ] || [ $rc -eq 137 ]; then
    echo "profile check TIMED OUT (rc=$rc)" >&2
fi
exit $rc
