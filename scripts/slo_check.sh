#!/usr/bin/env bash
# Tier-2 delivery-SLO gate (ISSUE 20): the e2e latency plane, the
# multi-window burn-rate engine, and per-shard completion attribution,
# end to end through a live broker + API. Asserts:
#   1. BURN LIFECYCLE — real deliveries attribute per path and feed the
#      burn denominator; a driven violation storm fires SLO_BURN (fast
#      AND slow windows over threshold), surfaces on GET /slo,
#      GET /tenants/<id> and GET /cluster/slo, feeds the shedder
#      advisory, and recovers with exactly one SLO_RECOVERED after the
#      storm clears the slow window + cooldown,
#   2. SHARD ATTRIBUTION — an injected device hang (tpu-device fault
#      rule) on one mesh shard NAMES that shard: hung in the /mesh
#      completion board, mesh:shard<k> in the e2e degraded set; both
#      clear after the rule is removed and the canary re-closes,
#   3. OTLP FRAMING — slo_event records ship through the exporter in
#      both framings; the OTLP lines validate against
#      scripts/otlp_schema.json (resourceLogs envelope).
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${SLO_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BIFROMQ_DEVICE_DEADLINE_S=0.3 \
    python - <<'EOF'
import asyncio, json, os, time

from bifromq_tpu.obs import OBS, FileSink, TelemetryExporter
from bifromq_tpu.obs.burnrate import SLO_EVENTS
from bifromq_tpu.utils.hlc import HLC


async def http(port, method, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"{method} {path} HTTP/1.1\r\nhost: x\r\n"
                 f"content-length: 0\r\nconnection: close\r\n\r\n"
                 .encode())
    await writer.drain()
    raw = await reader.read(524288)
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), json.loads(payload)


def check(ok, msg):
    if not ok:
        raise SystemExit(f"[slo_check] FAILED: {msg}")
    print(f"[slo_check] ok: {msg}")


async def main():
    from bifromq_tpu.apiserver import APIServer
    from bifromq_tpu.mqtt.broker import MQTTBroker
    from bifromq_tpu.mqtt.client import MQTTClient

    OBS.reset()
    OBS.enabled = True
    broker = MQTTBroker(port=0)
    await broker.start()
    api = APIServer(broker, port=0)
    await api.start()

    # ---- 1. burn lifecycle through the API ------------------------------
    sub = MQTTClient(port=broker.port, client_id="s1", username="good/s")
    await sub.connect()
    await sub.subscribe("a/t", qos=1)
    pub = MQTTClient(port=broker.port, client_id="p1", username="good/p")
    await pub.connect()
    # warm the match path first: the FIRST publish pays the device
    # kernel compile (seconds on CPU) — a real latency the e2e plane
    # faithfully records, but not the steady state this gate scores
    await pub.publish("a/t", b"warm", qos=0)
    await sub.recv()

    code, out = await http(
        api.port, "PUT",
        "/obs?slo_fast_window_s=1&slo_slow_window_s=2"
        "&slo_cooldown_s=0.5&slo_burn_threshold=2")
    check(code == 200 and out["slo"]["fast_window_s"] == 1.0,
          "PUT /obs installs burn knobs (clears pre-warm burn state)")
    OBS.e2e.reset()

    for i in range(20):
        await pub.publish("a/t", b"x", qos=i % 2)
    for _ in range(20):
        await sub.recv()
    code, out = await http(api.port, "GET", "/slo")
    paths = out["e2e"]["tenants"]["good"]["paths"]["local_fanout"]
    check(paths["qos0"]["count"] == 10 and paths["qos1"]["count"] == 10,
          "full-population e2e attribution per (path, qos)")

    # violation storm: every record is a delivery the victim never got
    for _ in range(50):
        OBS.record_delivery_violation("victim", 0, "shed")
    OBS.burnrate.evaluate()
    code, out = await http(api.port, "GET", "/slo")
    check("victim" in out["burn"]["burning"]
          and any(e["kind"] == "slo_burn" for e in out["events"]),
          "violation storm fires SLO_BURN on GET /slo")
    check(OBS.is_burning("victim"), "shedder advisory sees the burn")
    code, out = await http(api.port, "GET", "/tenants/victim")
    check(code == 200 and out["burn"]["burning"], "/tenants/<id> burn")
    code, out = await http(api.port, "GET", "/cluster/slo")
    check("victim" in out["burning"], "/cluster/slo federates the burn")
    check("good" not in out["burning"], "healthy tenant never burns")

    # storm clears: slow window (2s) + cooldown drain, then recovery
    deadline = time.monotonic() + 15.0
    recovered = False
    while time.monotonic() < deadline and not recovered:
        await asyncio.sleep(0.5)
        OBS.burnrate.evaluate()
        recovered = not OBS.is_burning("victim")
    kinds = [e["kind"] for e in SLO_EVENTS.tail(100)
             if e["tenant"] == "victim"]
    check(recovered and kinds == ["slo_burn", "slo_recovered"],
          f"one burn episode, one recovery ({kinds})")

    # ---- 2. injected device hang names the shard ------------------------
    from bifromq_tpu.models.oracle import Route
    from bifromq_tpu.parallel.sharded import MeshMatcher, make_mesh
    from bifromq_tpu.resilience.faults import get_injector
    from bifromq_tpu.types import RouteMatcher

    def rt(tf, i):
        return Route(matcher=RouteMatcher.from_topic_filter(tf),
                     broker_id=0, receiver_id=f"r{i}",
                     deliverer_key=f"d{i}", incarnation=0)

    m = MeshMatcher(mesh=make_mesh(1, 4), max_levels=8, k_states=16,
                    match_cache=False, auto_compact=False)
    tens = [f"t{i}" for i in range(24)]
    for i, t in enumerate(tens):
        m.add_route(t, rt(f"a/{i}/+", i))
    m.refresh()
    sick = m._base_ct.shard_of("t0")
    inj = get_injector()
    rule = inj.add_rule(service="tpu-device",
                        method=f"mesh:shard{sick}", action="hang",
                        side="device")
    qs = [(t, f"a/{i}/x") for i, t in enumerate(tens)]
    try:
        for _ in range(4):
            await m.match_batch_async(qs)
    finally:
        inj.remove_rule(rule)
    code, out = await http(api.port, "GET", "/mesh")
    comp = next(s["completion"] for s in out["meshes"]
                if "completion" in s)
    check(sick in comp["hung"]
          and comp["shards"][str(sick)]["hung"] is True,
          f"hung device NAMED in /mesh completion (shard {sick})")
    code, out = await http(api.port, "GET", "/slo")
    check(f"mesh:shard{sick}" in out["e2e"]["degraded"],
          "e2e degraded attribution names mesh:shard%d" % sick)

    # recovery: rule gone, canary re-closes, rows note ready again
    m.shard_breakers[sick].recovery_time = 0.0
    await m.match_batch_async(qs)
    check(m.shard_breakers[sick].state == "closed", "canary re-closed")
    code, out = await http(api.port, "GET", "/mesh")
    comp = next(s["completion"] for s in out["meshes"]
                if "completion" in s)
    check(comp["hung"] == [], "completion board clears after recovery")
    code, out = await http(api.port, "GET", "/slo")
    check(f"mesh:shard{sick}" not in out["e2e"]["degraded"],
          "degraded attribution clears after recovery")

    # ---- 3. OTLP framing of slo_event records ---------------------------
    otlp_path = "/tmp/slo_check_otlp.jsonl"
    try:
        os.unlink(otlp_path)
    except FileNotFoundError:
        pass
    exp = TelemetryExporter(FileSink(otlp_path), interval_s=60,
                            framing="otlp",
                            resource=OBS.resource_envelope())
    await exp._flush_once()      # drains the SLO journal from phase 1

    schema = json.load(open("scripts/otlp_schema.json"))

    def validate(obj, sch, path="$"):
        if "oneOf" in sch:
            errs = []
            for i, branch in enumerate(sch["oneOf"]):
                try:
                    validate(obj, branch, f"{path}<{i}>")
                    return
                except AssertionError as e:
                    errs.append(str(e))
            raise AssertionError(f"{path}: no oneOf branch matched: "
                                 + " | ".join(errs))
        t = sch.get("type")
        if t:
            pytype = {"object": dict, "array": list, "string": str,
                      "number": (int, float), "boolean": bool}[t]
            assert isinstance(obj, pytype), f"{path}: not {t}"
        for req in sch.get("required", ()):
            assert req in obj, f"{path}: missing {req!r}"
        for k, sub in sch.get("properties", {}).items():
            if isinstance(obj, dict) and k in obj:
                validate(obj[k], sub, f"{path}.{k}")
        if "items" in sch and isinstance(obj, list):
            assert len(obj) >= sch.get("minItems", 0), \
                f"{path}: fewer than minItems"
            for i, el in enumerate(obj):
                validate(el, sch["items"], f"{path}[{i}]")

    lines = [ln for ln in open(otlp_path).read().splitlines() if ln]
    check(bool(lines), "otlp exporter wrote envelopes")
    slo_bodies = 0
    for ln in lines:
        obj = json.loads(ln)
        validate(obj, schema)
        for rl in obj.get("resourceLogs", []):
            for sl in rl.get("scopeLogs", []):
                for rec in sl.get("logRecords", []):
                    body = rec.get("body", {}).get("stringValue", "")
                    if '"slo_burn"' in body or '"slo_recovered"' in body:
                        slo_bodies += 1
    check(slo_bodies >= 2,
          f"{slo_bodies} slo_event records validate against "
          f"scripts/otlp_schema.json")

    for c in (sub, pub):
        await c.disconnect()
    await api.stop()
    broker.inbox.close()
    await broker.stop()
    OBS.reset()
    print("[slo_check] PASS")


asyncio.run(main())
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "[slo_check] FAIL (rc=$rc)"
    exit $rc
fi
