#!/usr/bin/env bash
# Tier-2 retained & session plane gate (ISSUE 13): a >=10k-mutation
# retained SET/CLEAR flood against a live PATCHED RetainedIndex on CPU,
# asserting the serving-plane contract:
#   1. ZERO full rebuilds inside the flood window — set/clear/expire are
#      in-place arena patches; compilation is allowed ONLY as the
#      fragmentation-triggered compaction,
#   2. device wildcard-scan results byte-identical (as sorted topic
#      sets) to the host match_filter_host oracle BEFORE, DURING and
#      AFTER the storm — including $SYS roots and '#'/'+' folds — and
#      identical to a from-scratch rebuild after it,
#   3. the async scan plane serves through the ring with the
#      filter-keyed cache hitting on the repeat pass and a forced
#      watchdog timeout degrading to the exact oracle,
#   4. a herd-vs-quiet reconnect drain storm admits tenant-fairly (the
#      quiet tenant's sessions never queue behind the herd).
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${RETAINED_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import asyncio
import os
import random
import time

from bifromq_tpu.models.retained import RetainedIndex, match_filter_host
from bifromq_tpu.retained_plane import DrainGovernor, RetainedScanPlane
from bifromq_tpu.utils import topic as t

N_BASE = int(os.environ.get("RETAINED_CHECK_BASE", "4000"))
N_OPS = int(os.environ.get("RETAINED_CHECK_OPS", "10000"))

rng = random.Random(17)
NAMES = [f"l{i}" for i in range(200)] + ["", "$s"]


def rand_topic(i=None):
    n = rng.randint(1, 6)
    lv = [rng.choice(NAMES) for _ in range(n)]
    if rng.random() < 0.03:
        lv = ["$SYS"] + lv
    if i is not None:
        lv.append(f"d{i}")
    return "/".join(lv)


def rand_filter():
    n = rng.randint(1, 6)
    lv = []
    for i in range(n):
        roll = rng.random()
        if roll < 0.25:
            lv.append("+")
        elif roll < 0.33 and i == n - 1:
            lv.append("#")
        else:
            lv.append(rng.choice(NAMES))
    return lv


FILTERS = [rand_filter() for _ in range(96)] + \
    [["#"], ["+"], ["$SYS", "#"], ["+", "+"], ["+", "#"]]


def check_parity(idx, tag):
    got = idx.match_batch([("T", f) for f in FILTERS])
    trie = idx.tries.get("T")
    for f, g in zip(FILTERS, got):
        want = sorted(match_filter_host(trie, f)) if trie else []
        assert sorted(g) == want, (tag, f, len(g), len(want))


live = set()
while len(live) < N_BASE:
    live.add(rand_topic())
idx = RetainedIndex(k_states=16)
for topic in sorted(live):
    idx.add_topic("T", t.parse(topic), topic)
idx.refresh()
assert hasattr(idx._compiled, "retained_add"), \
    "index is not patched — BIFROMQ_RETAIN_PATCH off?"
rebuilds0 = idx.rebuilds
check_parity(idx, "before")

# ---- the flood: >=10k set/clear/expire-shaped mutations ----------------
t0 = time.perf_counter()
pool = sorted(live)
for i in range(N_OPS):
    roll = rng.random()
    if roll < 0.55:
        topic = rand_topic(i)
        if topic not in live:
            idx.add_topic("T", t.parse(topic), topic)
            live.add(topic)
            pool.append(topic)
    elif roll < 0.85 and pool:
        topic = pool.pop(rng.randrange(len(pool)))
        if topic in live:
            idx.remove_topic("T", t.parse(topic), topic)
            live.discard(topic)
    elif pool:
        topic = pool[rng.randrange(len(pool))]   # re-SET (payload only)
        idx.add_topic("T", t.parse(topic), topic)
    if i == N_OPS // 2:
        check_parity(idx, "during")
flood_s = time.perf_counter() - t0
check_parity(idx, "after")
assert idx.rebuilds == rebuilds0, \
    f"flood ran {idx.rebuilds - rebuilds0} full rebuilds"
assert idx.patch_fallbacks == 0, idx.patch_fallbacks
print(f"flood: {N_OPS} ops in {flood_s:.1f}s "
      f"({N_OPS / flood_s:,.0f} ops/s), rebuilds=0, "
      f"compactions={idx.compactions}, "
      f"patch={idx._compiled.patch_stats()}")

# patched index == from-scratch rebuild
fresh = RetainedIndex(patched=False, k_states=16)
for topic in sorted(live):
    fresh.add_topic("T", t.parse(topic), topic)
fresh.refresh()
got = idx.match_batch([("T", f) for f in FILTERS])
want = fresh.match_batch([("T", f) for f in FILTERS])
for f, g, w in zip(FILTERS, got, want):
    assert sorted(g) == sorted(w), ("rebuild-parity", f)
print("patched == post-compaction rebuild == host oracle: OK")


# ---- async scan plane: ring + cache + watchdog degradation -------------
async def scan_leg():
    plane = RetainedScanPlane(lambda: idx)
    idx.delta_hooks.append(plane.cache.on_delta)
    queries = [("T", f) for f in FILTERS[:64]]
    rows = await plane.scan_batch(queries, limit=10)
    trie = idx.tries["T"]
    for (tenant, f), row in zip(queries, rows):
        full = match_filter_host(trie, list(f))
        assert len(row) == min(10, len(full)) and set(row) <= set(full)
    h0 = plane.cache.hits
    await plane.scan_batch(queries, limit=10)
    hit_rate = (plane.cache.hits - h0) / len(queries)
    assert hit_rate > 0.95, hit_rate
    from bifromq_tpu.resilience.device import DeviceTimeoutError
    ring = plane._pipeline_ring()

    async def hang(res, **kw):
        raise DeviceTimeoutError(0.01)
    orig = ring.wait_ready
    ring.wait_ready = hang
    rows = await plane.scan_batch([("T", ["#"])])
    ring.wait_ready = orig
    assert sorted(rows[0]) == sorted(match_filter_host(trie, ["#"]))
    assert plane.degraded_total.get("timeout") == 1
    print(f"scan plane: repeat hit rate {hit_rate:.2f}, watchdog "
          f"timeout degraded to exact oracle: OK")

asyncio.run(scan_leg())


# ---- drain storm: herd tenant vs quiet tenants must stay fair ----------
async def drain_leg():
    gov = DrainGovernor(slots=8, per_tenant=2, noisy_fn=lambda t_: False)
    waits = {}

    async def one(tenant):
        s0 = time.perf_counter()
        async with gov.slot(tenant):
            await asyncio.sleep(0.002)
        waits.setdefault(tenant, []).append(time.perf_counter() - s0)

    herd = [one("A") for _ in range(160)]
    quiet = [one(f"q{i % 4}") for i in range(8)]
    await asyncio.gather(*herd, *quiet)
    herd_mean = sum(waits["A"]) / len(waits["A"])
    qs = [w for k, ws in waits.items() if k != "A" for w in ws]
    quiet_mean = sum(qs) / len(qs)
    assert quiet_mean < herd_mean / 4, (quiet_mean, herd_mean)
    print(f"drain storm: herd mean {herd_mean * 1e3:.1f}ms, quiet mean "
          f"{quiet_mean * 1e3:.1f}ms — tenant-fair: OK")

asyncio.run(drain_leg())
print("RETAINED CHECK PASSED")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "RETAINED CHECK FAILED (rc=$rc)"
fi
exit $rc
