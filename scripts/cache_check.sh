#!/usr/bin/env bash
# Tier-2 match-result cache gate (ISSUE 4): exercises the TenantMatchCache
# plane in front of TpuMatcher.match_batch on CPU and asserts
#   1. a repeated-topic (Zipf) workload shows >80% hit rate,
#   2. every cached serve is bit-identical to the host oracle — including
#      across interleaved route mutations (filter-aware invalidation),
#   3. the unique-topic miss path does not regress vs cache-off
#      (generous 1.5x wall-clock bound: CI boxes are noisy).
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the chaos/obs gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${CACHE_CHECK_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import random, time

from bifromq_tpu import workloads
from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.oracle import Route, SubscriptionTrie
from bifromq_tpu.types import RouteMatcher

N_SUBS = 20_000
BATCH = 256
HOT_TOPICS = 48

tries = workloads.config_wildcard(N_SUBS, seed=0)
rng = random.Random(11)


def clone_tries(src):
    """Independent copy: from_tries SHARES trie objects, so the mutation
    phase below must not pollute the pristine set the unique-topic A/B
    matchers are built from (a leaked 'gate/#' route would inflate every
    query's walk work in both legs)."""
    out = {}
    for t, trie in src.items():
        nt = SubscriptionTrie()
        for r in trie.routes():
            nt.add(r)
        out[t] = nt
    return out


def canon(m):
    return (sorted((r.matcher.mqtt_topic_filter, r.receiver_url)
                   for r in m.normal),
            {f: sorted(r.receiver_url for r in ms)
             for f, ms in m.groups.items()})


def assert_parity(matcher, queries, ctx):
    got = matcher.match_batch(queries)
    want = matcher.match_from_tries(queries)
    for g, w, q in zip(got, want, queries):
        assert canon(g) == canon(w), f"parity broke ({ctx}): {q[1]}"
    return got


# ---- 1+2: repeated-topic workload -> hit rate + oracle parity ------------
pool = workloads.probe_topics(HOT_TOPICS, seed=1)
cum, acc = [], 0.0
for i in range(HOT_TOPICS):
    acc += 1.0 / (i + 1)
    cum.append(acc)
m_on = TpuMatcher.from_tries(clone_tries(tries), match_cache=True,
                             auto_compact=False)
for step in range(24):
    batch = [("tenant0", pool[j]) for j in rng.choices(
        range(HOT_TOPICS), cum_weights=cum, k=BATCH)]
    assert_parity(m_on, batch, f"repeated step {step}")
    if step % 6 == 5:
        # interleave mutations: exact and wildcard filters both — stale
        # results surviving these is exactly what the gate exists to catch
        tf = rng.choice(["gate/exact/t", "gate/+/wild", "gate/#"])
        route = Route(matcher=RouteMatcher.from_topic_filter(tf),
                      broker_id=0, receiver_id=f"gr{step}",
                      deliverer_key="d0", incarnation=step)
        m_on.add_route("tenant0", route)
stats = m_on.match_cache.snapshot()
print(f"repeated-topic cache stats: {stats}")
assert stats["hit_rate"] > 0.8, \
    f"hit rate {stats['hit_rate']} <= 0.8 on a repeated-topic workload"

# ---- 3: unique-topic workload must not regress ---------------------------
# de-duplicated (probe_topics repeats Zipf draws): duplicates would let
# in-batch dedup subsidize the cache-on leg and mask probe/put overhead
seen, uniq, gen = set(), [], 2
while len(uniq) < BATCH * 8:
    for t in workloads.probe_topics(BATCH * 8, seed=gen):
        k = tuple(t)
        if k not in seen:
            seen.add(k)
            uniq.append(t)
    gen += 1
sets = [[("tenant0", t) for t in uniq[i * BATCH:(i + 1) * BATCH]]
        for i in range(8)]


def timed(matcher):
    for s in sets:     # warm every shape this workload will use
        matcher.match_batch(s)
    best = float("inf")
    for _ in range(3):  # best-of-3: shared CI boxes are noisy
        t0 = time.perf_counter()
        for s in sets:
            if matcher.match_cache is not None:
                matcher.match_cache.clear()   # keep every pass a miss pass
            matcher.match_batch(s)
        best = min(best, time.perf_counter() - t0)
    return best


# fresh matchers over the PRISTINE tries for a fair A/B (the mutation
# phase above ran on its own clone, so neither leg carries gate routes)
m_off = TpuMatcher.from_tries(tries, match_cache=False, auto_compact=False)
t_off = timed(m_off)
m_on2 = TpuMatcher.from_tries(tries, match_cache=True, auto_compact=False)
t_on = timed(m_on2)
print(f"unique-topic: cache-off {t_off:.3f}s, cache-on {t_on:.3f}s "
      f"({t_on / t_off:.2f}x)")
assert t_on <= 1.5 * t_off, \
    f"miss path regressed: cache-on {t_on:.3f}s vs off {t_off:.3f}s"

# parity on the unique workload too (the miss/put path end to end)
assert_parity(m_on2, sets[0], "unique")
print("cache_check PASSED")
EOF
rc=$?
if [ $rc -eq 124 ] || [ $rc -eq 137 ]; then
    echo "cache check TIMED OUT (rc=$rc)" >&2
fi
exit $rc
