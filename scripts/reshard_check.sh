#!/usr/bin/env bash
# Tier-2 elastic-mesh gate (ISSUE 17): live tenant migration + online
# rebalancing on a Zipf-skewed 8-way HOST mesh
# (XLA_FLAGS=--xla_force_host_platform_device_count=8), asserting:
#   1. the skew-driven rebalancer PLANS a move off the hot shard (load
#      model skew > threshold, capacity-planner veto consulted),
#   2. the live migration ladder (begin -> copy* -> ready -> cutover ->
#      tombstone) runs with ZERO full rebuilds and ZERO match-cache
#      generation bumps, with exact host-oracle row parity after EVERY
#      copy chunk and through the dual-serve window — including
#      mutations folded in mid-migration,
#   3. post-move shard skew strictly improves,
#   4. the ABORT ladder: a hang injected on the migration's TARGET
#      shard opens that shard's breaker mid-copy and the next step()
#      aborts cleanly — source-only serving restored, partial target
#      rows tombstoned, exact parity, migration retryable.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${RESHARD_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BIFROMQ_DEVICE_DEADLINE_S=0.3 \
    BIFROMQ_SHARD_DEADLINE_S=0.3 \
    python - <<'EOF'
import asyncio, os, random

import numpy as np

from bifromq_tpu.models.oracle import Route
from bifromq_tpu.obs import OBS
from bifromq_tpu.parallel.reshard import (MeshRebalancer, MigrationAborted,
                                          ShardLoadModel)
from bifromq_tpu.parallel.sharded import MeshMatcher, make_mesh
from bifromq_tpu.resilience.faults import get_injector
from bifromq_tpu.types import RouteMatcher

N_SHARDS = 8
N_TENANTS = int(os.environ.get("RESHARD_CHECK_TENANTS", "32"))
WHALE_ROUTES = int(os.environ.get("RESHARD_CHECK_WHALE_ROUTES", "400"))


def mk(tf, rid):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=rid, deliverer_key="d0", incarnation=0)


def canon(r):
    return (sorted((x.matcher.mqtt_topic_filter, x.receiver_url)
                   for x in r.normal),
            {f: sorted(x.receiver_url for x in ms)
             for f, ms in r.groups.items()})


def assert_parity(m, probe, label):
    got = m.match_batch(probe)
    want = m.match_from_tries(probe)
    bad = sum(1 for a, b in zip(got, want) if canon(a) != canon(b))
    assert bad == 0, f"{label}: {bad}/{len(probe)} rows mismatch the oracle"


# ---- Zipf-skewed population: tenant i gets ~N/(i+1) routes -------------
mesh = make_mesh(1, N_SHARDS)
m = MeshMatcher(mesh=mesh, max_levels=8, k_states=16,
                auto_compact=False, match_cache=True)
tenants = [f"zt{i}" for i in range(N_TENANTS)]
whale = tenants[0]
total = 0
for i, t in enumerate(tenants):
    n = max(2, WHALE_ROUTES // (i + 1))
    for j in range(n):
        m.add_route(t, mk(f"z/{t}/{j}/+", f"r{i}_{j}"))
        total += 1
m.refresh()
m.query_heat[whale] = 65536          # the whale owns the heat too
probe = [(tenants[i % N_TENANTS], f"z/{tenants[i % N_TENANTS]}/{i}/x")
         for i in range(128)]
print(f"zipf mesh: {total} routes over {N_TENANTS} tenants / "
      f"{N_SHARDS} shards, whale={whale} "
      f"({max(2, WHALE_ROUTES)} routes + all heat)")
assert_parity(m, probe, "pre-move")

# ---- 1. the rebalancer must plan the whale off its hot shard -----------
model = ShardLoadModel()
skew0 = model.skew(model.rows(m))
reb = MeshRebalancer(m, max_skew=1.2, min_heat=64)
decision = reb.plan()
assert decision is not None, f"no plan at skew {skew0:.2f}"
assert decision["tenant"] == whale, decision
src, dst = decision["src"], decision["dst"]
assert src == m._base_ct.shard_of(whale) and dst != src
print(f"plan: skew {skew0:.2f} -> move {whale} shard{src} -> shard{dst}")

# ---- 2. step-wise live migration: parity after EVERY chunk -------------
ledger = OBS.profiler.ledger
rebuilds0, gen0, bumps0 = (m.compile_count, m.match_cache._gen,
                           ledger.generation_bumps)
mig = m.migrate_tenant(whale, src, dst, run=False)
rng = random.Random(17)
chunks = 0
while mig.state == "copying":
    done = mig.step(64)
    chunks += 1
    # mutations mid-migration: dual-fold into BOTH arenas
    t = rng.choice([whale, rng.choice(tenants)])
    m.add_route(t, mk(f"mid/{chunks}/+", f"mid{chunks}"))
    assert_parity(m, probe[:48] + [(t, f"mid/{chunks}/q")],
                  f"copy chunk {chunks}")
    if done:
        break
assert mig.state == "ready", mig.state
assert m._base_ct.shards_of(whale) == [src, dst]
m.add_route(whale, mk("dual/serve/+", "dualrcv"))
assert_parity(m, probe + [(whale, "dual/serve/q")], "dual-serve window")
mig.cutover()
assert m._base_ct.shards_of(whale) == [dst]
assert_parity(m, probe, "post-cutover")
assert mig.finish(), "ring busy at tombstone time"
assert_parity(m, probe, "post-tombstone")
assert m.compile_count == rebuilds0, "full rebuild during live migration"
assert m.match_cache._gen == gen0, "match-cache generation bump"
assert ledger.generation_bumps == bumps0, "ledger generation bump"
print(f"migrate: {mig.copied_n} routes in {chunks} chunks, rebuilds=0 "
      f"gen-bumps=0, parity exact every chunk "
      f"(fallbacks={m.patch_fallbacks})")

# ---- 3. the move must IMPROVE skew -------------------------------------
skew1 = model.skew(model.rows(m))
assert skew1 < skew0, f"skew {skew0:.2f} -> {skew1:.2f} did not improve"
print(f"skew: {skew0:.2f} -> {skew1:.2f}")

# ---- 4. abort ladder: hang the TARGET shard mid-copy -------------------
victim = tenants[1]
src2 = m._base_ct.shard_of(victim)
dst2 = next(s for s in range(N_SHARDS) if s != src2)
mig2 = m.migrate_tenant(victim, src2, dst2, run=False)
assert not mig2.step(8), "victim copy must span several chunks"
inj = get_injector()
rule = inj.add_rule(service="tpu-device", method=f"mesh:shard{dst2}",
                    action="hang", side="device")


async def trip_target():
    for k in range(4):           # trip threshold (3) + one open serve
        # unique topics per round: the match cache must MISS so every
        # round actually dispatches to the hung target shard
        qs = [(t, f"trip/{k}/{t}") for t in tenants] * 2
        got = await m.match_batch_async(qs)
        want = m.match_from_tries(qs)
        assert all(canon(a) == canon(b) for a, b in zip(got, want)), \
            "rows must stay exact through the hang (oracle degradation)"

asyncio.run(trip_target())
assert m.shard_breakers[dst2].state == "open", \
    [br.state for br in m.shard_breakers]
try:
    mig2.step(8)
    raise SystemExit("step() must abort on an open target breaker")
except MigrationAborted as e:
    print(f"abort: {e}")
assert mig2.state == "aborted"
assert not (m._base_ct.migrating or {}), "migration state must clear"
assert m._base_ct.shards_of(victim) == [src2], "source-only serving"
inj.remove_rule(rule)
assert_parity(m, probe, "post-abort")
print("RESHARD CHECK PASSED")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "RESHARD CHECK FAILED (rc=$rc)"
    exit $rc
fi
