#!/usr/bin/env python
"""Full-scale device run (VERDICT r4 #3): upload the tables saved by
scale_probe.py and measure the walk on the real chip.

Run ONLY when the tunnel is up (probe first). Reads
/tmp/scale_tables_<cfg>.npz, uploads each table with its own timing (the
axon tunnel uploads slowly — the record keeps upload separate from
compute), then measures the config's OWN serving kernel — the match-plane
interval walk for c5/c2_10m, the roles-swapped retained filter walk for
c4 — appending to bench_results/r5_fullscale.json.

Usage: python scripts/scale_device_run.py c5 [batch] [iters]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bifromq_tpu.utils.jaxenv import pin_jax_platform  # noqa: E402


def _load_tables(cfg):
    from bifromq_tpu.models.automaton import CompiledTrie
    z = np.load(f"/tmp/scale_tables_{cfg}.npz")
    ct = CompiledTrie(node_tab=z["node_tab"], edge_tab=z["edge_tab"],
                      child_list=z["child_list"], matchings=[],
                      tenant_root={}, salt=int(z["salt"]),
                      probe_len=int(z["probe_len"]),
                      max_levels=int(z["max_levels"]))
    roots_path = f"/tmp/scale_roots_{cfg}.json"
    if os.path.exists(roots_path):
        with open(roots_path) as f:
            ct.tenant_root = json.load(f)
    elif cfg == "c5":
        # a multi-tenant table probed at root 0 would silently measure a
        # single tenant's subtree — wrong-but-plausible numbers
        raise SystemExit(f"{roots_path} missing: re-run scale_probe.py c5")
    return ct


def _upload(ct, rec, *, need_route_tabs=True):
    from bifromq_tpu.ops.match import DeviceTrie
    import jax
    t0 = time.time()
    if need_route_tabs:
        dev = DeviceTrie.from_compiled(ct)
        names = ("node_tab", "edge_tab", "child_list", "count_tab",
                 "route_tab")
    else:
        # retained walk reads only the base tables — don't push the
        # derived count/route tables through the ~1MB/s tunnel
        dev = DeviceTrie(node_tab=jax.device_put(ct.node_tab),
                         edge_tab=jax.device_put(ct.edge_tab),
                         child_list=jax.device_put(ct.child_list))
        names = ("node_tab", "edge_tab", "child_list")
    for name in names:
        a = getattr(dev, name)
        np.asarray(a[:1])  # force the transfer (block_until_ready no-ops)
        print(f"uploaded {name}: {a.nbytes/1e6:.0f}MB "
              f"(cum {time.time()-t0:.0f}s)", flush=True)
    rec["upload_s"] = round(time.time() - t0, 1)
    return dev


def _pipelined(run, probe_sets, sync, batch, iters):
    """Fire-and-forget dispatch, one sync at the end; returns topics/s."""
    s = time.perf_counter()
    for it in range(iters - 1):
        run(probe_sets[it % len(probe_sets)])
    sync(run(probe_sets[(iters - 1) % len(probe_sets)]))
    return batch * iters / (time.perf_counter() - s)


def run_match(cfg, ct, dev, rec, batch, iters, k_states):
    """c5 / c2_10m: PUBLISH topics through the match-plane walks."""
    from bifromq_tpu.models.automaton import tokenize
    from bifromq_tpu.ops.match import (Probes, expand_intervals,
                                       walk_count_only, walk_routes)
    from bifromq_tpu import workloads

    n_batches = 4
    topics = workloads.probe_topics(batch * n_batches, seed=1)
    if cfg == "c5":
        import random
        rng = random.Random(3)
        tenants = sorted(ct.tenant_root)
        cum, acc = [], 0.0
        for i in range(len(tenants)):
            acc += 1.0 / (i + 1)
            cum.append(acc)
        tenant_seq = rng.choices(tenants, cum_weights=cum,
                                 k=batch * n_batches)
        roots = [ct.tenant_root[t] for t in tenant_seq]
    else:
        roots = [ct.tenant_root.get("tenant0", 0)] * (batch * n_batches)
    t0 = time.time()
    toks = [tokenize(topics[i * batch:(i + 1) * batch],
                     roots[i * batch:(i + 1) * batch],
                     max_levels=ct.max_levels, salt=ct.salt, batch=batch)
            for i in range(n_batches)]
    rec["tokenize_topics_per_s"] = round(
        batch * n_batches / (time.time() - t0), 1)
    probe_sets = [Probes.from_tokenized(t) for t in toks]
    for p in probe_sets:
        for a in (p.tok_h1, p.tok_h2, p.lengths, p.roots, p.sys_mask):
            np.asarray(a[:1])

    # ---- count walk: warmup collects counts+overflow in the SAME pass
    run_c = lambda p: walk_count_only(dev, p, probe_len=ct.probe_len,
                                      k_states=k_states)
    t0 = time.time()
    outs = [run_c(p) for p in probe_sets]
    total_cnt = sum(float(np.asarray(c, dtype=np.float64).sum())
                    for c, _ in outs)
    total_ovf = sum(int(np.asarray(o).sum()) for _, o in outs)
    rec["count_jit_s"] = round(time.time() - t0, 1)
    rec["overflow_frac"] = round(total_ovf / (batch * n_batches), 5)
    rec["routes_per_topic"] = round(total_cnt / (batch * n_batches), 2)
    rec["count_topics_per_s"] = round(_pipelined(
        run_c, probe_sets, lambda r: np.asarray(r[0]), batch, iters), 1)

    # ---- routes walk: pipelined with readback + expand per iter ----------
    run_r = lambda p: walk_routes(dev, p, probe_len=ct.probe_len,
                                  k_states=k_states, max_intervals=64)

    def process(r):
        slots, _ = expand_intervals(np.asarray(r.start),
                                    np.asarray(r.count))
        return slots.size

    t0 = time.time()
    for p in probe_sets:
        process(run_r(p))
    rec["routes_jit_s"] = round(time.time() - t0, 1)
    s = time.perf_counter()
    prev = None
    total_routes = 0
    for it in range(iters):
        h = run_r(probe_sets[it % n_batches])
        if prev is not None:
            total_routes += process(prev)
        prev = h
    total_routes += process(prev)
    el = time.perf_counter() - s
    rec["routes_topics_per_s"] = round(batch * iters / el, 1)
    rec["routes_matched_per_s"] = round(total_routes / el, 1)

    lat = []
    for it in range(8):
        s = time.perf_counter()
        process(run_r(probe_sets[it % n_batches]))
        lat.append(time.perf_counter() - s)
    rec["routes_p50_ms"] = round(float(np.percentile(lat, 50)) * 1e3, 2)
    rec["routes_p99_ms"] = round(float(np.percentile(lat, 99)) * 1e3, 2)


def run_retained(ct, dev, rec, batch, iters, k_states):
    """c4: wildcard FILTERS through the roles-swapped retained walk."""
    from bifromq_tpu.models.automaton import tokenize_filters
    from bifromq_tpu.ops.retained import FilterProbes, retained_walk
    from bifromq_tpu import workloads

    n_batches = 4
    filters = workloads.probe_filters(batch * n_batches, seed=2)
    root = ct.tenant_root.get("tenant0", 0)
    t0 = time.time()
    toks = [tokenize_filters(filters[i * batch:(i + 1) * batch],
                             [root] * batch, max_levels=ct.max_levels,
                             salt=ct.salt, batch=batch)
            for i in range(n_batches)]
    rec["tokenize_filters_per_s"] = round(
        batch * n_batches / (time.time() - t0), 1)
    probe_sets = [FilterProbes.from_tokenized(t) for t in toks]
    for p in probe_sets:
        for a in (p.tok_h1, p.tok_h2, p.tok_kind, p.lengths, p.roots):
            np.asarray(a[:1])

    run = lambda p: retained_walk(dev, p, probe_len=ct.probe_len,
                                  k_states=k_states)
    t0 = time.time()
    outs = [run(p) for p in probe_sets]
    total_matched = sum(
        float(np.maximum(np.asarray(r)[..., 1], 0).sum())
        for r, _ in outs)
    total_ovf = sum(int(np.asarray(o).sum()) for _, o in outs)
    rec["jit_s"] = round(time.time() - t0, 1)
    rec["overflow_frac"] = round(total_ovf / (batch * n_batches), 5)
    rec["matched_per_filter"] = round(total_matched / (batch * n_batches), 2)
    rec["filters_per_s"] = round(_pipelined(
        run, probe_sets, lambda r: np.asarray(r[0]), batch, iters), 1)
    rec["matched_retained_per_s"] = round(
        rec["filters_per_s"] * rec["matched_per_filter"], 1)

    lat = []
    for it in range(8):
        s = time.perf_counter()
        np.asarray(run(probe_sets[it % n_batches])[0])
        lat.append(time.perf_counter() - s)
    rec["p50_ms"] = round(float(np.percentile(lat, 50)) * 1e3, 2)
    rec["p99_ms"] = round(float(np.percentile(lat, 99)) * 1e3, 2)


def main():
    cfg = sys.argv[1] if len(sys.argv) > 1 else "c5"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 16384
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    k_states = int(os.environ.get("SCALE_K", "16"))

    pin_jax_platform()
    import jax
    print("devices:", jax.devices(), flush=True)

    ct = _load_tables(cfg)
    rec = {"config": cfg, "batch": batch, "iters": iters,
           "k_states": k_states, "n_nodes": int(ct.n_nodes)}
    dev = _upload(ct, rec, need_route_tabs=(cfg != "c4"))
    if cfg == "c4":
        run_retained(ct, dev, rec, batch, iters, k_states)
    else:
        run_match(cfg, ct, dev, rec, batch, iters, k_states)
    rec["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    rec["platform"] = jax.devices()[0].platform

    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_results", "r5_fullscale.json")
    results = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            results = json.load(f)
    results[f"{cfg}_B{batch}_K{k_states}"] = rec
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
