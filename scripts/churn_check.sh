#!/usr/bin/env bash
# Tier-2 subscription-churn gate (ISSUE 9): sustained subscribe /
# unsubscribe against a live base on CPU-scaled inputs, asserting the
# incremental-patch contract:
#   1. ZERO full rebuilds inside the churn window (steady churn below the
#      tombstone threshold must never trigger the old every-2048-mutations
#      recompile),
#   2. ZERO match-cache generation bumps (patches and same-salt
#      compactions keep every cached result valid),
#   3. single-mutation patch apply (host plan + narrow device update) p99
#      under a CPU-scaled bound AND >=100x faster than this base's own
#      full-rebuild cost,
#   4. exact host-oracle row parity after the storm — including the
#      tombstone-walk paths ('#'/'+'/$share filters churned and removed).
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${CHURN_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import os, random, time

import numpy as np

from bifromq_tpu import workloads
from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.obs import OBS
from bifromq_tpu.types import RouteMatcher

N_SUBS = int(os.environ.get("CHURN_CHECK_SUBS", "20000"))
N_OPS = int(os.environ.get("CHURN_CHECK_OPS", "400"))
P99_MS_MAX = float(os.environ.get("CHURN_CHECK_P99_MS", "250"))
SPEEDUP_MIN = float(os.environ.get("CHURN_CHECK_SPEEDUP", "100"))


def mk(tf, rid, inc=0, broker=0):
    return Route(matcher=RouteMatcher.from_topic_filter(tf),
                 broker_id=broker, receiver_id=rid, deliverer_key="d0",
                 incarnation=inc)


def canon(m):
    return (sorted((r.matcher.mqtt_topic_filter, r.receiver_url)
                   for r in m.normal),
            {f: sorted(r.receiver_url for r in ms)
             for f, ms in m.groups.items()})


tries = workloads.config_wildcard(N_SUBS, seed=0)
m = TpuMatcher.from_tries(tries, match_cache=True)
rebuild_s = m._last_compile_s
assert hasattr(m._base_ct, "patch_stats"), \
    "base is not patchable — BIFROMQ_PATCH off?"
gen0 = m.match_cache._gen
compiles0 = m.compile_count
bumps0 = OBS.profiler.ledger.generation_bumps

topics = workloads.probe_topics(1024, seed=1)
batches = [[("tenant0", t) for t in topics[i * 64:(i + 1) * 64]]
           for i in range(8)]
m.match_batch(batches[0])                         # warm walk shapes
m.add_route("tenant0", mk("churn/warm/+", "w"))   # warm the scatter jit
m._flush_patches()

# ---- the storm: mixed adds/removes across wildcard + shared filters ----
rng = random.Random(7)
kinds = ["churn/{i}/+", "churn/{i}/#", "churn/lit/{i}", "$share/g{g}/churn/{i}/+"]
live = []
lat = []
for i in range(N_OPS):
    tf = rng.choice(kinds).format(i=i % 64, g=i % 4)
    rid = f"r{rng.randrange(96)}"
    s0 = time.perf_counter()
    if rng.random() < 0.6 or not live:
        m.add_route("tenant0", mk(tf, rid, inc=i))
        live.append((tf, rid))
    else:
        tf2, rid2 = live.pop(rng.randrange(len(live)))
        m.remove_route("tenant0", RouteMatcher.from_topic_filter(tf2),
                       (0, rid2, "d0"), incarnation=i)
    m._flush_patches()
    lat.append(time.perf_counter() - s0)
    if i % 16 == 0:
        got = m.match_batch(batches[(i // 16) % 8])
        want = m.match_from_tries(batches[(i // 16) % 8])
        assert all(canon(a) == canon(b) for a, b in zip(got, want)), \
            f"mid-storm parity broke at op {i}"
m.drain()

# ---- 1. zero full rebuilds in the window -------------------------------
rebuilds = m.compile_count - compiles0
assert rebuilds == 0, f"{rebuilds} full rebuild(s) during steady churn"

# ---- 2. zero generation bumps ------------------------------------------
assert m.match_cache._gen == gen0, "match-cache generation bumped"
assert OBS.profiler.ledger.generation_bumps == bumps0

# ---- 3. patch-apply p99 bound + speedup vs the full rebuild ------------
p99 = float(np.percentile(lat, 99))
assert p99 * 1e3 < P99_MS_MAX, \
    f"patch apply p99 {p99*1e3:.1f}ms >= {P99_MS_MAX}ms"
speedup = rebuild_s / max(1e-9, p99)
assert speedup >= SPEEDUP_MIN, \
    f"patch apply only {speedup:.0f}x faster than the {rebuild_s:.2f}s rebuild"

# ---- 4. exact oracle parity after the storm ----------------------------
probe = [("tenant0", t) for t in topics[:256]]
probe += [("tenant0", ["churn", str(i), "leaf"]) for i in range(64)]
probe += [("tenant0", ["churn", "lit", str(i)]) for i in range(64)]
got = m.match_batch(probe)
want = m.match_from_tries(probe)
bad = sum(1 for a, b in zip(got, want) if canon(a) != canon(b))
assert bad == 0, f"{bad}/{len(probe)} rows diverge from the oracle"

st = m._base_ct.patch_stats()
print(f"churn gate OK: {N_OPS} ops, rebuilds=0, generation bumps=0, "
      f"patch p99 {p99*1e3:.2f}ms ({speedup:.0f}x vs {rebuild_s:.2f}s "
      f"rebuild), parity {len(probe)}/{len(probe)}, "
      f"frag={st['frag_ratio']} dead={st['dead_slots']} "
      f"relocations={st['relocations']}")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "churn_check: FAILED (rc=$rc)" >&2
    exit $rc
fi
echo "churn_check: OK"
