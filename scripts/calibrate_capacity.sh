#!/usr/bin/env bash
# One-liner for the operational planner re-fit (ISSUE 11 satellite,
# ROADMAP sharding follow-up (c)): GET /capacity?calibrate=1 re-fits the
# CapacityPlanner's per-subscription coefficients from the live base
# (true logical sub count, not the slot-count proxy) and reports
# old-vs-new coefficient deltas + the predicted-bytes shift.
#
# Usage: calibrate_capacity.sh [base_url] [n_subs]
#   base_url  API server (default http://127.0.0.1:8080)
#   n_subs    target population for the predicted-bytes delta
#             (default 1000000)
set -euo pipefail

BASE="${1:-http://127.0.0.1:8080}"
N_SUBS="${2:-1000000}"

curl -fsS "${BASE}/capacity?calibrate=1&n_subs=${N_SUBS}" \
    | python -m json.tool
