#!/bin/bash
# Tunnel-up measurement session (round 5), ordered by value so an early
# tunnel flap still leaves the headline numbers on disk:
#   1. c2@1M honest e2e headline (+ latency frontier + B/K sweep cells)
#   2. all five BASELINE configs (count + routes modes)
#   3. full-scale c4 (2.0GB upload), then c5 (4.4GB upload) — the long
#      uploads go LAST; a flap mid-upload loses only the full-scale runs.
# Each step appends to its own log; the script never aborts on failure.
cd /root/repo || exit 1
mkdir -p bench_results/r5_logs
L=bench_results/r5_logs
export BENCH_DEVICE_WAIT=180 BENCH_DEVICE_TIMEOUT=90

echo "=== step 1: c2 headline + latency $(date +%T)" | tee -a $L/session.log
BENCH_CONFIGS=2 BENCH_LATENCY=1 timeout 2400 python bench.py \
  > $L/c2_headline.json 2> $L/c2_headline.log
echo "step 1 rc=$? $(date +%T)" | tee -a $L/session.log

echo "=== step 2: all configs $(date +%T)" | tee -a $L/session.log
timeout 4800 python bench.py > $L/full.json 2> $L/full.log
echo "step 2 rc=$? $(date +%T)" | tee -a $L/session.log

echo "=== step 3: c4 full-scale $(date +%T)" | tee -a $L/session.log
timeout 5400 python scripts/scale_device_run.py c4 16384 20 \
  > $L/c4_fullscale.log 2>&1
echo "step 3 rc=$? $(date +%T)" | tee -a $L/session.log

echo "=== step 4: c5 full-scale $(date +%T)" | tee -a $L/session.log
timeout 9000 python scripts/scale_device_run.py c5 16384 20 \
  > $L/c5_fullscale.log 2>&1
echo "step 4 rc=$? $(date +%T)" | tee -a $L/session.log
echo "=== session done $(date +%T)" | tee -a $L/session.log
