#!/usr/bin/env bash
# Tier-2 gate (ISSUE 10): graftcheck static analysis + sanitizers.
#
# Fails when:
#   - the analyzer reports ANY unsuppressed finding on the package
#   - a suppression entry matches no live site (dead suppressions rot)
#   - the checked-in stamp.json hash disagrees with a fresh run
#     (someone changed findings/suppressions without --write-stamp)
#   - a rule fixture stops firing, or the transfer-guard harness fails
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

echo "== graftcheck: zero unsuppressed findings, no dead suppressions =="
python -m bifromq_tpu.analysis

echo "== stamp freshness (GET /metrics build-info serves this) =="
fresh=$(python -m bifromq_tpu.analysis --json \
        | python -c "import json,sys; print(json.load(sys.stdin)['hash'])")
stamped=$(python -c "import json; \
print(json.load(open('bifromq_tpu/analysis/stamp.json'))['hash'])")
if [ "$fresh" != "$stamped" ]; then
    echo "FAIL: stamp hash drift (fresh=$fresh stamped=$stamped)" >&2
    echo "      rerun: python -m bifromq_tpu.analysis --write-stamp" >&2
    exit 1
fi
echo "stamp hash $stamped matches fresh run"

echo "== rule fixtures fire + transfer-guard harness =="
python -m pytest tests/test_analysis.py tests/test_sanitize.py -q \
    -p no:cacheprovider

echo "analysis_check PASS"
