#!/usr/bin/env bash
# Tier-2 delta-plane observability gate (ISSUE 18): the lag plane, the
# continuous parity auditor, and the unattended autoscaler, end to end
# on a live leader + standby over the real delta stream. Asserts:
#   1. LAG VISIBILITY — a churn storm applied with an artificially aged
#      HLC makes per-stream apply lag visible (stream flagged stale),
#      and draining back to live-stamped records returns lag to ~0 and
#      clears the flag only after the full hysteresis window,
#   2. PARITY AUDIT — an injected single-byte arena corruption on the
#      standby is caught within ONE audit interval and healed by
#      EXACTLY one bounded resync: zero full rebuilds, zero match-cache
#      generation bumps,
#   3. AUTOSCALER — sustained synthetic pressure on a real 4-shard mesh
#      grows it unattended (K consecutive ticks), the quiet window
#      shrinks it back, and no second action lands inside the cooldown.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${LAG_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'EOF'
import asyncio, os, random

from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.obs.audit import ParityAuditor, fingerprint_scope
from bifromq_tpu.obs.lag import LAG, REPL_EVENTS
from bifromq_tpu.replication import records as R
from bifromq_tpu.replication.standby import WarmStandby
from bifromq_tpu.replication.stream import DeltaLog
from bifromq_tpu.types import RouteMatcher
from bifromq_tpu.utils.hlc import HLC

N_OPS = int(os.environ.get("LAG_CHECK_OPS", "300"))
os.environ.setdefault("BIFROMQ_REPL_LAG_STALE_S", "2.0")


def rt(tf, i):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=f"rcv{i}", deliverer_key=f"d{i}",
                 incarnation=0)


def make_leader(n=60):
    leader = TpuMatcher(auto_compact=False)
    log = DeltaLog("n0", "r0")
    leader.on_delta = lambda t, f, op, plan, fb: log.append(
        tenant=t, filter_levels=f, op=op, plan=plan, fallback=fb)
    leader.on_rebase = lambda salt, reason: log.anchor(salt, reason)
    for i in range(n):
        leader.add_route("T", rt(f"s/{i}/t", i))
    leader.refresh()
    return leader, log


def wire(recs):
    return [R.decode_record(r.encoded())[0] for r in recs]


async def main():
    random.seed(11)

    # ---- 1. lag visibility under a churn storm --------------------------
    leader, log = make_leader()

    async def fetch(_rid, epoch, seq, _timeout):
        status, recs = log.since(epoch, seq)
        return status, wire(recs), log.cursor()

    async def base(_rid):
        return "n0", log.cursor(), R.decode_base(
            R.encode_base(leader._base_ct, leader.tries))

    sb = WarmStandby(matcher=TpuMatcher(auto_compact=False),
                     range_id="r0", fetch_fn=fetch, base_fn=base)
    await sb.sync_once()
    assert sb.attached and sb.resyncs == 1, "initial resync"

    # churn storm whose records the standby applies LATE: age every
    # record's HLC stamp by rewriting it 5 s into the past
    AGE_MS = 5000
    for i in range(N_OPS):
        leader.add_route("T", rt(f"storm/{i}/t", 1000 + i))
    status, recs = log.since(*sb.cursor)
    assert status == "ok"
    aged = []
    for rec in wire(recs):
        rec.hlc = HLC.INST.get() - (AGE_MS << 16)
        aged.append(rec)
    assert sb.offer(aged)
    snap = LAG.snapshot()
    (stream,) = [s for s in snap["streams"] if s["range"] == "r0"]
    assert stream["lag_s"] > 2.0, f"storm lag visible: {stream}"
    assert stream["stale"] and sb.stale(), "stream flagged stale"
    assert stream["applied_window"] >= N_OPS
    print(f"[lag_check] 1. churn storm: lag={stream['lag_s']:.2f}s "
          f"stale={stream['stale']} applied={stream['applied_window']}")

    # stale: promote refuses, force overrides (without promoting here)
    try:
        sb.promote()
        raise SystemExit("stale promote must refuse without force")
    except RuntimeError:
        pass

    # drain back to live-stamped records → lag ~0, flag clears after
    # the full hysteresis window (fresh applies spaced past it)
    import time as _time
    deadline = _time.monotonic() + 30.0
    while sb.stale() and _time.monotonic() < deadline:
        leader.add_route("T", rt(f"live/{random.random()}", 2000))
        status, recs = log.since(*sb.cursor)
        assert sb.offer(wire(recs))
        await asyncio.sleep(0.25)
    (stream,) = [s for s in LAG.snapshot()["streams"]
                 if s["range"] == "r0"]
    assert not stream["stale"], "flag cleared after quiet window"
    assert stream["lag_s"] < 1.0, f"lag drained: {stream['lag_s']}"
    assert sb.promote() is sb.matcher, "fresh standby promotes"
    print(f"[lag_check] 1. drained: lag={stream['lag_s']:.3f}s "
          f"stale={stream['stale']}")

    # ---- 2. injected corruption → one audit interval → one resync -------
    leader, log = make_leader()

    async def fetch2(_rid, epoch, seq, _timeout):
        status, recs = log.since(epoch, seq)
        return status, wire(recs), log.cursor()

    async def base2(_rid):
        return "n0", log.cursor(), R.decode_base(
            R.encode_base(leader._base_ct, leader.tries))

    sb2 = WarmStandby(matcher=TpuMatcher(auto_compact=False),
                      range_id="r0", fetch_fn=fetch2, base_fn=base2)
    await sb2.sync_once()
    compile0 = sb2.matcher.compile_count
    gen0 = sb2.matcher.match_cache._gen
    auditor = ParityAuditor(leader)

    sb2.matcher._base_ct.node_tab[0, 0] ^= 1       # ONE corrupted byte
    auditor.audit_once()                           # next audit interval
    await sb2.sync_once()
    assert sb2.parity_divergences == 1 and not sb2.attached, \
        "caught within one audit interval"
    await sb2.sync_once()                          # heals
    assert sb2.attached and sb2.resyncs == 2, "exactly one resync"
    auditor.audit_once()
    await sb2.sync_once()
    assert sb2.parity_divergences == 1 and sb2.resyncs == 2, \
        "no resync storm"
    assert sb2.matcher.compile_count == compile0, "zero rebuilds"
    assert sb2.matcher.match_cache._gen == gen0, "zero generation bumps"
    assert fingerprint_scope(sb2.matcher, "route") \
        == fingerprint_scope(leader, "route"), "arenas re-converged"
    n_div = sum(1 for r in REPL_EVENTS.tail(10_000)
                if r["kind"] == "parity_divergence")
    assert n_div == 1, f"one divergence event, got {n_div}"
    print(f"[lag_check] 2. corruption caught+healed: divergences="
          f"{sb2.parity_divergences} resyncs={sb2.resyncs} "
          f"compiles={sb2.matcher.compile_count - compile0}")

    # ---- 3. autoscaler: grow unattended, shrink after quiet -------------
    os.environ["BIFROMQ_MESH_AUTOSCALE_K"] = "3"
    os.environ["BIFROMQ_MESH_AUTOSCALE_QUIET_S"] = "10"
    os.environ["BIFROMQ_MESH_AUTOSCALE_COOLDOWN_S"] = "5"
    from bifromq_tpu.parallel.autoscale import MeshAutoscaler
    from bifromq_tpu.parallel.sharded import MeshMatcher, make_mesh

    m = MeshMatcher(mesh=make_mesh(1, 4), max_levels=8, k_states=16,
                    auto_compact=False, match_cache=False)
    for i in range(24):
        m.add_route(f"t{i % 6}", rt(f"s/{i}/t", i))
    m.refresh()
    n0 = m._base_ct.n_shards
    t = [0.0]
    state = {"pressure": 0.99}

    def signals():
        return {"skew": 1.0, "pressure": state["pressure"],
                "n_shards": m._base_ct.n_shards,
                "migrating": len(m._base_ct.migrating or {}),
                "stale_streams": 0, "worst_lag_s": 0.0}

    class NoMove:
        def plan(self): return None
        def step(self): raise AssertionError("unreachable")

    a = MeshAutoscaler(m, rebalancer=NoMove(), signals_fn=signals,
                       clock=lambda: t[0])
    for _ in range(3):
        a.tick()
        t[0] += 0.5
    assert m._base_ct.n_shards == n0 + 1, "grew unattended after K ticks"
    grew_at = a.actions
    assert grew_at == 1
    # sustained pressure INSIDE the cooldown: re-arms but never acts
    for _ in range(6):
        a.tick()
        t[0] += 0.5
    assert a.actions == 1 and m._base_ct.n_shards == n0 + 1, \
        "no flapping inside cooldown"
    # pressure subsides → quiet window → unattended shrink
    state["pressure"] = 0.0
    t[0] += 6.0
    a.tick()                                      # quiet window opens
    t[0] += 11.0
    d = a.tick()
    assert d["acted"] and d["action"] == "shrink", d
    assert m._base_ct.n_shards == n0, "shrank back after quiet window"
    assert all("signals" in x for x in a.decisions), "provenance"
    print(f"[lag_check] 3. autoscaler: grow@{n0}->{n0 + 1}, "
          f"shrink->{m._base_ct.n_shards}, actions={a.actions}, "
          f"decisions={len(a.decisions)}")
    print("[lag_check] PASS")


asyncio.run(main())
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "[lag_check] FAIL (rc=$rc)"
    exit $rc
fi
