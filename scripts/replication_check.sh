#!/usr/bin/env bash
# Tier-2 replication-fabric gate (ISSUE 12): a live leader dist-worker
# behind the real RPC fabric, a WarmStandby attached over it, and a
# remote pub-side match cache fed by the exact-invalidation stream.
# Asserts the patch-delta replication contract:
#   1. a churn storm on the leader keeps the standby in EXACT parity by
#      deltas alone — zero full rebuilds and zero match-cache generation
#      bumps on the replica, arenas byte-identical where no anchor
#      intervened, rows identical to the leader's host oracle always,
#   2. killing the leader, the PROMOTED standby serves correct rows
#      (vs an independently maintained oracle trie) without compiling,
#   3. a remote cache entry for a mutated (tenant, filter) is evicted by
#      the stream — far inside a deliberately huge TTL, no TTL wait.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${REPL_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import asyncio, os, random, time

from bifromq_tpu.dist.remote import (SERVICE, DistWorkerRPCService,
                                     RemoteDistWorker)
from bifromq_tpu.dist.worker import DistWorker
from bifromq_tpu.models.matchcache import TenantMatchCache
from bifromq_tpu.models.oracle import Route, SubscriptionTrie
from bifromq_tpu.replication.standby import InvalidationPuller, WarmStandby
from bifromq_tpu.rpc.fabric import RPCServer, ServiceRegistry
from bifromq_tpu.types import RouteMatcher

N_SEED = int(os.environ.get("REPL_CHECK_SEED_SUBS", "300"))
N_OPS = int(os.environ.get("REPL_CHECK_OPS", "400"))
TTL_S = 1000.0


def rt(tf, i):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=f"rcv{i}", deliverer_key=f"d{i}",
                 incarnation=0)


def canon(m):
    return (sorted((r.matcher.mqtt_topic_filter, r.receiver_url)
                   for r in m.normal),
            {f: sorted(r.receiver_url for r in ms)
             for f, ms in m.groups.items()})


async def drain(sb, min_applied=0, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        await asyncio.sleep(0.05)
        if sb.attached and sb.lag() == 0 and sb.applied >= min_applied:
            return True
    return False


async def main():
    rng = random.Random(42)
    worker = DistWorker(node_id="leader0")
    await worker.start()
    server = RPCServer(host="127.0.0.1", port=0)
    DistWorkerRPCService(worker).register(server)
    await server.start()
    reg = ServiceRegistry()
    reg.announce(SERVICE, f"127.0.0.1:{server.port}")
    remote = RemoteDistWorker(reg)

    # independently maintained oracle (the test's own truth)
    oracle = SubscriptionTrie()
    live = {}

    async def add(tf, i):
        out = await remote.add_route("T", rt(tf, i))
        assert out in ("ok", "exists"), out
        oracle.add(rt(tf, i))
        live[(tf, (0, f"rcv{i}", f"d{i}"))] = rt(tf, i)

    for i in range(N_SEED):
        await add(f"seed/{i}/t", i)
    await add("seed/+/t", 9000)
    await add("wild/#", 9001)

    # ---- leg 1: standby tracks a churn storm by deltas alone ----------
    sb = WarmStandby(reg)
    await sb.start()
    assert await drain(sb), f"standby never attached: {sb.status()}"
    resyncs0 = sb.resyncs
    gen0 = sb.matcher.match_cache._gen
    applied0 = sb.applied
    n = 0
    i = N_SEED
    while n < N_OPS:
        i += 1
        if rng.random() < 0.6:
            tf = f"churn/{rng.randint(0, 80)}/x"
            await add(tf, i)
            n += 1
        elif live:
            key = rng.choice(list(live))
            r = live.pop(key)
            out = await remote.remove_route("T", r.matcher,
                                            r.receiver_url, r.incarnation)
            if out == "ok":
                oracle.remove(r.matcher, r.receiver_url, r.incarnation)
                n += 1
    assert await drain(sb, min_applied=applied0 + 1), sb.status()
    assert sb.resyncs == resyncs0, \
        f"storm forced a resync ({sb.resyncs - resyncs0}) — not delta-only"
    assert sb.matcher.compile_count == 0, "replica REBUILT"
    assert sb.matcher.match_cache._gen == gen0, "replica generation bumped"

    topics = ([f"seed/{j}/t" for j in range(N_SEED)]
              + [f"churn/{j}/x" for j in range(81)] + ["wild/deep/q"])
    got = sb.matcher.match_batch([("T", t) for t in topics])
    coproc = next(iter(worker.store.coprocs.values()))
    want = coproc.matcher.match_from_tries([("T", t) for t in topics])
    bad = [t for t, g, w in zip(topics, got, want) if canon(g) != canon(w)]
    assert not bad, f"row parity broke on {bad[:5]}"
    print(f"leg1 OK: {sb.applied} deltas applied, lag=0, "
          f"rebuilds=0, gen_bumps=0, parity over {len(topics)} topics")

    # ---- leg 3 setup BEFORE the leader dies: exact invalidation -------
    cache = TenantMatchCache(scope="pub", ttl_s=TTL_S)

    def inval(t, f):
        cache.bump_all() if t is None else cache.invalidate(t, f)
    puller = InvalidationPuller(reg, inval, wait_s=0.3)
    await puller.start()
    t0 = time.monotonic()
    while not puller.cursors and time.monotonic() - t0 < 10:
        await asyncio.sleep(0.05)
    await asyncio.sleep(0.5)    # absorb the initial-attach bump
    tok = cache.token("T")
    assert cache.put("T", "inval/probe/z", (1, 1), "CACHED", tok)
    assert cache.get("T", "inval/probe/z", (1, 1)) == "CACHED"
    t0 = time.monotonic()
    await add("inval/probe/z", 7777)
    evicted_in = None
    while time.monotonic() - t0 < 10:
        await asyncio.sleep(0.02)
        if cache.get("T", "inval/probe/z", (1, 1)) is None:
            evicted_in = time.monotonic() - t0
            break
    assert evicted_in is not None, "stream never evicted the entry"
    assert evicted_in < TTL_S / 100, evicted_in
    oracle.add(rt("inval/probe/z", 7777))
    print(f"leg3 OK: exact invalidation in {evicted_in*1e3:.0f}ms "
          f"(TTL={TTL_S:.0f}s untouched)")
    await puller.stop()

    # ---- leg 2: kill the leader, promote the standby ------------------
    assert await drain(sb), sb.status()
    await sb.stop()
    await server.stop()
    await worker.stop()
    promoted = sb.promote()
    assert promoted.compile_count == 0, "promotion compiled"
    got = promoted.match_batch([("T", t) for t in topics
                                + ["inval/probe/z"]])
    for t, g in zip(topics + ["inval/probe/z"], got):
        want = oracle.match(t.split("/"))
        assert canon(g) == canon(want), t
    compiles_at_promotion = promoted.compile_count
    assert compiles_at_promotion == 0, "serving after promotion compiled"
    # and it mutates as a first-class serving matcher now (this may
    # legitimately kick the NORMAL frag-compaction lifecycle — the gate's
    # zero-rebuild bar covers attach → promote → first serves)
    promoted.add_route("T", rt("post/failover/x", 1))
    g = promoted.match_batch([("T", "post/failover/x")])[0]
    assert canon(g) == canon(promoted.match_from_tries(
        [("T", "post/failover/x")])[0])
    promoted.drain()    # join any background compaction before exit
    print(f"leg2 OK: promoted standby served {len(topics) + 1} topics "
          f"correctly with compile_count={compiles_at_promotion}")
    print("REPLICATION CHECK PASSED")


asyncio.run(main())
EOF
rc=$?
exit $rc
