#!/usr/bin/env bash
# Tier-2 chaos-campaign gate (ISSUE 16): the seeded, scriptable fault
# campaigns — hung-shard split dispatch with blast-radius assertions and
# the standby mid-promote crash — run twice from fresh state inside the
# tests and must produce byte-identical report signatures.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${CAMPAIGN_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m campaign \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@"
rc=$?
if [ $rc -eq 124 ] || [ $rc -eq 137 ]; then
    echo "chaos-campaign suite TIMED OUT (rc=$rc)" >&2
fi
exit $rc
