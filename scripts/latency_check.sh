#!/usr/bin/env bash
# Tier-2 device-pipeline latency gate (ISSUE 6): exercises the async
# dispatch ring + queue-depth-adaptive batching on CPU-scaled inputs and
# asserts
#   1. pipelined small-batch serving lands e2e batch p99 under a
#      CPU-scaled threshold (default 50ms; the TPU target is <1ms),
#   2. the pipelined p99 beats the sync full-batch baseline by >=10x
#      (the BENCH_r01 666ms-sync failure shape),
#   3. fused-kernel on (interpret mode on CPU) and off produce IDENTICAL
#      match results on a randomized workload,
#   4. the match-cache hit path does not regress: a repeated-topic
#      workload still serves >80% from cache through the async path and
#      a pure compaction does not cold-start it.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${LATENCY_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import asyncio, os, random, time

import numpy as np

from bifromq_tpu import workloads
from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.pipeline import pipeline_depth

N_SUBS = 20_000
BIG = 2048
SMALL = 16
ITERS = 10
P99_MS_MAX = float(os.environ.get("LATENCY_CHECK_P99_MS", "50"))

tries = workloads.config_wildcard(N_SUBS, seed=0)
topics = workloads.probe_topics(BIG * 4, seed=1)


def canon(m):
    return (sorted((r.matcher.mqtt_topic_filter, r.receiver_url)
                   for r in m.normal),
            {f: sorted(r.receiver_url for r in ms)
             for f, ms in m.groups.items()})


# ---- 1+2: sync baseline vs pipelined p99 --------------------------------
m = TpuMatcher.from_tries(tries, match_cache=False, auto_compact=False)
big_batches = [[("tenant0", t) for t in topics[i * BIG:(i + 1) * BIG]]
               for i in range(4)]
m.match_batch(big_batches[0])           # warm
sync_lat = []
for it in range(ITERS):
    s0 = time.perf_counter()
    m.match_batch(big_batches[it % 4])
    sync_lat.append(time.perf_counter() - s0)
sync_p99 = float(np.percentile(sync_lat, 99)) * 1e3

sm = [[("tenant0", topics[(j * SMALL + k) % len(topics)])
       for k in range(SMALL)] for j in range(512)]


async def run_pipe():
    lats = []
    nxt = {"i": 0}

    async def worker():
        while nxt["i"] < len(sm):
            b = sm[nxt["i"]]
            nxt["i"] += 1
            s0 = time.perf_counter()
            await m.match_batch_async(b)
            lats.append(time.perf_counter() - s0)

    await m.match_batch_async(sm[0])    # warm the small shape
    await asyncio.gather(*[worker() for _ in range(pipeline_depth())])
    return lats

pipe_lat = asyncio.run(run_pipe())
pipe_p99 = float(np.percentile(pipe_lat, 99)) * 1e3
speedup = sync_p99 / max(1e-9, pipe_p99)
print(f"sync batch p99 {sync_p99:.1f}ms, pipelined batch p99 "
      f"{pipe_p99:.2f}ms, speedup {speedup:.1f}x "
      f"(ring peak in-flight {m._ring.peak_inflight})")
assert pipe_p99 < P99_MS_MAX, \
    f"pipelined p99 {pipe_p99:.1f}ms over the {P99_MS_MAX}ms CPU bound"
assert speedup >= 10, f"p99 speedup {speedup:.1f}x < 10x"

# ---- 3: fused-kernel on/off parity --------------------------------------
rng = random.Random(3)
probe = [("tenant0", topics[rng.randrange(len(topics))])
         for _ in range(64)]
legs = {}
for mode in ("0", "1"):
    os.environ["BIFROMQ_FUSED_KERNEL"] = mode
    mm = TpuMatcher.from_tries(tries, match_cache=False,
                               auto_compact=False, k_states=8)
    legs[mode] = [canon(r) for r in mm.match_batch(probe, batch=64)]
os.environ.pop("BIFROMQ_FUSED_KERNEL")
assert legs["0"] == legs["1"], "fused kernel diverged from lax walk"
print("fused on/off parity ok (64 randomized queries)")

# ---- 4: cache hit path through the async pipeline -----------------------
mc = TpuMatcher.from_tries(tries, match_cache=True, auto_compact=False)
hot = [("tenant0", topics[i]) for i in range(24)]


async def hot_loop():
    for _ in range(20):
        res = await mc.match_batch_async(hot)
        for r, q in zip(res, hot):
            want = mc.match_from_tries([q])[0]
            assert canon(r) == canon(want), "cached serve diverged"

asyncio.run(hot_loop())
hits, misses = mc.match_cache.counts()
rate = hits / max(1, hits + misses)
print(f"async hit rate {rate:.3f} ({hits} hits / {misses} misses)")
assert rate > 0.8, f"hit rate {rate:.3f} <= 0.8"

# pure compaction must not cold-start the cache (ISSUE 6 satellite):
# an exact-filter mutation evicts ONE key, then the fold into a fresh
# same-salt base must leave the generation (and the hot set) alone
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.types import RouteMatcher
mc.add_route("tenant0", Route(
    matcher=RouteMatcher.from_topic_filter("gate/exact/key"),
    broker_id=0, receiver_id="gate", deliverer_key="d0"))
gen0 = mc.match_cache._gen
mc.refresh()    # real compaction: folds the overlay into a new base
assert mc.match_cache._gen == gen0, "pure compaction bumped generation"
h0 = mc.match_cache.hits
asyncio.run(hot_loop())
assert mc.match_cache.hits > h0, "compaction cold-started the cache"
print("pure-compaction cache retention ok")
print("LATENCY GATE PASS")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "latency_check: FAIL (rc=$rc)" >&2
    exit $rc
fi
echo "latency_check: PASS"
