#!/usr/bin/env bash
# Tier-2 observability gate (ISSUE 3): boots a real broker + API server,
# drives traffic from two tenants (one deliberately hot), then asserts
#   1. GET /tenants ranks the hot tenant above the quiet one,
#   2. the push exporter delivered well-formed JSON-lines (>=1 metrics
#      record) to its file sink with its drop counter exposed,
#   3. /metrics carries the "device" section.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the chaos gate.
set -o pipefail

cd "$(dirname "$0")/.."

EXPORT_FILE="$(mktemp /tmp/obs_check_XXXX.jsonl)"
trap 'rm -f "$EXPORT_FILE"' EXIT

timeout -k 10 "${OBS_CHECK_TIMEOUT:-180}" \
    env JAX_PLATFORMS=cpu \
        BIFROMQ_OBS_EXPORT="$EXPORT_FILE" \
        BIFROMQ_OBS_EXPORT_INTERVAL_S=0.5 \
    python - <<'EOF'
import asyncio, json, os, sys

async def http(port, method, path, body=b""):
    r, w = await asyncio.open_connection("127.0.0.1", port)
    w.write(f"{method} {path} HTTP/1.1\r\nhost: x\r\n"
            f"content-length: {len(body)}\r\n"
            f"connection: close\r\n\r\n".encode() + body)
    await w.drain()
    raw = await r.read(262144)
    w.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), json.loads(payload)

async def main():
    from bifromq_tpu.apiserver import APIServer
    from bifromq_tpu.mqtt.broker import MQTTBroker
    from bifromq_tpu.mqtt.client import MQTTClient
    from bifromq_tpu.plugin.events import CollectingEventCollector
    from bifromq_tpu.utils.metrics import (MeteringEventCollector,
                                           MetricsRegistry)

    registry = MetricsRegistry()
    events = MeteringEventCollector(registry, CollectingEventCollector())
    broker = MQTTBroker(port=0, events=events)
    await broker.start()
    api = APIServer(broker, port=0, metrics=registry)
    await api.start()
    clients = []
    try:
        # hot tenant: 4 subscribers x heavy publish; quiet tenant: 1 sub,
        # a trickle
        for tenant, n in (("hot", 4), ("quiet", 1)):
            for i in range(n):
                c = MQTTClient(port=broker.port,
                               client_id=f"{tenant}-s{i}",
                               username=f"{tenant}/u{i}")
                await c.connect()
                await c.subscribe("load/t")
                clients.append(c)
        hot = MQTTClient(port=broker.port, client_id="hp",
                         username="hot/pub")
        quiet = MQTTClient(port=broker.port, client_id="qp",
                           username="quiet/pub")
        await hot.connect(); await quiet.connect()
        clients += [hot, quiet]
        for _ in range(60):
            await hot.publish("load/t", b"x" * 64, qos=1)
        for _ in range(3):
            await quiet.publish("load/t", b"x", qos=1)

        status, out = await http(api.port, "GET", "/tenants")
        assert status == 200, out
        ranked = [r["tenant"] for r in out["tenants"]]
        assert "hot" in ranked and "quiet" in ranked, ranked
        assert ranked.index("hot") < ranked.index("quiet"), ranked
        print(f"OK /tenants ranking: {ranked}")

        status, snap = await http(api.port, "GET", "/metrics")
        assert status == 200 and "device" in snap, snap.keys()
        assert "exporter" in snap["obs"], snap["obs"]
        assert "dropped" in snap["obs"]["exporter"]
        print(f"OK /metrics device section: "
              f"{json.dumps(snap['device'], default=str)[:160]}")

        # let the exporter tick at least once more, then check the sink
        await asyncio.sleep(1.2)
    finally:
        for c in clients:
            try:
                await c.disconnect()
            except Exception:
                pass
        await api.stop()
        broker.inbox.close()
        await broker.stop()      # final exporter flush happens here

    path = os.environ["BIFROMQ_OBS_EXPORT"]
    lines = [ln for ln in open(path).read().splitlines() if ln]
    assert lines, "exporter wrote nothing"
    records = [json.loads(ln) for ln in lines]   # raises on malformed
    kinds = {r["type"] for r in records}
    assert "metrics" in kinds, kinds
    metric = next(r for r in records if r["type"] == "metrics"
                  and r.get("slo"))
    assert "hot" in metric["slo"], sorted(metric["slo"])
    print(f"OK exporter: {len(records)} well-formed JSON-lines "
          f"({sorted(kinds)})")

asyncio.run(main())
print("obs_check PASSED")
EOF
rc=$?
if [ $rc -eq 124 ] || [ $rc -eq 137 ]; then
    echo "obs check TIMED OUT (rc=$rc)" >&2
fi
exit $rc
