#!/usr/bin/env python
"""North-star scale probe (VERDICT r4 #3): build + compile the full-scale
configs HOST-SIDE and record whether the compiled tables fit v5e HBM.

Pure host work — no jax import, safe to run while the TPU tunnel is down.
Emits bench_results/r5_scale_probe.json and saves the packed arrays to
/tmp/scale_tables_<cfg>.npz so a later device run (scale_device_run.py)
can upload without rebuilding (the 10M-sub Python trie build is the slow
part).

Usage: python scripts/scale_probe.py [c5|c4|c2_10m ...]
"""

import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_BYTES = 16 * 2 ** 30   # v5e: 16 GiB per chip


def _rss_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def _compile_and_record(name, rec, tries, *, max_levels):
    """Shared compile→measure→save block (one definition: the HBM
    accounting and npz key set cannot drift between configs)."""
    from bifromq_tpu.models.automaton import compile_tries
    t0 = time.time()
    ct = compile_tries(tries, max_levels=max_levels)
    rec["compile_s"] = round(time.time() - t0, 1)
    rec["n_nodes"] = int(ct.n_nodes)
    rec["n_slots"] = int(ct.n_slots)
    n = ct.node_tab.shape[0]
    tb = {
        "node_tab": int(ct.node_tab.nbytes),
        "edge_tab": int(ct.edge_tab.nbytes),
        "child_list": int(ct.child_list.nbytes),
        # device-side derived tables (ops.match.DeviceTrie.from_compiled):
        # CT_COLS=4 and RT_COLS=8 int32 columns per node
        "count_tab": n * 4 * 4,
        "route_tab": n * 8 * 4,
    }
    tb["total"] = sum(tb.values())
    rec["tables_bytes"] = tb
    rec["fits_hbm_v5e"] = tb["total"] < HBM_BYTES
    rec["hbm_frac"] = round(tb["total"] / HBM_BYTES, 4)
    rec["peak_rss_gb"] = round(_rss_gb(), 1)
    np.savez(f"/tmp/scale_tables_{name}.npz", node_tab=ct.node_tab,
             edge_tab=ct.edge_tab, child_list=ct.child_list,
             salt=np.int64(ct.salt), probe_len=np.int64(ct.probe_len),
             max_levels=np.int64(ct.max_levels))
    with open(f"/tmp/scale_roots_{name}.json", "w") as f:
        json.dump(ct.tenant_root, f)
    return rec


def probe_c5(total_subs=10_000_000, n_tenants=10_000):
    from bifromq_tpu import workloads
    rec = {"config": "c5_multitenant", "n_subs": total_subs,
           "n_tenants": n_tenants}
    t0 = time.time()
    tries = workloads.config_multi_tenant(n_tenants, total_subs, seed=0)
    rec["build_s"] = round(time.time() - t0, 1)
    print(f"[c5] tries built in {rec['build_s']}s rss={_rss_gb():.1f}GB",
          flush=True)
    return _compile_and_record("c5", rec, tries, max_levels=16)


def probe_c4(n_topics=5_000_000):
    from bifromq_tpu import workloads
    from bifromq_tpu.models.oracle import SubscriptionTrie
    from bifromq_tpu.models.retained import _topic_route
    rec = {"config": "c4_retained", "n_retained": n_topics}
    t0 = time.time()
    topics = workloads.config_retained(n_topics, seed=0)["tenant0"]
    trie = SubscriptionTrie()
    for levels in topics:
        trie.add(_topic_route(levels, "/".join(levels)))
    rec["build_s"] = round(time.time() - t0, 1)
    print(f"[c4] trie built in {rec['build_s']}s rss={_rss_gb():.1f}GB",
          flush=True)
    return _compile_and_record("c4", rec, {"tenant0": trie}, max_levels=18)


def probe_c2_10m(n_subs=10_000_000):
    from bifromq_tpu import workloads
    rec = {"config": "c2_wildcard", "n_subs": n_subs}
    t0 = time.time()
    tries = workloads.config_wildcard(n_subs, seed=0)
    rec["build_s"] = round(time.time() - t0, 1)
    print(f"[c2@10M] tries built in {rec['build_s']}s rss={_rss_gb():.1f}GB",
          flush=True)
    return _compile_and_record("c2_10m", rec, tries, max_levels=16)


def main():
    which = sys.argv[1:] or ["c5", "c4"]
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_results", "r5_scale_probe.json")
    results = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            results = json.load(f)
    for name in which:
        fn = {"c5": probe_c5, "c4": probe_c4, "c2_10m": probe_c2_10m}[name]
        rec = fn()
        rec["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        results[name] = rec
        print(f"[{name}] {json.dumps(rec)}", flush=True)
        with open(out_path, "w") as f:
            json.dump(results, f, indent=1)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
