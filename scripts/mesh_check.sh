#!/usr/bin/env bash
# Tier-2 sharded-mesh gate (ISSUE 15): the multi-chip matcher as a
# first-class serving plane on an 8-way HOST mesh
# (XLA_FLAGS=--xla_force_host_platform_device_count=8), asserting:
#   1. a 400-op churn storm through the per-shard patch plane runs ZERO
#      full rebuilds and ZERO match-cache generation bumps, with exact
#      host-oracle row parity before/during/after — per-shard patch
#      apply >=100x cheaper than this base's own mesh rebuild,
#   2. per-shard ShardedTables.device_bytes() stays <= the
#      CapacityPlanner.fits per-shard prediction (the multichip capacity
#      model must never drift from the mesh upload path),
#   3. per-shard FAULT DOMAINS: a hang injected on ONE shard's device
#      opens ONLY that shard's breaker; its rows serve exactly from the
#      host oracle while every healthy shard keeps serving on device
#      (no further watchdog timeouts), and the half-open canary
#      re-closes the breaker on row parity.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${MESH_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BIFROMQ_DEVICE_DEADLINE_S=0.3 \
    python - <<'EOF'
import asyncio, os, random, time

import numpy as np

from bifromq_tpu import workloads
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.obs import OBS
from bifromq_tpu.obs.capacity import CapacityPlanner
from bifromq_tpu.parallel.sharded import MeshMatcher, make_mesh
from bifromq_tpu.resilience.faults import get_injector
from bifromq_tpu.types import RouteMatcher

N_SUBS = int(os.environ.get("MESH_CHECK_SUBS", "20000"))
N_OPS = int(os.environ.get("MESH_CHECK_OPS", "400"))
SPEEDUP_MIN = float(os.environ.get("MESH_CHECK_SPEEDUP", "100"))
N_SHARDS = 8


def mk(tf, rid, inc=0):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=rid, deliverer_key="d0", incarnation=inc)


def canon(r):
    return (sorted((x.matcher.mqtt_topic_filter, x.receiver_url)
                   for x in r.normal),
            {f: sorted(x.receiver_url for x in ms)
             for f, ms in r.groups.items()})


def assert_parity(m, probe, label):
    got = m.match_batch(probe)
    want = m.match_from_tries(probe)
    bad = sum(1 for a, b in zip(got, want) if canon(a) != canon(b))
    assert bad == 0, f"{label}: {bad}/{len(probe)} rows mismatch the oracle"


mesh = make_mesh(1, N_SHARDS)
tries = workloads.config_multi_tenant(n_tenants=48, total_subs=N_SUBS,
                                      seed=0)
tenants = sorted(tries)
t0 = time.perf_counter()
m = MeshMatcher.from_tries(tries, mesh=mesh, match_cache=False)
rebuild_s = m._last_compile_s
print(f"mesh base: {sum(len(t) for t in tries.values())} subs over "
      f"{N_SHARDS} shards, compile+install {time.perf_counter()-t0:.1f}s "
      f"(mesh rebuild {rebuild_s:.1f}s)")

# ---- capacity: per-shard padded bytes <= planner prediction ------------
db = m._base_ct.device_bytes()
worst = max(p["padded_bytes"] for p in db["per_shard"])
tables = m._base_ct
slots_ref = max(1, max(ct.n_slots for ct in tables.compiled))
e_max = max(1, max(
    int(np.count_nonzero(ct.edge_tab.reshape(-1, 4)[:, 0] >= 0))
    for ct in tables.compiled))
planner = CapacityPlanner(
    nodes_per_sub=max(ct.node_tab.shape[0]
                      for ct in tables.compiled) / slots_ref,
    edges_per_sub=e_max / slots_ref, slots_per_sub=1.0,
    edge_load=e_max / (tables.edge_tab.shape[1] * tables.probe_len))
predicted = planner.fits(slots_ref * N_SHARDS, mesh=(1, N_SHARDS),
                         probe_len=tables.probe_len)["tables"]["total"]
assert worst <= predicted, (
    f"per-shard padded bytes {worst} exceed fits() prediction {predicted}")
print(f"capacity: worst shard {worst}B <= predicted {predicted}B "
      f"(pad_waste={db['pad_waste_ratio']})")

# ---- churn storm: zero rebuilds, zero bumps, parity, >=100x ------------
topics = workloads.probe_topics(512, seed=1)
probe = [(tenants[i % len(tenants)], t) for i, t in enumerate(topics[:256])]
m.match_batch(probe)                     # warm walk shapes
# warm the per-shard scatter jits OUTSIDE the timed window (one flush
# per shard: the scatter programs are keyed per shard id + shape class,
# and their one-off traces are compile cost, not patch cost — same
# discipline as the single-chip churn gate's warm)
seen = set()
i = 0
while len(seen) < N_SHARDS and i < 200:
    t = tenants[i % len(tenants)]
    seen.add(tables.shard_of(t))
    m.add_route(t, mk(f"gate/warm/{i}/+", f"w{i}"))
    m._flush_patches()
    i += 1
assert_parity(m, probe, "before storm")

ledger = OBS.profiler.ledger
compiles0, bumps0 = m.compile_count, ledger.generation_bumps
rng = random.Random(3)
lat, added = [], []
for i in range(N_OPS):
    tenant = tenants[i % len(tenants)]
    tf = f"gate/{i}/+"
    s0 = time.perf_counter()
    if i % 3 == 2 and added:
        tnt, f, rid = added.pop(rng.randrange(len(added)))
        m.remove_route(tnt, RouteMatcher.from_topic_filter(f),
                       (0, rid, "d0"), incarnation=1)
    else:
        m.add_route(tenant, mk(tf, f"c{i}", inc=1))
        added.append((tenant, tf, f"c{i}"))
    m._flush_patches()
    lat.append(time.perf_counter() - s0)
    if i % 50 == 25:
        assert_parity(m, probe[:64], f"during storm (op {i})")
p99 = float(np.percentile(np.array(lat), 99))
speedup = rebuild_s / max(1e-9, p99)
assert m.compile_count == compiles0, (
    f"{m.compile_count - compiles0} full rebuilds inside the churn window")
assert ledger.generation_bumps == bumps0, "generation bumps during churn"
assert speedup >= SPEEDUP_MIN, (
    f"patch p99 {p99*1e3:.1f}ms only {speedup:.0f}x vs the "
    f"{rebuild_s:.1f}s mesh rebuild (need >={SPEEDUP_MIN}x)")
storm_probe = probe + [(t, f"gate/{i}/x")
                       for i, (t, _, _) in enumerate(added[:64])]
assert_parity(m, storm_probe, "after storm")
print(f"churn: {N_OPS} ops, rebuilds=0 bumps=0, patch p99 "
      f"{p99*1e3:.2f}ms = {speedup:.0f}x vs rebuild, parity exact "
      f"(fallbacks={m.patch_fallbacks})")

# ---- per-shard fault domain: one hung shard degrades only itself -------
sick = tables.shard_of(tenants[0])
inj = get_injector()
rule = inj.add_rule(service="tpu-device", method=f"mesh:shard{sick}",
                    action="hang", side="device")


async def fault_leg():
    qs = probe[:128]
    for _ in range(4):          # trip threshold (3) + one open serve
        got = await m.match_batch_async(qs)
        want = m.match_from_tries(qs)
        assert all(canon(a) == canon(b) for a, b in zip(got, want)), \
            "rows must stay exact through the hang (oracle degradation)"
    states = [br.state for br in m.shard_breakers]
    assert states[sick] == "open", states
    assert all(s == "closed" for i, s in enumerate(states) if i != sick), (
        f"ONLY shard {sick} may open: {states}")
    inj.remove_rule(rule)
    timeouts0 = m._ring.timeouts_total
    got = await m.match_batch_async(qs)
    want = m.match_from_tries(qs)
    assert all(canon(a) == canon(b) for a, b in zip(got, want))
    assert m._ring.timeouts_total == timeouts0, (
        "healthy shards must keep serving on device with no timeouts "
        "while the sick shard's breaker is open")
    m.shard_breakers[sick].recovery_time = 0.0
    await m.match_batch_async(qs)
    assert m.shard_breakers[sick].state == "closed", "canary must re-close"
    q = m._ring.quarantine.snapshot()
    assert q.get("by_tag", {}).get(f"mesh:shard{sick}", 0) >= 1, q

asyncio.run(fault_leg())
print(f"fault domain: shard {sick} hang -> only its breaker opened, "
      f"healthy shards stayed on device, canary re-closed "
      f"(quarantine {m._ring.quarantine.snapshot()})")
print("MESH CHECK PASSED")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "MESH CHECK FAILED (rc=$rc)"
    exit $rc
fi
