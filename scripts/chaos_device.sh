#!/usr/bin/env bash
# Tier-2 device-chaos gate (ISSUE 7): inject hang + error + slow device
# faults under load and assert the broker's device-fault resilience plane
# holds:
#   1. with a PERMANENT device-hang fault injected, serving never
#      deadlocks — every match returns exact (host-oracle) rows within
#      the watchdog deadline budget,
#   2. the device circuit breaker opens within its failure threshold of
#      batches, after which dispatches stop entirely,
#   3. clearing the fault restores device serving via the half-open
#      canary probe — verified by `kernel=lax|lax_donated|fused` span
#      tags returning on device.dispatch spans,
#   4. QoS0 shedding fires ONLY under injected overload and is
#      tenant-fair (the noisy tenant sheds strictly more than the quiet
#      tenant in the same window); the bounded QoS>0 ingest gate
#      backpressures without ever dropping (zero QoS1 loss).
# Runs on CPU (JAX_PLATFORMS=cpu) under a hard timeout like the other
# gates, plus the chaos-marked unit suite for this plane.
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${CHAOS_DEVICE_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/test_device_chaos.py \
    -q -m chaos -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

timeout -k 10 "${CHAOS_DEVICE_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu BIFROMQ_DEVICE_DEADLINE_S=0.3 \
    python - <<'EOF'
import asyncio, time

from bifromq_tpu import trace
from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.resilience.device import LoadShedder, IngestGate
from bifromq_tpu.resilience.faults import get_injector
from bifromq_tpu.types import RouteMatcher
from bifromq_tpu.utils.metrics import FABRIC, FabricMetric


def mk(tf, r):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=r, deliverer_key="d0")


m = TpuMatcher(max_levels=8, k_states=8, auto_compact=False,
               match_cache=False)
m.add_route("T", mk("a/b", "r1"))
m.add_route("T", mk("a/+", "r2"))
m.refresh()
m.device_breaker.recovery_time = 0.2
thr = m.device_breaker.failure_threshold
inj = get_injector()


async def serve(topic):
    res = await m.match_batch_async([("T", topic)])
    return sorted(r.receiver_id for r in res[0].normal)


async def main():
    # ---- 1+2: permanent hang → no deadlock, breaker opens -----------------
    inj.add_rule(service="tpu-device", method="dispatch", action="hang")
    t0 = time.monotonic()
    for i in range(thr + 2):
        assert await serve(["a", "b"]) == ["r1", "r2"], "wrong rows"
    wall = time.monotonic() - t0
    budget = 0.3 * (thr + 2) + 2.0
    assert wall < budget, f"hang serving took {wall:.1f}s > {budget:.1f}s"
    assert m.device_breaker.state == "open", m.device_breaker.state
    d_open = m._ring.dispatched_total
    assert await serve(["a", "b"]) == ["r1", "r2"]
    assert m._ring.dispatched_total == d_open, "open breaker dispatched"
    assert m._ring.timeouts_total >= thr
    print(f"hang gate ok: {thr + 2} batches in {wall:.2f}s, breaker open "
          f"after {m._ring.timeouts_total} timeouts, dispatch stopped")

    # ---- error + slow faults also degrade exactly -------------------------
    inj.reset()
    m.device_breaker.force_close()          # re-arm a closed breaker
    inj.add_rule(service="tpu-device", method="dispatch", action="error",
                 max_hits=1)
    assert await serve(["a", "b"]) == ["r1", "r2"]
    inj.add_rule(service="tpu-device", method="dispatch", action="slow",
                 delay=0.05, max_hits=1)
    assert await serve(["a", "b"]) == ["r1", "r2"]
    print("error + slow fault gate ok (exact rows either way)")

    # ---- 3: canary recovery, kernel tags return ---------------------------
    m.device_breaker.force_open()
    await asyncio.sleep(0.25)               # recovery window
    trace.TRACER.reset()
    trace.TRACER.sampler.default_rate = 1.0
    try:
        assert await serve(["a", "b"]) == ["r1", "r2"]   # the canary
        assert m.device_breaker.state == "closed", "canary did not close"
        assert await serve(["a", "x"]) == ["r2"]
        kernels = {s["tags"].get("kernel")
                   for s in trace.TRACER.export(limit=100)
                   if s["name"] == "device.dispatch"}
        assert kernels & {"lax", "lax_donated", "fused"}, kernels
    finally:
        trace.TRACER.sampler.default_rate = 0.0
        trace.TRACER.reset()
    print(f"canary recovery ok: breaker closed, kernel tags {kernels}")

    # ---- 4: shed only under injected overload, tenant-fair ----------------
    clk = [0.0]
    shed = LoadShedder(clock=lambda: clk[0])
    pressure = [0.0]
    import bifromq_tpu.obs as obs_pkg
    real_qp = obs_pkg.OBS.device.queue_pressure
    real_dd = obs_pkg.OBS.device.dispatch_queue_depth
    real_noisy = obs_pkg.OBS.is_noisy
    obs_pkg.OBS.device.queue_pressure = lambda: pressure[0]
    obs_pkg.OBS.device.dispatch_queue_depth = lambda: 0
    obs_pkg.OBS.is_noisy = lambda tenant: tenant == "noisy"
    try:
        for _ in range(50):                 # healthy: nothing sheds
            clk[0] += 0.01
            assert not shed.should_shed("noisy")
            assert not shed.should_shed("quiet")
        assert shed.shed_total == 0, "shed outside injected overload"
        pressure[0] = 2.0                   # injected overload (level 1)
        for _ in range(50):
            clk[0] += 0.01
            shed.should_shed("noisy")
            shed.should_shed("quiet")
        snap = shed.snapshot()["match_shed_total"]
        assert snap.get("noisy", 0) > snap.get("quiet", 0), snap
        assert snap.get("quiet", 0) == 0, snap
    finally:
        obs_pkg.OBS.device.queue_pressure = real_qp
        obs_pkg.OBS.device.dispatch_queue_depth = real_dd
        obs_pkg.OBS.is_noisy = real_noisy
    print(f"shed gate ok: silent when healthy, tenant-fair under "
          f"overload {snap}")

    # ---- zero QoS1 loss: the gate parks, it never drops -------------------
    gate = IngestGate(capacity=4)
    delivered = []

    async def one(i):
        await gate.acquire()
        try:
            await asyncio.sleep(0.001)
            delivered.append(i)
        finally:
            gate.release()

    await asyncio.gather(*(one(i) for i in range(64)))
    assert len(delivered) == 64, "QoS1 admission lost work"
    assert gate.peak_inflight <= 4
    print(f"qos1 gate ok: 64/64 delivered, peak in-flight "
          f"{gate.peak_inflight} (bounded)")


asyncio.run(main())
print("DEVICE CHAOS GATE PASS")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "chaos_device: FAIL (rc=$rc)" >&2
    exit $rc
fi
echo "chaos_device: PASS"
