#!/usr/bin/env bash
# Tier-2 device fan-out gate (ISSUE 19): the second device stage —
# interval expansion + per-peer bucketing — asserting the contract:
#   1. the full parity suite (device expansion ≡ host expand_intervals
#      + numpy stable-argsort bucketing, overflow/trunc/empty/migration
#      cases included),
#   2. a ~100K-route microbench: device fused expand+bucket beats the
#      pre-change host shape (grid readback + C++/numpy expansion +
#      per-route python delivery grouping) by >= the bar,
#   3. serving attribution + A/B: BIFROMQ_DEVICE_EXPAND=1 serves
#      byte-identical MatchedRoutes to =0, batches carry a dev_expand
#      stage in the profiler split and the device.expand histogram.
# Runs on CPU (JAX_PLATFORMS=cpu), hard timeout like the other gates.
set -o pipefail

cd "$(dirname "$0")/.."

echo "== 1. expansion/bucketing parity suite =="
timeout -k 10 "${EXPAND_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_expand_device.py -q -p no:cacheprovider \
    || exit 1

echo "== 2. microbench + 3. serving A/B =="
timeout -k 10 "${EXPAND_CHECK_TIMEOUT:-420}" \
    env JAX_PLATFORMS=cpu \
    python - <<'EOF'
import os, time

import numpy as np
import jax

from bifromq_tpu.models.matcher import TpuMatcher
from bifromq_tpu.models.oracle import Route
from bifromq_tpu.obs import OBS
from bifromq_tpu.ops.match import (RouteIntervals, bucket_pairs_host,
                                   expand_intervals, expand_routes)
from bifromq_tpu.types import RouteMatcher
from bifromq_tpu.utils.metrics import STAGES

SPEEDUP_MIN = float(os.environ.get("EXPAND_CHECK_SPEEDUP", "1.5"))

# ---- 2. ~100K-route microbench: device stage vs pre-change host shape
B, A = 1024, 16
rng = np.random.default_rng(11)
counts = rng.poisson(6, size=(B, A)).astype(np.int32)
starts = rng.integers(0, 200_000, size=(B, A)).astype(np.int32)
total = int(counts.sum())
cap = max(65536, -(-int(total * 2) // 65536) * 65536)
ivl = RouteIntervals(
    start=jax.device_put(starts), count=jax.device_put(counts),
    n_routes=jax.device_put(counts.sum(axis=1)),
    overflow=jax.device_put(np.zeros(B, bool)))
slot_peer = jax.device_put(np.zeros(0, np.int32))   # single-server arena

er = expand_routes(ivl, slot_peer, cap=cap, n_peers=0)   # jit warmup
np.asarray(er.peer_offsets)

def best_of(fn, reps=7):
    best = float("inf")
    for _ in range(reps):
        s = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - s)
    return best

def device_leg():
    er = expand_routes(ivl, slot_peer, cap=cap, n_peers=0)
    np.asarray(er.peer_slots); np.asarray(er.peer_rows)
    np.asarray(er.row_offsets); np.asarray(er.trunc)

def host_leg():
    # the pre-ISSUE-19 serving shape: full grid readback, host
    # expansion, per-route python delivery grouping
    gs = np.asarray(ivl.start); gc = np.asarray(ivl.count)
    slots, offs = expand_intervals(gs, np.maximum(gc, 0))
    by_peer = {}
    for sl in slots.tolist():
        by_peer.setdefault(0, []).append(sl)

dev_s, host_s = best_of(device_leg), best_of(host_leg)
speedup = host_s / max(1e-9, dev_s)
print(f"microbench: {total:,} routes — device {dev_s*1e3:.1f}ms, "
      f"host {host_s*1e3:.1f}ms -> {speedup:.1f}x (bar {SPEEDUP_MIN}x)")
assert speedup >= SPEEDUP_MIN, \
    f"device expand only {speedup:.2f}x the host path"

# untimed: the non-identity bucket path stays byte-exact vs the oracle
sp = rng.integers(0, 3, 200_000).astype(np.int32)
er = expand_routes(ivl, jax.device_put(sp), cap=cap, n_peers=3)
h_slots, h_offs = expand_intervals(starts, np.maximum(counts, 0))
h_rows = np.repeat(np.arange(B, dtype=np.int32), np.diff(h_offs))
hps, hpr, hpo = bucket_pairs_host(h_slots, h_rows, sp, 3)
live = int(np.asarray(er.peer_offsets)[4])
assert live == int(hpo[4]), "live-pair count drift"
assert np.array_equal(np.asarray(er.peer_slots)[:live], hps[:live])
assert np.array_equal(np.asarray(er.peer_rows)[:live], hpr[:live])
print(f"bucket parity: {live:,} pairs across 3 peers + sentinels OK")

# ---- 3. serving A/B + stage attribution ------------------------------
def mk(tf, rid):
    return Route(matcher=RouteMatcher.from_topic_filter(tf), broker_id=0,
                 receiver_id=rid, deliverer_key="d0", incarnation=1)

# match_cache=False (not None, which means "default"): the ISSUE-4
# front-end would serve the second leg's identical queries from cache
# and the device stage would never run
m = TpuMatcher(auto_compact=False, match_cache=False)
for i in range(256):
    m.add_route("tenant0", mk(f"dev/{i}/+", f"r{i}"))
    m.add_route("tenant0", mk(f"dev/{i}/#", f"w{i}"))
m.refresh()
queries = [("tenant0", f"dev/{i % 256}/x") for i in range(64)]

def canon(results):
    return [sorted((x.matcher.mqtt_topic_filter, x.receiver_url)
                   for x in r.normal) for r in results]

prev = os.environ.get("BIFROMQ_DEVICE_EXPAND")
try:
    os.environ["BIFROMQ_DEVICE_EXPAND"] = "0"
    legacy = canon(m.match_batch(queries))
    os.environ["BIFROMQ_DEVICE_EXPAND"] = "1"
    b0 = OBS.profiler.batches_total
    device = canon(m.match_batch(queries))
finally:
    if prev is None:
        os.environ.pop("BIFROMQ_DEVICE_EXPAND", None)
    else:
        os.environ["BIFROMQ_DEVICE_EXPAND"] = prev
assert legacy == device, "MatchedRoutes drift between expand modes"
assert m.last_expanded is not None, "device leg served without buckets"
n_new = OBS.profiler.batches_total - b0
recs = OBS.profiler.records()[-n_new:] if n_new else []
assert recs and any(r.dev_expand_s > 0 for r in recs), \
    "no dev_expand attribution on the device-expand batch"
assert "device.expand" in STAGES.snapshot(), \
    "device.expand stage histogram empty"
split = OBS.profiler.split_snapshot(probe=False)
assert "dev_expand_ms_p50" in split, split.keys()
print(f"serving A/B: {len(queries)} topics byte-identical across modes; "
      f"dev_expand stage attributed on {len(recs)} batch(es)")
print("EXPAND CHECK PASSED")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "EXPAND CHECK FAILED (rc=$rc)" >&2
fi
exit $rc
