#!/usr/bin/env bash
# Tier-2 chaos gate: the wire-level fault-injection suite (ISSUE 1).
# Runs the chaos-marked tests under a hard timeout on the CPU mesh
# (JAX_PLATFORMS=cpu, same virtual 8-device config as tier-1).
set -o pipefail

cd "$(dirname "$0")/.."

timeout -k 10 "${CHAOS_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@"
rc=$?
if [ $rc -eq 124 ] || [ $rc -eq 137 ]; then
    echo "chaos suite TIMED OUT (rc=$rc)" >&2
fi
exit $rc
