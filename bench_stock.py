#!/usr/bin/env python
"""Measure the stock-CPU baseline for bench.py's vs_baseline.

The image has no JVM, so the reference Java broker cannot run here. Instead
`native/stockmatch.cpp` re-implements the reference's match hot loop
(TenantRouteMatcher.matchAll + TopicFilterIterator, see the .cpp header for
file:line cites) with only stock-FAVORING simplifications, and this script
runs it over the exact config-2 workload bench.py uses (same seeds, same
generator): the measured rate is a conservative stand-in for the stock
single-node dist-worker match rate on this box's CPU.

Writes bench_results/stock_baseline.json; bench.py picks that up instead of
the old ASSUMED_STOCK_RATE.

Env knobs: STOCK_SUBS (1_000_000), STOCK_BATCH (16384), STOCK_ITERS (8),
STOCK_SEED (0), STOCK_CONFIGS ("1,2"), STOCK_SWEEP_B ("" = just
STOCK_BATCH; e.g. "4096,16384,65536" measures each and keeps the best —
the stock side gets its best operating point).
"""

import json
import os
import random
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
N_SUBS = int(os.environ.get("STOCK_SUBS", "1000000"))
BATCH = int(os.environ.get("STOCK_BATCH", "16384"))
ITERS = int(os.environ.get("STOCK_ITERS", "8"))
SEED = int(os.environ.get("STOCK_SEED", "0"))


def export_config2(routes_path: str, topics_path: str, *,
                   n_subs: int = N_SUBS, seed: int = SEED,
                   n_topics: int = None) -> None:
    """Write the config-2 route filters and probe topics to flat files.

    Replays workloads.config_wildcard's exact rng sequence (filter gen +
    the persistent_ratio draw) so the filters are identical to what
    bench.py compiles onto the device, and probe_topics with the same
    seed+1 bench.py uses.
    """
    sys.path.insert(0, REPO)
    from bifromq_tpu import workloads

    rng = random.Random(seed)
    names, weights = workloads._zipf_levels(1000)
    with open(routes_path, "w") as f:
        for _ in range(n_subs):
            levels = workloads.gen_filter_levels(rng, names, weights,
                                                 max_depth=6)
            rng.random()  # config_wildcard's persistent_ratio draw
            f.write("/".join(levels) + "\n")
    topics = workloads.probe_topics(n_topics or BATCH * 4, seed=seed + 1)
    with open(topics_path, "w") as f:
        for t in topics:
            f.write("/".join(t) + "\n")


def export_config1(routes_path: str, topics_path: str, *,
                   n_subs: int = 10_000, seed: int = SEED,
                   n_topics: int = None) -> None:
    """Config-1 export: exact-topic subs (workloads.config_exact replay)
    + bench.py's c1 probe topics (same n_level_names derivation)."""
    sys.path.insert(0, REPO)
    from bifromq_tpu import workloads

    rng = random.Random(seed)
    n_names = max(64, n_subs // 100)
    names, weights = workloads._zipf_levels(n_names)
    with open(routes_path, "w") as f:
        for _ in range(n_subs):
            levels = workloads.gen_topic_levels(rng, names, weights)
            rng.random()  # config_exact's persistent_ratio draw
            f.write("/".join(levels) + "\n")
    topics = workloads.probe_topics(n_topics or BATCH * 4, seed=seed + 1,
                                    n_level_names=n_names)
    with open(topics_path, "w") as f:
        for t in topics:
            f.write("/".join(t) + "\n")


def _binary_healthy(binary: str) -> bool:
    """A no-arg run must reach main (usage line, rc=2). A binary built
    against a NEWER glibc/libstdc++ than this container's dies in the
    loader instead (rc=1, "version `GLIBC_...' not found" on stderr) —
    the 2 seed-state tier-1 failures were exactly this stale artifact."""
    try:
        out = subprocess.run([binary], capture_output=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return b"usage:" in out.stderr or b"usage:" in out.stdout


def ensure_binary() -> str:
    """Build (or re-build) the stock baseline binary.

    Raises ``RuntimeError`` when no runnable binary can be produced
    (no toolchain in the image) — callers that can degrade (the tier-1
    tests) skip on it instead of failing.
    """
    binary = os.path.join(REPO, "native", "stockmatch")
    src = os.path.join(REPO, "native", "stockmatch.cpp")
    stale = (not os.path.exists(binary)
             or os.path.getmtime(binary) < os.path.getmtime(src)
             or not _binary_healthy(binary))
    if stale:
        try:
            subprocess.run(["g++", "-O3", "-std=c++17", "-march=native",
                            "-o", binary, src], check=True,
                           capture_output=True)
        except (OSError, subprocess.CalledProcessError) as e:
            # str(CalledProcessError) omits the captured stderr — surface
            # the compiler diagnostics or the operator has to re-run g++
            # by hand to see why the build broke
            stderr = getattr(e, "stderr", None) or b""
            detail = stderr.decode("utf-8", "replace").strip()
            raise RuntimeError(
                "stockmatch build failed: "
                f"{e}{(': ' + detail[-2000:]) if detail else ''}") from e
        if not _binary_healthy(binary):
            raise RuntimeError("stockmatch rebuilt but still not runnable")
    return binary


def run_stock(config: str, *, n_subs: int, batch: int = BATCH,
              iters: int = ITERS, seed: int = SEED) -> dict:
    binary = ensure_binary()
    n_topics = max(batch * 4, 262144)
    routes_path = f"/tmp/stock_c{config}_routes_{n_subs}_{seed}.txt"
    topics_path = f"/tmp/stock_c{config}_topics_{n_topics}_{seed}.txt"
    if not (os.path.exists(routes_path) and os.path.exists(topics_path)):
        t0 = time.time()
        exporter = export_config1 if config == "1" else export_config2
        exporter(routes_path, topics_path, n_subs=n_subs, seed=seed,
                 n_topics=n_topics)
        print(f"[c{config}] exported workload in {time.time() - t0:.1f}s",
              file=sys.stderr)
    out = subprocess.run([binary, routes_path, topics_path, str(batch),
                          str(iters)], check=True, capture_output=True,
                         text=True)
    res = json.loads(out.stdout)
    res["n_subs"] = n_subs
    print(f"[c{config}] B={batch}: {json.dumps(res)}", file=sys.stderr)
    return res


def main():
    configs = os.environ.get("STOCK_CONFIGS", "1,2").split(",")
    sweep_b = [int(x) for x in os.environ.get("STOCK_SWEEP_B", "").split(",")
               if x] or [BATCH]
    out = {
        "note": ("faithful C++ re-implementation of the reference "
                 "TenantRouteMatcher.matchAll hot loop (no JVM in image); "
                 "simplifications all favor the stock side — see "
                 "native/stockmatch.cpp header. Best batch size wins per "
                 "config (the stock side gets its best operating point)."),
        "nproc": os.cpu_count(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    for config in configs:
        n_subs = 10_000 if config == "1" else N_SUBS
        best, cells = None, {}
        for b in sweep_b:
            r = run_stock(config, n_subs=n_subs, batch=b,
                          iters=max(1, ITERS // max(1, b // BATCH)))
            cells[f"B{b}"] = r
            if best is None or r["topics_per_s"] > best["topics_per_s"]:
                best = r
        key = "c1_exact_10000" if config == "1" else f"c2_wildcard_{n_subs}"
        out[key] = {"best": best, "cells": cells}

    os.makedirs(os.path.join(REPO, "bench_results"), exist_ok=True)
    path = os.path.join(REPO, "bench_results", "stock_baseline.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
