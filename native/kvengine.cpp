// Native KV engine: durable ordered keyspaces behind the IKVEngine SPI.
//
// Plays the role RocksDB (C++ via rocksdbjni) plays in the reference
// (base-kv-local-engine-rocksdb: column-family-per-space, WAL, checkpoints
// for snapshots — SURVEY.md §2.9). Design: per-space ordered memtable
// (std::map) + append-only WAL with group fsync; checkpoint writes a full
// sorted dump and truncates the WAL; recovery = load checkpoint + replay WAL.
//
// C ABI for ctypes (no pybind11 in the image). All functions are
// thread-safe via a per-engine mutex; Python holds the GIL around calls
// anyway, so contention is nil in practice.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

using Bytes = std::string;

struct Space;

struct Engine {
    std::string dir;
    std::mutex mu;
    std::map<std::string, std::unique_ptr<Space>> spaces;
};

enum WalOp : uint8_t { WAL_PUT = 0, WAL_DEL = 1, WAL_DEL_RANGE = 2 };

struct Space {
    Engine* eng;
    std::string name;
    std::map<Bytes, Bytes> data;
    FILE* wal = nullptr;
    std::string wal_path;
    std::string ckpt_path;
    uint64_t wal_bytes = 0;
    // 0 = flush to OS page cache per batch commit (survives process crash);
    // 1 = fsync per batch commit (survives power loss) — the WALable SPI's
    // sync-on-commit contract.
    int sync_mode = 0;

    ~Space() {
        if (wal) fclose(wal);
    }
};

static void write_u32(FILE* f, uint32_t v) { fwrite(&v, 4, 1, f); }

static bool read_u32(FILE* f, uint32_t* v) { return fread(v, 4, 1, f) == 1; }

static void wal_append(Space* sp, uint8_t op, const Bytes& a, const Bytes& b) {
    fputc(op, sp->wal);
    write_u32(sp->wal, (uint32_t)a.size());
    fwrite(a.data(), 1, a.size(), sp->wal);
    write_u32(sp->wal, (uint32_t)b.size());
    fwrite(b.data(), 1, b.size(), sp->wal);
    sp->wal_bytes += 9 + a.size() + b.size();
}

// Batch-commit barrier: acknowledged writes must not sit in a userspace
// stdio buffer, so the Python write batch calls this once at done() — flush
// to the kernel (survives process crash); sync_mode additionally fsyncs
// (survives power loss). Group commit, not per-record syscalls.
static void wal_commit(Space* sp) {
    fflush(sp->wal);
    if (sp->sync_mode) fsync(fileno(sp->wal));
}

static void apply_op(Space* sp, uint8_t op, const Bytes& a, const Bytes& b) {
    if (op == WAL_PUT) {
        sp->data[a] = b;
    } else if (op == WAL_DEL) {
        sp->data.erase(a);
    } else {  // WAL_DEL_RANGE: [a, b)
        auto lo = sp->data.lower_bound(a);
        auto hi = sp->data.lower_bound(b);
        sp->data.erase(lo, hi);
    }
}

static void load_checkpoint(Space* sp) {
    FILE* f = fopen(sp->ckpt_path.c_str(), "rb");
    if (!f) return;
    uint32_t klen, vlen;
    while (read_u32(f, &klen)) {
        Bytes k(klen, '\0');
        if (fread(&k[0], 1, klen, f) != klen) break;
        if (!read_u32(f, &vlen)) break;
        Bytes v(vlen, '\0');
        if (vlen && fread(&v[0], 1, vlen, f) != vlen) break;
        sp->data.emplace(std::move(k), std::move(v));
    }
    fclose(f);
}

static void replay_wal(Space* sp) {
    FILE* f = fopen(sp->wal_path.c_str(), "rb");
    if (!f) return;
    for (;;) {
        int op = fgetc(f);
        if (op == EOF) break;
        uint32_t alen, blen;
        if (!read_u32(f, &alen)) break;
        Bytes a(alen, '\0');
        if (alen && fread(&a[0], 1, alen, f) != alen) break;
        if (!read_u32(f, &blen)) break;
        Bytes b(blen, '\0');
        if (blen && fread(&b[0], 1, blen, f) != blen) break;
        apply_op(sp, (uint8_t)op, a, b);
    }
    fclose(f);
}

struct Iter {
    std::vector<std::pair<Bytes, Bytes>> items;  // snapshot of the range
    size_t pos = 0;
};

}  // namespace

extern "C" {

void* kv_open(const char* dir) {
    auto* e = new Engine();
    e->dir = dir;
    mkdir(dir, 0755);
    return e;
}

void kv_close(void* eng) { delete static_cast<Engine*>(eng); }

void* kv_space(void* engp, const char* name) {
    auto* e = static_cast<Engine*>(engp);
    std::lock_guard<std::mutex> lock(e->mu);
    auto it = e->spaces.find(name);
    if (it != e->spaces.end()) return it->second.get();
    auto sp = std::make_unique<Space>();
    sp->eng = e;
    sp->name = name;
    sp->wal_path = e->dir + "/" + name + ".wal";
    sp->ckpt_path = e->dir + "/" + name + ".ckpt";
    load_checkpoint(sp.get());
    replay_wal(sp.get());
    sp->wal = fopen(sp->wal_path.c_str(), "ab");
    Space* raw = sp.get();
    e->spaces[name] = std::move(sp);
    return raw;
}

int kv_put(void* spp, const char* k, int klen, const char* v, int vlen) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    Bytes key(k, klen), val(v, vlen);
    wal_append(sp, WAL_PUT, key, val);
    apply_op(sp, WAL_PUT, key, val);
    return 0;
}

int kv_del(void* spp, const char* k, int klen) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    Bytes key(k, klen);
    wal_append(sp, WAL_DEL, key, "");
    apply_op(sp, WAL_DEL, key, "");
    return 0;
}

int kv_del_range(void* spp, const char* s, int slen, const char* e2,
                 int elen) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    Bytes a(s, slen), b(e2, elen);
    wal_append(sp, WAL_DEL_RANGE, a, b);
    apply_op(sp, WAL_DEL_RANGE, a, b);
    return 0;
}

// returns 1 if found; caller frees with kv_free
int kv_get(void* spp, const char* k, int klen, char** out, int* outlen) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    auto it = sp->data.find(Bytes(k, klen));
    if (it == sp->data.end()) return 0;
    *outlen = (int)it->second.size();
    *out = (char*)malloc(it->second.size() + 1);
    memcpy(*out, it->second.data(), it->second.size());
    return 1;
}

void kv_free(char* p) { free(p); }

uint64_t kv_count(void* spp) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    return sp->data.size();
}

int kv_flush(void* spp) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    fflush(sp->wal);
    return fsync(fileno(sp->wal));
}

// full-dump checkpoint then truncate the WAL (RocksDB-checkpoint analog)
int kv_checkpoint(void* spp) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    std::string tmp = sp->ckpt_path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    for (auto& kv : sp->data) {
        write_u32(f, (uint32_t)kv.first.size());
        fwrite(kv.first.data(), 1, kv.first.size(), f);
        write_u32(f, (uint32_t)kv.second.size());
        fwrite(kv.second.data(), 1, kv.second.size(), f);
    }
    fflush(f);
    fsync(fileno(f));
    fclose(f);
    if (rename(tmp.c_str(), sp->ckpt_path.c_str()) != 0) return -1;
    // truncate the WAL by swapping in a fresh handle; on failure keep the old
    // handle — replaying a pre-checkpoint WAL over the checkpoint is a no-op
    // (ops re-apply in order to the same final state), so an un-truncated WAL
    // is safe, a nullptr handle is not.
    FILE* nw = fopen(sp->wal_path.c_str(), "wb");
    if (!nw) return -1;
    fclose(sp->wal);
    sp->wal = nw;
    sp->wal_bytes = 0;
    return 0;
}

// sync_mode: 0 = flush-per-commit (default), 1 = fsync-per-commit
void kv_set_sync(void* spp, int sync_mode) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    sp->sync_mode = sync_mode;
}

// group-commit barrier for a write batch (see wal_commit)
void kv_commit(void* spp) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    wal_commit(sp);
}

uint64_t kv_wal_bytes(void* spp) {
    return static_cast<Space*>(spp)->wal_bytes;
}

void* kv_iter(void* spp, const char* s, int slen, const char* e2, int elen,
              int reverse) {
    auto* sp = static_cast<Space*>(spp);
    std::lock_guard<std::mutex> lock(sp->eng->mu);
    auto* it = new Iter();
    auto lo = slen >= 0 ? sp->data.lower_bound(Bytes(s, slen))
                        : sp->data.begin();
    auto hi = elen >= 0 ? sp->data.lower_bound(Bytes(e2, elen))
                        : sp->data.end();
    for (auto p = lo; p != hi; ++p) it->items.emplace_back(p->first, p->second);
    if (reverse) std::reverse(it->items.begin(), it->items.end());
    return it;
}

int kv_iter_valid(void* itp) {
    auto* it = static_cast<Iter*>(itp);
    return it->pos < it->items.size();
}

void kv_iter_key(void* itp, const char** k, int* klen) {
    auto* it = static_cast<Iter*>(itp);
    *k = it->items[it->pos].first.data();
    *klen = (int)it->items[it->pos].first.size();
}

void kv_iter_value(void* itp, const char** v, int* vlen) {
    auto* it = static_cast<Iter*>(itp);
    *v = it->items[it->pos].second.data();
    *vlen = (int)it->items[it->pos].second.size();
}

void kv_iter_next(void* itp) { static_cast<Iter*>(itp)->pos++; }

void kv_iter_close(void* itp) { delete static_cast<Iter*>(itp); }

}  // extern "C"
