// Native host-side retained-filter walker over the COMPILED automaton
// tables (the same int32 node/edge/child arrays the device walk reads).
//
// Role: the bounded-lanes device walk (ops/retained.py retained_walk)
// flags '+'-heavy filters whose frontier outgrows every lane budget; this
// DFS has no lane concept, so those rows resolve at C++ speed instead of
// the Python trie oracle (~8ms/filter measured on a 200K-topic trie —
// this walker is ~two orders faster). Semantics mirror retained_walk /
// models.retained.match_filter_host exactly: literal steps are
// single-choice bucket probes, '+' iterates the CSR child slice ('$'
// children skipped at the root), '#' emits the subtree slot range with
// the root-level '$' prefix skipped, reaching the end emits the node's
// own slot range. Output is (start, count) slot ranges — the caller
// expands them with the same vectorized ragged-arange as device results.
//
// Design (not copied): the reference's RetainMatcher scans a RocksDB
// key range per filter; this walks our own packed DFS trie arrays.

#include <cstdint>
#include <cstring>

namespace {

// node_tab columns (models/automaton.py layout contract)
constexpr int NODE_HASH = 1;
constexpr int NODE_RSTART = 2;
constexpr int NODE_RCOUNT = 3;
constexpr int NODE_CCOUNT = 5;
constexpr int NODE_CSTART = 6;
constexpr int NODE_SUB_RCOUNT = 7;
constexpr int NODE_SYS_CCOUNT = 8;
constexpr int NODE_SYS_SLOTS = 9;
constexpr int NODE_COLS = 12;

constexpr int KIND_LIT = 0;
constexpr int KIND_PLUS = 1;
constexpr int KIND_HASH = 2;

// MUST stay in sync with models.automaton._mix_u32 / ops.match._mix_u32
inline uint32_t mix_u32(uint32_t node, uint32_t h1, uint32_t h2) {
    uint32_t x = node * 0x9E3779B1u;
    x ^= h1 * 0x85EBCA6Bu;
    x ^= x >> 15;
    x *= 0xC2B2AE35u;
    x ^= h2 * 0x27D4EB2Fu;
    x ^= x >> 13;
    return x;
}

struct Walker {
    const int32_t *node_tab;
    const int32_t *edge_tab;   // [NB, P, 4]
    int64_t n_buckets;
    int64_t probe_len;
    const int32_t *child_list;
    const int32_t *kinds;      // this row's tok_kind
    const int32_t *h1s;
    const int32_t *h2s;
    int32_t n_levels;
    int32_t *ranges;           // [max_ranges, 2]
    int64_t max_ranges;
    int64_t n_ranges = 0;
    int64_t emitted = 0;       // total slots emitted (limit check)
    int64_t limit;             // <=0: unbounded
    bool range_overflow = false;

    inline const int32_t *rec(int32_t node) const {
        return node_tab + (int64_t)node * NODE_COLS;
    }

    // returns false when the walk should stop (limit reached or range
    // budget blown)
    bool emit(int32_t start, int32_t count) {
        if (count <= 0) return true;
        if (n_ranges >= max_ranges) {
            range_overflow = true;
            return false;
        }
        ranges[n_ranges * 2] = start;
        ranges[n_ranges * 2 + 1] = count;
        ++n_ranges;
        emitted += count;
        return !(limit > 0 && emitted >= limit);
    }

    int32_t edge_lookup(int32_t node, int32_t h1, int32_t h2) const {
        uint32_t b = mix_u32((uint32_t)node, (uint32_t)h1, (uint32_t)h2) &
                     (uint32_t)(n_buckets - 1);
        const int32_t *row = edge_tab + (int64_t)b * probe_len * 4;
        for (int64_t p = 0; p < probe_len; ++p) {
            const int32_t *e = row + p * 4;
            if (e[0] == node && e[1] == h1 && e[2] == h2) return e[3];
            if (e[0] < 0) break;  // buckets fill front-to-back
        }
        return -1;
    }

    bool walk(int32_t node, int32_t i) {
        const int32_t *r = rec(node);
        if (i == n_levels) return emit(r[NODE_RSTART], r[NODE_RCOUNT]);
        int32_t kind = kinds[i];
        bool at_root = i == 0;
        if (kind == KIND_HASH) {
            // subtree range; at the root skip own slots + '$' subtrees
            // (mirrors retained_walk's sys_skip = rcount + sys_slots)
            int32_t skip = at_root
                ? r[NODE_RCOUNT] + r[NODE_SYS_SLOTS] : 0;
            return emit(r[NODE_RSTART] + skip,
                        r[NODE_SUB_RCOUNT] - skip);
        }
        if (kind == KIND_PLUS) {
            int32_t cstart = r[NODE_CSTART];
            int32_t ccount = r[NODE_CCOUNT];
            if (at_root) {
                cstart += r[NODE_SYS_CCOUNT];
                ccount -= r[NODE_SYS_CCOUNT];
            }
            for (int32_t c = 0; c < ccount; ++c) {
                if (!walk(child_list[cstart + c], i + 1)) return false;
            }
            return true;
        }
        int32_t child = edge_lookup(node, h1s[i], h2s[i]);
        if (child >= 0) return walk(child, i + 1);
        return true;
    }
};

}  // namespace

extern "C" {

// Walk ``n_rows`` tokenized filters; per row writes up to ``max_ranges``
// (start, count) pairs, the range count, and an overflow flag (range
// budget blown — caller falls back to the Python oracle for that row).
void retained_match_rows(
    const int32_t *node_tab, const int32_t *edge_tab, int64_t n_buckets,
    int64_t probe_len, const int32_t *child_list,
    const int32_t *tok_h1, const int32_t *tok_h2, const int32_t *tok_kind,
    const int32_t *lengths, const int32_t *roots,
    int64_t n_rows, int64_t width,
    int64_t max_ranges, int64_t limit,
    int32_t *out_ranges, int32_t *out_nranges, uint8_t *out_overflow) {
    for (int64_t row = 0; row < n_rows; ++row) {
        out_nranges[row] = 0;
        out_overflow[row] = 0;
        int32_t len = lengths[row];
        int32_t root = roots[row];
        if (len < 0 || root < 0) continue;
        Walker w;
        w.node_tab = node_tab;
        w.edge_tab = edge_tab;
        w.n_buckets = n_buckets;
        w.probe_len = probe_len;
        w.child_list = child_list;
        w.kinds = tok_kind + row * width;
        w.h1s = tok_h1 + row * width;
        w.h2s = tok_h2 + row * width;
        w.n_levels = len;
        w.ranges = out_ranges + row * max_ranges * 2;
        w.max_ranges = max_ranges;
        w.limit = limit;
        w.walk(root, 0);
        out_nranges[row] = (int32_t)w.n_ranges;
        out_overflow[row] = w.range_overflow ? 1 : 0;
    }
}

}  // extern "C"
