// Interval -> slot-id expansion (the host half of the route-materializing
// walk): writes start..start+count-1 for every (start, count) pair into
// one flat int32 vector. Pure sequential stores — memory-bandwidth-bound,
// ~15x the numpy repeat/arange chain this replaces (measured 2.9s ->
// ~0.2s for a 144M-slot c2 batch), which matters because host expansion
// runs serially against the device pipeline in the e2e serving loop.

#include <cstdint>

extern "C" {

// Expand a [rows, lanes, 2] interval grid (the walk_routes output shape);
// fills row_totals[r] = slots written for row r and returns the total
// (the caller asserts it against its own count sum).
int64_t expand_grid(const int32_t *grid, int64_t rows, int64_t lanes,
                    int32_t *out, int64_t *row_totals) {
    int64_t w = 0;
    for (int64_t r = 0; r < rows; ++r) {
        int64_t before = w;
        const int32_t *row = grid + r * lanes * 2;
        for (int64_t l = 0; l < lanes; ++l) {
            int32_t start = row[l * 2];
            int32_t count = row[l * 2 + 1];
            for (int32_t j = 0; j < count; ++j) out[w++] = start + j;
        }
        row_totals[r] = w - before;
    }
    return w;
}

}  // extern "C"
