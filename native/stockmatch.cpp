// Stock-CPU baseline proxy: a faithful C++ re-implementation of the
// reference dist-worker's route-match hot loop, used ONLY to measure the
// "stock broker on this box's CPU" baseline that bench.py divides by.
//
// The image ships no JVM (java/mvnw cannot run), so the reference's own
// JMH harnesses cannot execute here. This binary re-creates the exact
// algorithm of
//   bifromq-dist/bifromq-dist-worker/src/main/java/org/apache/bifromq/
//     dist/worker/cache/TenantRouteMatcher.java:68 (matchAll: per-batch
//     topic trie + sorted route sweep with the probe-20 seek heuristic)
//   bifromq-dist/bifromq-dist-coproc-proto/src/main/java/org/apache/
//     bifromq/dist/trie/TopicFilterIterator.java:38 (expansion-set
//     iterator: seek/next over the virtual filter trie)
//   .../trie/{N,S,M}TopicFilterTrieNode.java (normal/"+"/"#" nodes)
//   .../trie/TopicTrieNode.java (per-batch topic trie, $-topics not
//     wildcard-matchable at the first level)
// in C++ with these *stock-favoring* simplifications (each makes the
// baseline FASTER than the real Java broker, so the vs_baseline multiple
// we report is conservative):
//   - routes live in a sorted in-memory vector (lower_bound seek) instead
//     of RocksDB; no proto decode per entry (buildMatchRoute skipped)
//   - matches accumulate into flat per-topic counters instead of
//     MatchedRoutes object graphs
//   - no fan-out cap bookkeeping, no event collector, no timers
//   - C++ with -O3 vs JIT'd Java
// Java's String.compareTo is UTF-16 code-unit order; level names here are
// ASCII so byte order is identical.
//
// Usage: stockmatch <routes_file> <topics_file> <batch> <iters>
//   routes_file: one topic filter per line (levels '/'-joined)
//   topics_file: one concrete topic per line
// Prints one JSON line: topics/s over the timed sweep plus cross-check
// totals (total matched route entries) that tests compare against the
// repo's own oracle/device matcher.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

const std::string NUL = std::string(1, '\0');   // TopicConst.NUL
const std::string SINGLE = "+";
const std::string MULTI = "#";

// ---------------------------------------------------------------------------
// Per-batch topic trie (TopicTrieNode.java)
// ---------------------------------------------------------------------------
struct TopicTrieNode {
    std::string level_name;
    bool wildcard_matchable = false;
    std::map<std::string, TopicTrieNode *> children;
    // instance ids of every probe-batch topic that lands on this node:
    // DUPLICATE topics are distinct instances — each needs its route set
    // delivered, so matched_entries credits every instance (the earlier
    // last-writer-wins int dropped duplicates and undercounted the stock
    // side ~2x on Zipf probe streams)
    std::vector<int> topic_ids;

    bool is_user_topic() const { return !topic_ids.empty(); }
};

struct TopicTrieArena {
    std::vector<std::unique_ptr<TopicTrieNode>> nodes;
    TopicTrieNode *make(const std::string &name, bool wm) {
        nodes.emplace_back(new TopicTrieNode());
        nodes.back()->level_name = name;
        nodes.back()->wildcard_matchable = wm;
        return nodes.back().get();
    }
};

// TopicTrieNode.Builder.addChild (non-global: first level of a $-topic is
// not wildcard matchable)
void add_topic(TopicTrieArena &arena, TopicTrieNode *root,
               const std::vector<std::string> &levels, int topic_id) {
    TopicTrieNode *node = root;
    for (size_t i = 0; i < levels.size(); ++i) {
        bool wm = i > 0 || levels[i].rfind('$', 0) != 0;
        auto it = node->children.find(levels[i]);
        if (it == node->children.end()) {
            TopicTrieNode *child = arena.make(levels[i], wm);
            it = node->children.emplace(levels[i], child).first;
        }
        node = it->second;
    }
    node->topic_ids.push_back(topic_id);
}

// ---------------------------------------------------------------------------
// Virtual filter-trie nodes ({N,S,M}TopicFilterTrieNode.java)
// ---------------------------------------------------------------------------
struct FilterNode {
    enum Kind { N, S, M } kind = N;
    FilterNode *parent = nullptr;
    std::string level_name;
    // child iteration state (names sorted; pos==-1 <=> invalid child)
    std::vector<std::string> sub_level_names;
    std::map<std::string, std::vector<TopicTrieNode *>> sub_topic_nodes;
    std::vector<TopicTrieNode *> sub_wildcard_matchable;
    std::vector<TopicTrieNode *> backing_topics;
    int pos = -1;

    bool at_valid_child() const {
        return pos >= 0 && pos < (int)sub_level_names.size();
    }
    void seek_child(const std::string &name) {  // ceiling
        auto it = std::lower_bound(sub_level_names.begin(),
                                   sub_level_names.end(), name);
        pos = it == sub_level_names.end() ? -1
                                          : int(it - sub_level_names.begin());
    }
    void next_child() {
        if (pos >= 0) {
            ++pos;
            if (pos >= (int)sub_level_names.size()) pos = -1;
        }
    }
};

void collect_topics(TopicTrieNode *node, std::set<TopicTrieNode *> &out) {
    if (node->is_user_topic()) out.insert(node);
    for (auto &kv : node->children) collect_topics(kv.second, out);
}

struct FilterArena {
    std::vector<std::unique_ptr<FilterNode>> pool;
    std::vector<FilterNode *> free_list;  // node pooling, like the
                                          // reference's Caffeine POOL
    FilterNode *alloc() {
        if (!free_list.empty()) {
            FilterNode *n = free_list.back();
            free_list.pop_back();
            n->sub_level_names.clear();
            n->sub_topic_nodes.clear();
            n->sub_wildcard_matchable.clear();
            n->backing_topics.clear();
            n->pos = -1;
            return n;
        }
        pool.emplace_back(new FilterNode());
        return pool.back().get();
    }
    void release(FilterNode *n) { free_list.push_back(n); }
};

// shared N/S init: children = merged children of the sibling set; "#"
// child if backing topics or wildcard-matchable children exist; "+" child
// if wildcard-matchable children exist
void init_children(FilterNode *n,
                   const std::vector<TopicTrieNode *> &siblings,
                   bool only_wildcard_matchable_backing) {
    std::set<std::string> names;
    for (TopicTrieNode *s : siblings) {
        if (s->is_user_topic()) n->backing_topics.push_back(s);
        for (auto &kv : s->children) {
            TopicTrieNode *sub = kv.second;
            if (sub->wildcard_matchable)
                n->sub_wildcard_matchable.push_back(sub);
            n->sub_topic_nodes[sub->level_name].push_back(sub);
            names.insert(sub->level_name);
        }
    }
    (void)only_wildcard_matchable_backing;
    if (!n->backing_topics.empty()) names.insert(MULTI);
    if (!n->sub_wildcard_matchable.empty()) {
        names.insert(MULTI);
        names.insert(SINGLE);
    }
    n->sub_level_names.assign(names.begin(), names.end());
    n->seek_child("");
}

FilterNode *make_n(FilterArena &a, FilterNode *parent,
                   const std::string &level_name,
                   const std::vector<TopicTrieNode *> &siblings) {
    FilterNode *n = a.alloc();
    n->kind = FilterNode::N;
    n->parent = parent;
    n->level_name = level_name;
    init_children(n, siblings, false);
    return n;
}

FilterNode *make_s(FilterArena &a, FilterNode *parent,
                   const std::vector<TopicTrieNode *> &siblings) {
    FilterNode *n = a.alloc();
    n->kind = FilterNode::S;
    n->parent = parent;
    n->level_name = SINGLE;
    init_children(n, siblings, true);
    return n;
}

FilterNode *make_m(FilterArena &a, FilterNode *parent,
                   const std::vector<TopicTrieNode *> &siblings) {
    FilterNode *n = a.alloc();
    n->kind = FilterNode::M;
    n->parent = parent;
    n->level_name = MULTI;
    std::set<TopicTrieNode *> topics;  // MTopicFilterTrieNode.init: parent
    if (parent)                        // backing + whole sibling subtrees
        topics.insert(parent->backing_topics.begin(),
                      parent->backing_topics.end());
    for (TopicTrieNode *s : siblings) collect_topics(s, topics);
    n->backing_topics.assign(topics.begin(), topics.end());
    // M node has no children (leaf in the filter trie)
    return n;
}

FilterNode *child_node(FilterArena &a, FilterNode *n) {
    const std::string &name = n->sub_level_names[n->pos];
    if (name == MULTI) return make_m(a, n, n->sub_wildcard_matchable);
    if (name == SINGLE) return make_s(a, n, n->sub_wildcard_matchable);
    return make_n(a, n, name, n->sub_topic_nodes[name]);
}

// ---------------------------------------------------------------------------
// Expansion-set iterator (TopicFilterIterator.java — seek/next subset used
// by matchAll; seekPrev/prev are not on the matchAll path)
// ---------------------------------------------------------------------------
struct ExpansionIterator {
    FilterArena arena;
    TopicTrieNode *root = nullptr;
    std::vector<FilterNode *> stack;

    void pop_release() {
        arena.release(stack.back());
        stack.pop_back();
    }
    void clear() {
        while (!stack.empty()) pop_release();
    }
    bool valid() const { return !stack.empty(); }

    void init(TopicTrieNode *r) {
        root = r;
        seek({});
    }

    void seek(const std::vector<std::string> &filter_levels) {
        clear();
        stack.push_back(make_n(arena, nullptr, root->level_name, {root}));
        int i = -1;
        bool drained = false;
        while (!stack.empty() && i < (int)filter_levels.size()) {
            const std::string &to_seek = i == -1 ? NUL : filter_levels[i];
            ++i;
            FilterNode *node = stack.back();
            int cmp = to_seek.compare(node->level_name);
            if (cmp < 0) {
                break;
            } else if (cmp == 0) {
                if (i == (int)filter_levels.size()) break;
                node->seek_child(filter_levels[i]);
                if (node->at_valid_child()) {
                    stack.push_back(child_node(arena, node));
                } else {
                    pop_release();
                    if (stack.empty()) break;
                    bool descended = false;
                    while (!stack.empty()) {
                        FilterNode *parent = stack.back();
                        parent->next_child();
                        if (parent->at_valid_child()) {
                            stack.push_back(child_node(arena, parent));
                            descended = true;
                            break;
                        }
                        pop_release();
                    }
                    if (descended) break;
                }
            } else {
                // to_seek > level name: nothing >= filter exists
                clear();
                drained = true;
            }
        }
        (void)drained;
        // descend to the least filter with backing topics
        while (!stack.empty()) {
            FilterNode *node = stack.back();
            if (node->backing_topics.empty()) {
                // invariant from the reference: a childless filter node
                // always has backing topics, so at_valid_child holds here
                stack.push_back(child_node(arena, node));
            } else {
                break;
            }
        }
    }

    void next() {
        while (!stack.empty()) {
            FilterNode *node = stack.back();
            if (node->at_valid_child()) {
                FilterNode *sub = child_node(arena, node);
                stack.push_back(sub);
                if (!sub->backing_topics.empty()) break;
            } else {
                pop_release();
                if (!stack.empty()) stack.back()->next_child();
            }
        }
    }

    // key(): current filter (prefix of non-NUL ancestor level names + own)
    std::vector<std::string> key() const {
        std::vector<std::string> out;
        for (FilterNode *n : stack)
            if (n->level_name != NUL) out.push_back(n->level_name);
        return out;
    }

    const std::vector<TopicTrieNode *> &value_topics() const {
        return stack.back()->backing_topics;
    }
};

// ---------------------------------------------------------------------------
// matchAll (TenantRouteMatcher.java:68) over a sorted in-memory route set
// ---------------------------------------------------------------------------
struct MatchStats {
    uint64_t matched_entries = 0;  // (route entry, topic) pairs added
    uint64_t seeks = 0;
    uint64_t probes = 0;
};

void match_all(const std::vector<std::vector<std::string>> &routes,
               const std::vector<std::vector<std::string>> &topics,
               size_t begin, size_t end, std::vector<uint64_t> &per_topic,
               MatchStats &stats) {
    TopicTrieArena arena;
    TopicTrieNode *root = arena.make(NUL, false);
    for (size_t t = begin; t < end; ++t)
        add_topic(arena, root, topics[t], (int)t);

    ExpansionIterator exp;
    exp.init(root);
    if (!exp.valid()) return;

    // matchedTopicFilters memo: filter -> topic ids
    std::unordered_map<std::string, std::vector<int>> memo;
    auto memo_key = [](const std::vector<std::string> &levels) {
        std::string k;
        for (const auto &l : levels) {
            k += l;
            k += '\0';
        }
        return k;
    };

    size_t itr = 0;  // route iterator (sorted); seek == lower_bound
    ++stats.seeks;
    int probe = 0;
    while (itr < routes.size()) {
        const std::vector<std::string> &filter = routes[itr];
        auto mit = memo.find(memo_key(filter));
        if (mit == memo.end()) {
            exp.seek(filter);
            ++stats.seeks;
            if (!exp.valid()) {
                if (std::getenv("STOCKMATCH_DEBUG")) {
                    std::string f;
                    for (auto &l : filter) { f += l; f += '/'; }
                    std::fprintf(stderr,
                                 "DRAIN at itr=%zu/%zu filter=%s\n",
                                 itr, routes.size(), f.c_str());
                }
                break;  // no more filters can match
            }
            std::vector<std::string> to_match = exp.key();
            if (to_match == filter) {
                std::vector<int> ids;
                for (TopicTrieNode *n : exp.value_topics()) {
                    for (int id : n->topic_ids) {
                        per_topic[id] += 1;
                        ++stats.matched_entries;
                        ids.push_back(id);
                    }
                }
                memo.emplace(memo_key(filter), std::move(ids));
                ++itr;
                probe = 0;
            } else if (probe++ < 20) {
                // next() is much cheaper than seek(): probe the following
                // 20 route entries (TenantRouteMatcher.java:129)
                ++itr;
                ++stats.probes;
            } else {
                itr = std::lower_bound(routes.begin(), routes.end(),
                                       to_match) -
                      routes.begin();
                ++stats.seeks;
            }
        } else {
            ++itr;
            for (int id : mit->second) {
                per_topic[id] += 1;
                ++stats.matched_entries;
            }
        }
    }
}

std::vector<std::string> split_levels(const std::string &line) {
    std::vector<std::string> out;
    size_t start = 0;
    for (size_t i = 0; i <= line.size(); ++i) {
        if (i == line.size() || line[i] == '/') {
            out.push_back(line.substr(start, i - start));
            start = i + 1;
        }
    }
    return out;
}

}  // namespace

int main(int argc, char **argv) {
    if (argc != 5) {
        std::fprintf(stderr,
                     "usage: %s <routes_file> <topics_file> <batch> <iters>\n",
                     argv[0]);
        return 2;
    }
    const char *routes_path = argv[1];
    const char *topics_path = argv[2];
    size_t batch = std::strtoul(argv[3], nullptr, 10);
    size_t iters = std::strtoul(argv[4], nullptr, 10);

    std::vector<std::vector<std::string>> routes;
    {
        std::ifstream f(routes_path);
        std::string line;
        while (std::getline(f, line))
            if (!line.empty()) routes.push_back(split_levels(line));
    }
    // KV order: escaped filter keys sort like level-list lexicographic order
    std::sort(routes.begin(), routes.end());

    std::vector<std::vector<std::string>> topics;
    {
        std::ifstream f(topics_path);
        std::string line;
        while (std::getline(f, line))
            if (!line.empty()) topics.push_back(split_levels(line));
    }
    if (topics.size() < batch) {
        std::fprintf(stderr, "not enough topics (%zu < %zu)\n", topics.size(),
                     batch);
        return 2;
    }

    std::vector<uint64_t> per_topic(topics.size(), 0);
    MatchStats warm;
    match_all(routes, topics, 0, std::min(batch, topics.size()), per_topic,
              warm);  // warmup (page in, allocate pools)

    std::fill(per_topic.begin(), per_topic.end(), 0);
    MatchStats stats;
    auto t0 = std::chrono::steady_clock::now();
    size_t done = 0;
    for (size_t it = 0; it < iters; ++it) {
        size_t begin = (it * batch) % (topics.size() - batch + 1);
        match_all(routes, topics, begin, begin + batch, per_topic, stats);
        done += batch;
    }
    auto t1 = std::chrono::steady_clock::now();
    double secs = std::chrono::duration<double>(t1 - t0).count();

    std::printf(
        "{\"topics_per_s\": %.1f, \"batch\": %zu, \"iters\": %zu, "
        "\"routes\": %zu, \"matched_entries\": %llu, "
        "\"matched_routes_per_s\": %.1f, \"seeks\": %llu, \"probes\": %llu, "
        "\"elapsed_s\": %.3f}\n",
        done / secs, batch, iters, routes.size(),
        (unsigned long long)stats.matched_entries,
        stats.matched_entries / secs, (unsigned long long)stats.seeks,
        (unsigned long long)stats.probes, secs);
    // STOCKMATCH_DUMP=<path>: per-topic match counts from the timed
    // passes (parity diagnostics vs the oracle — tests/test_stockmatch)
    if (const char *dump = std::getenv("STOCKMATCH_DUMP")) {
        std::ofstream df(dump);
        for (size_t i = 0; i < per_topic.size(); ++i)
            df << per_topic[i] << "\n";
    }
    return 0;
}
