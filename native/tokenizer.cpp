// Native topic tokenizer: the host-side feeder of the TPU match kernel.
//
// The serving hot path hashes every level of every PUBLISH topic into the
// probe batch (models/automaton.py tokenize()). Pure-Python tokenization
// tops out ~140K topics/s — below the device walk's throughput — so this is
// the same move the reference makes with Netty/RocksDB native parts
// (SURVEY.md §2.9): keep the per-byte work in C++.
//
// Contains a compact BLAKE2b (RFC 7693) with digest_length=8 and a 16-byte
// salt in the parameter block, bit-exact with Python's
// hashlib.blake2b(level, digest_size=8, salt=salt8) where salt8 is the
// 8-byte little-endian salt zero-padded to 16 (hashlib pads too).
//
// C ABI for ctypes. Thread-safe (no globals).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

static const uint64_t IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

static inline uint64_t load64(const uint8_t* p) {
    uint64_t v;
    memcpy(&v, p, 8);
    return v;  // little-endian hosts only (x86/ARM)
}

#define G(a, b, c, d, x, y)                \
    do {                                   \
        a = a + b + (x);                   \
        d = rotr64(d ^ a, 32);             \
        c = c + d;                         \
        b = rotr64(b ^ c, 24);             \
        a = a + b + (y);                   \
        d = rotr64(d ^ a, 16);             \
        c = c + d;                         \
        b = rotr64(b ^ c, 63);             \
    } while (0)

static void compress(uint64_t h[8], const uint8_t block[128], uint64_t t,
                     bool last) {
    uint64_t m[16], v[16];
    for (int i = 0; i < 16; i++) m[i] = load64(block + 8 * i);
    for (int i = 0; i < 8; i++) v[i] = h[i];
    for (int i = 0; i < 8; i++) v[8 + i] = IV[i];
    v[12] ^= t;        // t0 (inputs < 2^64 bytes)
    if (last) v[14] = ~v[14];
    for (int r = 0; r < 12; r++) {
        const uint8_t* s = SIGMA[r];
        G(v[0], v[4], v[8], v[12], m[s[0]], m[s[1]]);
        G(v[1], v[5], v[9], v[13], m[s[2]], m[s[3]]);
        G(v[2], v[6], v[10], v[14], m[s[4]], m[s[5]]);
        G(v[3], v[7], v[11], v[15], m[s[6]], m[s[7]]);
        G(v[0], v[5], v[10], v[15], m[s[8]], m[s[9]]);
        G(v[1], v[6], v[11], v[12], m[s[10]], m[s[11]]);
        G(v[2], v[7], v[8], v[13], m[s[12]], m[s[13]]);
        G(v[3], v[4], v[9], v[14], m[s[14]], m[s[15]]);
    }
    for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[8 + i];
}

// blake2b(digest=8, salt=salt16) of msg; returns the 8 digest bytes as u64
static uint64_t blake2b8(const uint8_t* msg, size_t len,
                         const uint8_t salt16[16]) {
    uint64_t h[8];
    uint8_t param[64] = {0};
    param[0] = 8;   // digest_length
    param[2] = 1;   // fanout
    param[3] = 1;   // depth
    memcpy(param + 32, salt16, 16);
    for (int i = 0; i < 8; i++) h[i] = IV[i] ^ load64(param + 8 * i);
    uint8_t block[128];
    size_t off = 0;
    // full (non-final) blocks
    while (len - off > 128) {
        compress(h, msg + off, (uint64_t)(off + 128), false);
        off += 128;
    }
    size_t rem = len - off;
    memset(block, 0, 128);
    if (rem) memcpy(block, msg + off, rem);
    compress(h, block, (uint64_t)len, true);
    return h[0];  // first 8 little-endian digest bytes
}

// Tokenize rows [lo, hi) of a batch of '/'-separated topics into
// fixed-shape probe arrays.
//
// data/offsets: topic i is the UTF-8 bytes data[offsets[i]:offsets[i+1]].
// Outputs are row-major [batch, width] (width = max_levels + 1) int32 for
// tok_h1/tok_h2 (+ tok_kind in filter mode), plus per-row lengths, roots
// and sys flags. Rows with > max_levels levels are left as padding
// (length -1) for the caller's host-fallback path.
//
// filter_mode != 0 treats '+'/'#' levels as wildcard kinds (retained-probe
// tokenization) and skips their hashing; kind codes match automaton.py
// (0=literal, 1='+', 2='#'). tok_kind may be null when filter_mode == 0.
static void tok_rows(const uint8_t* data, const int32_t* offsets, int lo,
                     int hi, const int32_t* roots, int max_levels,
                     const uint8_t salt16[16], int filter_mode,
                     int32_t* tok_h1, int32_t* tok_h2, int32_t* tok_kind,
                     int32_t* lengths, int32_t* root_out, uint8_t* sys_mask,
                     int width) {
    for (int i = lo; i < hi; i++) {
        const uint8_t* s = data + offsets[i];
        int tlen = offsets[i + 1] - offsets[i];
        // count levels ('/' separators + 1)
        int n_levels = 1;
        for (int j = 0; j < tlen; j++)
            if (s[j] == '/') n_levels++;
        if (n_levels > max_levels) continue;  // padding row
        lengths[i] = n_levels;
        root_out[i] = roots[i];
        if (tlen > 0 && s[0] == '$') sys_mask[i] = 1;
        int32_t* h1 = tok_h1 + (int64_t)i * width;
        int32_t* h2 = tok_h2 + (int64_t)i * width;
        int32_t* kd = tok_kind ? tok_kind + (int64_t)i * width : nullptr;
        int lvl = 0, start = 0;
        for (int j = 0; j <= tlen; j++) {
            if (j == tlen || s[j] == '/') {
                const uint8_t* lp = s + start;
                int ll = j - start;
                if (filter_mode && ll == 1 && lp[0] == '+') {
                    kd[lvl] = 1;
                } else if (filter_mode && ll == 1 && lp[0] == '#') {
                    kd[lvl] = 2;
                } else {
                    uint64_t d = blake2b8(lp, (size_t)ll, salt16);
                    h1[lvl] = (int32_t)(uint32_t)(d & 0xFFFFFFFFu);
                    h2[lvl] = (int32_t)(uint32_t)(d >> 32);
                }
                lvl++;
                start = j + 1;
            }
        }
    }
}

}  // namespace

extern "C" {

// Serial tokenization (original ABI); see tok_rows for the contract.
void tok_topics(const uint8_t* data, const int32_t* offsets, int n_topics,
                const int32_t* roots, int max_levels, uint64_t salt,
                int filter_mode, int32_t* tok_h1, int32_t* tok_h2,
                int32_t* tok_kind, int32_t* lengths, int32_t* root_out,
                uint8_t* sys_mask, int width) {
    uint8_t salt16[16] = {0};
    memcpy(salt16, &salt, 8);  // little-endian, zero-padded like hashlib
    tok_rows(data, offsets, 0, n_topics, roots, max_levels, salt16,
             filter_mode, tok_h1, tok_h2, tok_kind, lengths, root_out,
             sys_mask, width);
}

// Multithreaded tokenization: rows are independent and each thread writes a
// disjoint row range, so the split is embarrassingly parallel. ctypes
// releases the GIL for the whole call. n_threads <= 1 degrades to serial.
void tok_topics_mt(const uint8_t* data, const int32_t* offsets, int n_topics,
                   const int32_t* roots, int max_levels, uint64_t salt,
                   int filter_mode, int32_t* tok_h1, int32_t* tok_h2,
                   int32_t* tok_kind, int32_t* lengths, int32_t* root_out,
                   uint8_t* sys_mask, int width, int n_threads) {
    uint8_t salt16[16] = {0};
    memcpy(salt16, &salt, 8);
    int hw = (int)std::thread::hardware_concurrency();
    int nt = std::min({n_threads > 0 ? n_threads : (hw > 0 ? hw : 1),
                       n_topics, 64});
    if (nt <= 1) {
        tok_rows(data, offsets, 0, n_topics, roots, max_levels, salt16,
                 filter_mode, tok_h1, tok_h2, tok_kind, lengths, root_out,
                 sys_mask, width);
        return;
    }
    std::vector<std::thread> threads;
    threads.reserve(nt);
    int chunk = (n_topics + nt - 1) / nt;
    for (int t = 0; t < nt; t++) {
        int lo = t * chunk;
        int hi = std::min(n_topics, lo + chunk);
        if (lo >= hi) break;
        threads.emplace_back(tok_rows, data, offsets, lo, hi, roots,
                             max_levels, salt16, filter_mode, tok_h1, tok_h2,
                             tok_kind, lengths, root_out, sys_mask, width);
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"
