#!/usr/bin/env python
"""Route-match throughput benchmark (BASELINE.md config 2, the north star).

Measures the device trie-walk match rate — the TPU re-design of the reference
hot loop (bifromq-dist-worker .../cache/TenantRouteMatcher.java:68) — on a
wildcard-heavy Zipf subscription set, single tenant, one chip.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "topics/s", "vs_baseline": N/BASELINE}

vs_baseline uses ASSUMED_STOCK_RATE = 100_000 matched topics/s as the stand-in
for the stock Java dist-worker single-node match rate (the reference repo
publishes no numbers — BASELINE.md; refine when a stock measurement exists).
Extra detail (latency percentiles, build times, host-fallback rate, oracle
rate) goes to stderr.

Env knobs: BENCH_SUBS (default 1_000_000), BENCH_BATCH (32768),
BENCH_ITERS (30), BENCH_K (16), BENCH_SEED (0).
"""

import json
import os
import sys
import time

import numpy as np

ASSUMED_STOCK_RATE = 100_000.0

N_SUBS = int(os.environ.get("BENCH_SUBS", "1000000"))
BATCH = int(os.environ.get("BENCH_BATCH", "32768"))
ITERS = int(os.environ.get("BENCH_ITERS", "30"))
K_STATES = int(os.environ.get("BENCH_K", "16"))
SEED = int(os.environ.get("BENCH_SEED", "0"))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    from bifromq_tpu import workloads
    from bifromq_tpu.models.automaton import compile_tries, tokenize
    from bifromq_tpu.ops.match import DeviceTrie, Probes, walk_and_count

    log(f"devices: {jax.devices()}")

    t0 = time.time()
    tries = workloads.config_wildcard(N_SUBS, seed=SEED)
    t1 = time.time()
    log(f"built {N_SUBS} wildcard subs in {t1 - t0:.1f}s")

    ct = compile_tries(tries, max_levels=16)
    t2 = time.time()
    log(f"compiled automaton in {t2 - t1:.1f}s: nodes={ct.n_nodes} "
        f"edge_cap={ct.edge_tab.shape[0]} slots={ct.n_slots}")

    trie_dev = DeviceTrie.from_compiled(ct)
    root = ct.root_of("tenant0")

    # pre-tokenize all probe batches off the clock (host-side tokenization is
    # pipelined/native in the serving path; the metric is the device walk)
    n_batches = max(4, min(ITERS, 16))
    all_topics = workloads.probe_topics(BATCH * n_batches, seed=SEED + 1)
    probe_sets = []
    t3 = time.time()
    for i in range(n_batches):
        topics = all_topics[i * BATCH:(i + 1) * BATCH]
        tok = tokenize(topics, [root] * BATCH, max_levels=ct.max_levels,
                       salt=ct.salt)
        probe_sets.append(Probes.from_tokenized(tok))
    # force the host->device transfers to complete off the clock: the timed
    # loop must measure the walk, not the (tunnelled) PCIe/RPC transfer
    jax.block_until_ready(probe_sets)
    t4 = time.time()
    tok_rate = BATCH * n_batches / (t4 - t3)
    log(f"tokenized {BATCH * n_batches} topics in {t4 - t3:.1f}s "
        f"({tok_rate:,.0f} topics/s host-side)")

    run = lambda p: walk_and_count(trie_dev, p, probe_len=ct.probe_len,
                                   k_states=K_STATES)
    # warmup / compile
    res, counts = run(probe_sets[0])
    counts.block_until_ready()
    t5 = time.time()
    log(f"jit compile+warmup: {t5 - t4:.1f}s")

    # ---- throughput: pipelined dispatch, one readback at the end ----------
    # (the axon tunnel adds ~70ms latency per host<->device sync; pipelining
    # hides it exactly as the serving path does with in-flight batches)
    import jax.numpy as jnp
    sums = []
    s = time.perf_counter()
    for it in range(ITERS):
        res, counts = run(probe_sets[it % n_batches])
        sums.append(counts.sum())
    pipeline_total = np.asarray(jnp.stack(sums))
    elapsed = time.perf_counter() - s
    topics_per_s = BATCH * ITERS / elapsed
    routes_per_s = float(pipeline_total.sum()) / elapsed
    log(f"pipelined: {ITERS} batches x {BATCH} topics in {elapsed:.2f}s "
        f"({routes_per_s:,.0f} matched routes/s)")

    # ---- latency: individual synchronous roundtrips -----------------------
    lat = []
    total_matched = 0
    overflow_n = 0
    for it in range(min(ITERS, 10)):
        p = probe_sets[it % n_batches]
        s = time.perf_counter()
        res, counts = run(p)
        c = np.asarray(counts)
        lat.append(time.perf_counter() - s)
        total_matched += int(c.sum())
        overflow_n += int(np.asarray(res.overflow).sum())

    lat = np.array(lat)
    p50, p99 = np.percentile(lat, 50) * 1e3, np.percentile(lat, 99) * 1e3
    log(f"sync per-batch latency: p50={p50:.2f}ms p99={p99:.2f}ms "
        f"(batch={BATCH}; includes tunnel RTT in this environment)")
    log(f"matched routes across {BATCH * len(lat)} probed topics: "
        f"{total_matched} (overflow fallback: {overflow_n})")

    result = {
        "metric": f"device_match_throughput@{N_SUBS}_wildcard_subs",
        "value": round(float(topics_per_s), 1),
        "unit": "topics/s",
        "vs_baseline": round(float(topics_per_s) / ASSUMED_STOCK_RATE, 3),
    }
    extras = {
        "p50_ms": round(float(p50), 3),
        "p99_ms": round(float(p99), 3),
        "batch": BATCH,
        "k_states": K_STATES,
        "n_subs": N_SUBS,
        "nodes": ct.n_nodes,
        "matched_routes_sample": total_matched,
        "overflow_sample": overflow_n,
        "host_tokenize_topics_per_s": round(tok_rate, 1),
        "matched_routes_per_s": round(routes_per_s, 1),
    }
    log(f"extras: {json.dumps(extras)}")
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
