#!/usr/bin/env python
"""Route-match throughput benchmarks for the five BASELINE.md configs.

The device kernel under test is the TPU re-design of the reference hot loop
(bifromq-dist-worker .../cache/TenantRouteMatcher.java:68 joined with
.../trie/TopicFilterIterator.java:38): level-packed automaton + fixed-shape
NFA walk (ops/match.py), retained-mode roles-swapped walk (ops/retained.py),
host tokenization in C++ (native/tokenizer.cpp).

Prints ONE JSON line on stdout — the headline config-2 number:
  {"metric": ..., "value": N, "unit": "routes/s", "vs_baseline": N/BASELINE}
All five configs' numbers go to stderr in the extras dict.

HEADLINE METRIC (VERDICT r4 #1): end-to-end MATCHED ROUTES per second —
tokenize + device interval walk + readback + vectorized expansion to
materialized per-topic route-slot arrays. The divisor is the MEASURED stock
baseline (bench_results/stock_baseline.json: native/stockmatch.cpp, the
faithful C++ port of the reference TenantRouteMatcher.matchAll cache-miss
loop, cross-checked vs the oracle). Comparison basis: KERNEL-vs-KERNEL,
cache-off, 1-core stock — the stock side omits the reference's
TenantRouteCache layer and its DistMatchParallelism workers; both sides
materialize per-topic route-entry vectors and neither does delivery I/O.
If stock_baseline.json is absent the old ASSUMED_STOCK_RATE=100K topics/s
stand-in is used and labeled as assumed.

RESILIENCE (VERDICT r4 #5): if device init fails through the probe window,
the bench emits the last-known-good result (bench_results/last_good.json)
marked "stale": true with its timestamp instead of rc=1 — three rounds of
driver records were lost to tunnel flaps at snapshot time.

The committed throughput is HONEST end-to-end device serving rate: pipelined
dispatch (the axon tunnel adds ~70ms per sync; serving pipelines exactly the
same way), host-fallback cost for overflowed topics folded in at the
measured oracle rate.

MATCH-RESULT CACHE (ISSUE 4): ``--match-cache=on|off`` (or env
BIFROMQ_MATCH_CACHE) A/Bs the TenantMatchCache plane; config "6" runs the
dedicated repeated-vs-unique-topic A/B through TpuMatcher.match_batch and
the broker config prints hit rate + dedup ratio next to the stage
breakdown.

DEVICE PIPELINE (ISSUE 6): config "7" A/Bs the sync blocking serve
against the async double-buffered dispatch ring (BENCH_PIPE_SUBS caps
its sub count, BENCH_PIPE_SMALL sets the shallow-queue batch;
BIFROMQ_PIPELINE_DEPTH / BIFROMQ_FUSED_KERNEL steer the pipeline
itself) and reports batch p50/p99 per leg + the dispatch/ready/fetch
stage split. Every run is stamped with device_kind + stale so
CPU-fallback rounds stay comparable; routes-mode reports tunnel RTT
apart from device-kernel time.

SUBSCRIPTION CHURN (ISSUE 9): config "8" runs sustained subscribe/
unsubscribe against a full-size base interleaved with publishes,
measuring single-mutation patch-apply latency (host plan + narrow device
scatter) against the full-rebuild cost, match p99 during churn, the
zero-rebuild/zero-generation-bump window, and exact oracle parity after
the storm (BENCH_CHURN_SUBS / BENCH_CHURN_OPS; persists
bench_results/churn_last.json and stamps record["churn"]).

INGEST BYTE PLANE (ISSUE 11): config "9" A/Bs publish-side topic prep —
per-message python loop vs the contiguous-byte-buffer plane (native C++
/ vectorized numpy) vs the device-side Pallas hash kernel — on the
topic-diversity corpus, checks exact three-way parity, and verifies the
profiler attributes a `tokenize` stage on every device batch
(BENCH_TOK_SUBS sizes its base; every record stamps a "tokenize"
section when config 9 ran).

MIXED MILLION-CLIENT WORKLOAD (ISSUE 13): config "10" executes one
deterministic `workloads.config_mixed` plan — Zipf tenants, QoS mix,
$share worker pools, a >=10k-op retained SET/CLEAR flood against the
PATCHED RetainedIndex (acceptance: ZERO full rebuilds, device scans
byte-identical to the host oracle before/during/after), async wildcard
scans through the retain.scan plane (cache hit-rate on the repeat
pass), publish matching under concurrent session churn, balanced-vs-
random $share election spread, a governed reconnect drain storm
(tenant fairness: the quiet tenants' mean admission wait must not sit
behind tenant0's herd), and the SLO top-k snapshot (BENCH_MIX_CLIENTS
default 100_000 — set 1_000_000 for the paper-scale record;
BENCH_MIX_RETAIN_OPS default 10_000). Stamps record["mixed"].

SHARDED MESH (ISSUE 15): config "11" serves BENCH_MESH_SUBS logical
subscriptions from a BENCH_MESH_REPLICAS x BENCH_MESH_SHARDS device
mesh (CPU: XLA_FLAGS=--xla_force_host_platform_device_count=8) with a
replicated hot tenant, checks per-shard bytes against the
CapacityPlanner.fits prediction, and runs a BENCH_MESH_CHURN_OPS churn
storm through the per-shard patch plane (acceptance: zero rebuilds,
zero generation bumps, exact oracle parity). Stamps record["mesh"].

ELASTIC MESH (ISSUE 17): config "12" live-migrates the Zipf whale
tenant off its hot shard through the begin/copy/ready/cutover/
tombstone ladder while async match batches serve THROUGH the
dual-serve window — migration wall-clock vs the full mesh rebuild,
match p99 during the window, skew before/after, zero rebuilds, zero
generation bumps, exact oracle parity. Stamps record["reshard"].

Env knobs: BENCH_CONFIGS ("1,2,3,4,5" default; "2" = headline only;
"6" = match-cache A/B; "7" = pipeline A/B; "8" = churn/patch;
"9" = ingest byte-plane A/B; "10" = mixed million-client workload;
"11" = sharded mesh serving; "12" = live migration vs mesh rebuild
(BENCH_RESHARD_SUBS 200000, BENCH_RESHARD_SHARDS 8,
BENCH_RESHARD_REPLICAS 1, BENCH_RESHARD_TENANTS 64,
BENCH_RESHARD_CHUNK 256);
BENCH_CACHE_HOT_TOPICS sizes config 6's Zipf pool),
BENCH_SUBS (config-2 subs, default 1_000_000), BENCH_BATCH (16384),
BENCH_ITERS (30), BENCH_K (16), BENCH_SEED (0), BENCH_RETAINED (1_000_000),
BENCH_COMPACTION (sort|scatter), BENCH_INTERVALS (64, route-walk lanes),
BENCH_ROUTES (1 = measure the e2e matched-routes path; 0 = count-only),
BENCH_LATENCY (0; 1 = small-batch latency frontier sweep, B in
BENCH_LATENCY_B default "256,1024,4096"),
BENCH_SHARED_TENANTS (1000), BENCH_SHARED_SUBS (1000), BENCH_MT_TENANTS
(10_000), BENCH_MT_SUBS (1_000_000).
"""

import json
import os
import sys
import time

import numpy as np

ASSUMED_STOCK_RATE = 100_000.0

_REPO = os.path.dirname(os.path.abspath(__file__))
LAST_GOOD_PATH = os.path.join(_REPO, "bench_results", "last_good.json")
STOCK_BASELINE_PATH = os.path.join(_REPO, "bench_results",
                                   "stock_baseline.json")


def load_stock_baseline():
    """Measured stock rates from the C++ proxy run, or the assumed fallback.

    Returns (topics_rate, routes_rate, basis_str). The c2 rates are the
    stock side's BEST cells (B16384 has the higher matched_routes/s; the
    comparison hands the stock side its best operating point per metric).
    """
    try:
        with open(STOCK_BASELINE_PATH) as f:
            sb = json.load(f)
        cells = sb["c2_wildcard_1000000"]["cells"]
        topics = max(c["topics_per_s"] for c in cells.values())
        routes = max(c["matched_routes_per_s"] for c in cells.values())
        return topics, routes, (
            "measured stockmatch.cpp (kernel-vs-kernel, cache-off, 1-core"
            " stock; best stock cell per metric)")
    except (OSError, KeyError, ValueError):
        return ASSUMED_STOCK_RATE, ASSUMED_STOCK_RATE, (
            "ASSUMED 100K/s stand-in (stock_baseline.json missing)")

# --match-cache=on|off A/B flag (ISSUE 4): mapped onto the env knob the
# matcher reads (BIFROMQ_MATCH_CACHE) so every plane in this process —
# TpuMatcher, MeshMatcher, the broker's dist service — follows the mode
for _arg in list(sys.argv[1:]):
    if _arg.startswith("--match-cache="):
        _mode = _arg.split("=", 1)[1].lower()
        if _mode not in ("on", "off"):
            raise SystemExit(f"--match-cache={_mode!r} (use on|off)")
        os.environ["BIFROMQ_MATCH_CACHE"] = "1" if _mode == "on" else "0"
        sys.argv.remove(_arg)

CONFIGS = os.environ.get("BENCH_CONFIGS", "1,2,3,4,5").split(",")
N_SUBS = int(os.environ.get("BENCH_SUBS", "1000000"))
BATCH = int(os.environ.get("BENCH_BATCH", "16384"))
ITERS = int(os.environ.get("BENCH_ITERS", "30"))
K_STATES = int(os.environ.get("BENCH_K", "16"))
SEED = int(os.environ.get("BENCH_SEED", "0"))
N_RETAINED = int(os.environ.get("BENCH_RETAINED", "1000000"))
SHARED_TENANTS = int(os.environ.get("BENCH_SHARED_TENANTS", "1000"))
SHARED_SUBS = int(os.environ.get("BENCH_SHARED_SUBS", "1000"))
MT_TENANTS = int(os.environ.get("BENCH_MT_TENANTS", "10000"))
MT_SUBS = int(os.environ.get("BENCH_MT_SUBS", "1000000"))
# 64 lanes: the c2@1M interval-count distribution measured p99=37 with
# 0.024% overflow at A=64 vs 2.2% at A=32 — and every overflow row costs
# a ~360 topics/s host-oracle re-match, so lane bytes are the cheaper coin
INTERVALS = int(os.environ.get("BENCH_INTERVALS", "64"))
ROUTES_MODE = os.environ.get("BENCH_ROUTES", "1") != "0"
LATENCY_MODE = os.environ.get("BENCH_LATENCY", "0") == "1"
EXPAND_AB_MODE = os.environ.get("BENCH_EXPAND_AB", "1") != "0"


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _compile(tries, *, name, max_levels=16):
    from bifromq_tpu.models.automaton import compile_tries
    from bifromq_tpu.ops.match import DeviceTrie

    t0 = time.time()
    ct = compile_tries(tries, max_levels=max_levels)
    t1 = time.time()
    log(f"[{name}] compiled: nodes={ct.n_nodes} slots={ct.n_slots} "
        f"({t1 - t0:.1f}s)")
    # ISSUE 8: bench builds bypass TpuMatcher, so stamp the compile into
    # the ledger here — the record's compile_ledger must attribute the
    # build that produced the headline table, not come back empty on
    # direct-walk configs (shared derivation with the matcher installs)
    from bifromq_tpu.obs.capacity import record_compile_event
    record_compile_event(ct, reason=f"bench:{name}", duration_s=t1 - t0)
    return ct, DeviceTrie.from_compiled(ct), t1 - t0


def _measure_match(tries, probe_fn, *, name, k_states=K_STATES,
                   iters=ITERS, batch=BATCH, max_levels=16,
                   compiled=None):
    """Compile `tries` (or reuse ``compiled``), probe with batches from
    probe_fn(i) -> queries.

    Returns dict of measured numbers. probe_fn yields (levels_list, tenant)
    pairs resolved against the compiled roots.
    """
    import jax

    from bifromq_tpu.models.automaton import tokenize
    from bifromq_tpu.ops.match import Probes, walk_count_only

    if compiled is None:
        ct, dev, compile_s = _compile(tries, name=name,
                                      max_levels=max_levels)
    else:
        ct, dev, compile_s = compiled
    t0 = time.time()
    t1 = t0 + compile_s

    n_batches = 4
    probe_sets = []
    all_queries = []
    toks = []
    t2 = time.time()
    for i in range(n_batches):
        queries = probe_fn(i, batch)
        all_queries.append(queries)
        toks.append(tokenize([q[0] for q in queries],
                             [ct.root_of(q[1]) for q in queries],
                             max_levels=ct.max_levels, salt=ct.salt,
                             batch=batch))
    t3 = time.time()
    # tokenize-only rate: device_put is timed apart — the axon tunnel
    # uploads at ~1MB/s, which used to drown the tokenizer number (r3
    # measured the tokenizer itself at ~400K topics/s while the old
    # combined metric read 4K)
    tok_rate = batch * n_batches / (t3 - t2)
    probe_sets = [Probes.from_tokenized(t) for t in toks]
    # block_until_ready is a NO-OP on the axon tunnel backend — only a
    # readback truly synchronizes (verify-skill gotcha; re-confirmed by
    # bisection: an unsynced warmup left jit compilation inside the timed
    # loop, 78 vs 10.8 ms/iter). Read back a slice of EVERY array of every
    # set so no in-flight upload bleeds into the warmup number.
    for p in probe_sets:
        for a in (p.tok_h1, p.tok_h2, p.lengths, p.roots, p.sys_mask):
            np.asarray(a[:1])
    t4u = time.time()
    upload_s = t4u - t3

    compaction = os.environ.get("BENCH_COMPACTION", "sort")
    if compaction not in ("sort", "scatter"):
        raise ValueError(f"BENCH_COMPACTION={compaction!r} "
                         "(must be sort|scatter)")
    run = lambda p: walk_count_only(dev, p, probe_len=ct.probe_len,
                                    k_states=k_states,
                                    compaction=compaction)

    for p in probe_sets:
        np.asarray(run(p)[0])  # true sync per set (see note above)
    t4 = time.time()
    log(f"[{name}] warmup+jit {t4 - t4u:.1f}s; probe upload {upload_s:.1f}s; "
        f"host tokenize {tok_rate:,.0f} topics/s")

    # ---- pipelined throughput: one readback at the end --------------------
    # fire-and-forget dispatch, sync once on the LAST call's output. On the
    # axon tunnel anything else collapses the pipeline: device scalars
    # transfer eagerly (~70ms RTT each), retained per-iter buffers cost a
    # serialized RTT each at readback, and a loop-carried accumulator
    # serializes dispatch (measured 157/225/113 ms/iter respectively vs
    # 10.7 ms/iter for this shape).
    s = time.perf_counter()
    for it in range(iters - 1):
        run(probe_sets[it % n_batches])
    cnt_last, ovf_last = run(probe_sets[(iters - 1) % n_batches])
    np.asarray(cnt_last)
    elapsed = time.perf_counter() - s
    device_rate = batch * iters / elapsed

    # exact totals, untimed: the timed loop cycles these same probe sets,
    # so per-set counts scaled by occurrence count reproduce it exactly
    uses = [(iters + n_batches - 1 - i) // n_batches for i in range(n_batches)]
    total_routes = 0.0
    total_ovf = 0
    ovf_masks = []
    for bi, p in enumerate(probe_sets):
        cnt, ovf = run(p)
        ovf_masks.append(np.asarray(ovf))
        total_routes += float(np.asarray(cnt, dtype=np.float64).sum()) * uses[bi]
        total_ovf += int(ovf_masks[-1].sum()) * uses[bi]

    # ---- host-fallback cost for overflowed topics -------------------------
    # overflowed topics re-match on the host oracle; fold that cost in,
    # sampling overflow rows across ALL probe sets (overflow may cluster)
    ovf_frac = total_ovf / (batch * iters)
    oracle_rate = None
    eff_rate = device_rate
    if total_ovf:
        samples = []
        for bi in range(n_batches):
            for qi in np.nonzero(ovf_masks[bi])[0][:32]:
                samples.append(all_queries[bi][qi])
        s = time.perf_counter()
        for levels, t in samples:
            trie = tries.get(t)
            if trie is not None:
                trie.match(list(levels))
        host_t = time.perf_counter() - s
        if samples:
            oracle_rate = len(samples) / host_t
            # effective: device pipeline + host oracle work in parallel
            # threads would overlap; be conservative and ADD the time
            host_total = (batch * iters * ovf_frac) / oracle_rate
            eff_rate = batch * iters / (elapsed + host_total)

    # ---- sync latency -----------------------------------------------------
    lat = []
    for it in range(min(iters, 8)):
        p = probe_sets[it % n_batches]
        s = time.perf_counter()
        cnt, ovf = run(p)
        np.asarray(cnt)
        lat.append(time.perf_counter() - s)
    lat = np.array(lat)
    out = {
        "topics_per_s": round(eff_rate, 1),
        "device_topics_per_s": round(device_rate, 1),
        "routes_per_s": round(total_routes / elapsed, 1),
        "overflow_frac": round(ovf_frac, 5),
        "oracle_fallback_topics_per_s": (round(oracle_rate, 1)
                                         if oracle_rate else None),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "host_tokenize_topics_per_s": round(tok_rate, 1),
        "probe_upload_s": round(upload_s, 2),
        "compile_s": round(t1 - t0, 1),
        "batch": batch,
        "k_states": k_states,
    }
    log(f"[{name}] {json.dumps(out)}")
    return out


def _measure_routes(tries, probe_fn, *, name, compiled,
                    k_states=None, iters=None, batch=None,
                    max_intervals=None):
    """End-to-end matched-routes measurement (the honest headline).

    Pipelined interval-walk dispatch with double-buffered readback: while
    the device walks iteration i+1, the host reads back and expands
    iteration i's intervals into materialized per-topic route-slot arrays
    (ops.match.expand_intervals) — the same per-topic route-entry vectors
    the stock proxy materializes. Tokenize cost is folded in SERIALLY
    (conservative: real serving overlaps the multicore C++ tokenizer with
    device compute).
    """
    from bifromq_tpu.models.automaton import TokenCache, tokenize
    from bifromq_tpu.ops.match import (Probes, expand_intervals,
                                       walk_routes)
    k_states = k_states or K_STATES
    iters = iters or ITERS
    batch = batch or BATCH
    max_intervals = max_intervals or INTERVALS
    tok_cache = (TokenCache()
                 if os.environ.get("BENCH_TOK_CACHE", "1") != "0" else None)

    ct, dev, compile_s = compiled
    n_batches = 4
    all_queries = [probe_fn(i, batch) for i in range(n_batches)]
    t2 = time.time()
    toks = [tokenize([q[0] for q in queries],
                     [ct.root_of(q[1]) for q in queries],
                     max_levels=ct.max_levels, salt=ct.salt, batch=batch,
                     cache=tok_cache)
            for queries in all_queries]
    t3 = time.time()
    tok_rate = batch * n_batches / (t3 - t2)  # COLD (first-touch) rate
    probe_sets = [Probes.from_tokenized(t) for t in toks]
    for p in probe_sets:
        for a in (p.tok_h1, p.tok_h2, p.lengths, p.roots, p.sys_mask):
            np.asarray(a[:1])  # true upload sync (see _measure_match note)
    compaction = os.environ.get("BENCH_COMPACTION", "sort")
    run = lambda p: walk_routes(dev, p, probe_len=ct.probe_len,
                                k_states=k_states,
                                max_intervals=max_intervals,
                                compaction=compaction)

    def process(r):
        s_np = np.asarray(r.start)
        c_np = np.asarray(r.count)
        ovf = np.asarray(r.overflow)
        slots, offs = expand_intervals(s_np, c_np)
        return slots.size, int(ovf.sum()), slots, offs

    t4u = time.time()
    for p in probe_sets:
        process(run(p))  # warmup + jit + readback-path warmup
    log(f"[{name}] routes-walk warmup+jit {time.time() - t4u:.1f}s; "
        f"host tokenize {tok_rate:,.0f} topics/s")

    # ---- pipelined e2e: dispatch iter i+1, then read back + expand iter i
    s = time.perf_counter()
    prev = None
    total_routes = 0
    total_ovf = 0
    for it in range(iters):
        h = run(probe_sets[it % n_batches])
        if prev is not None:
            nr, no, _, _ = process(prev)
            total_routes += nr
            total_ovf += no
        prev = h
    nr, no, _, _ = process(prev)
    total_routes += nr
    total_ovf += no
    elapsed = time.perf_counter() - s
    pipe_topics = batch * iters / elapsed
    pipe_routes = total_routes / elapsed

    # ---- host-oracle fold for rows even escalation couldn't fit ----------
    ovf_frac = total_ovf / (batch * iters)
    eff_elapsed = elapsed
    oracle_rate = None
    if total_ovf:
        from bifromq_tpu.models.automaton import tokenize as _tk  # noqa
        r0 = run(probe_sets[0])
        ovf_mask = np.asarray(r0.overflow)
        samples = [all_queries[0][qi]
                   for qi in np.nonzero(ovf_mask)[0][:32]]
        if samples:
            s0 = time.perf_counter()
            for levels, t in samples:
                trie = tries.get(t)
                if trie is not None:
                    trie.match(list(levels))
            oracle_rate = len(samples) / (time.perf_counter() - s0)
            eff_elapsed += (batch * iters * ovf_frac) / oracle_rate

    # ---- conservative serial tokenize fold -------------------------------
    tok_s = batch * iters / tok_rate
    e2e_topics = batch * iters / (eff_elapsed + tok_s)
    e2e_routes = total_routes / (eff_elapsed + tok_s)

    # ---- tunnel RTT vs device-kernel time (ISSUE 6 satellite) ------------
    # a tiny scalar round trip isolates the TRANSPORT cost (the axon
    # tunnel pays ~70ms per sync; CPU pays microseconds); walk_read minus
    # RTT approximates the kernel's own time, so CPU-fallback trajectory
    # records (BENCH_r02–r05) stay comparable to real-TPU ones
    import jax
    rtts = []
    for _ in range(8):
        s0 = time.perf_counter()
        np.asarray(jax.device_put(np.zeros(1, np.int32)))
        rtts.append(time.perf_counter() - s0)
    rtt_ms = float(np.percentile(rtts, 50)) * 1e3

    # ---- sync latency: tokenize + upload + walk + readback + expand ------
    lat = []
    phases = {"tok_ms": [], "upload_ms": [], "walk_read_ms": [],
              "expand_ms": []}
    for it in range(min(iters, 8)):
        queries = all_queries[it % n_batches]
        s0 = time.perf_counter()
        tk = tokenize([q[0] for q in queries],
                      [ct.root_of(q[1]) for q in queries],
                      max_levels=ct.max_levels, salt=ct.salt, batch=batch,
                      cache=tok_cache)
        s1 = time.perf_counter()
        p = Probes.from_tokenized(tk)
        np.asarray(p.tok_h1[:1])
        s2 = time.perf_counter()
        r = run(p)
        s_np = np.asarray(r.start)
        c_np = np.asarray(r.count)
        s3 = time.perf_counter()
        expand_intervals(s_np, c_np)
        s4 = time.perf_counter()
        lat.append(s4 - s0)
        phases["tok_ms"].append((s1 - s0) * 1e3)
        phases["upload_ms"].append((s2 - s1) * 1e3)
        phases["walk_read_ms"].append((s3 - s2) * 1e3)
        phases["expand_ms"].append((s4 - s3) * 1e3)
    lat = np.array(lat)
    out = {
        "e2e_topics_per_s": round(e2e_topics, 1),
        "e2e_matched_routes_per_s": round(e2e_routes, 1),
        "pipeline_topics_per_s": round(pipe_topics, 1),
        "pipeline_matched_routes_per_s": round(pipe_routes, 1),
        "routes_per_topic": round(total_routes / (batch * iters), 2),
        "overflow_frac": round(ovf_frac, 5),
        "oracle_fallback_topics_per_s": (round(oracle_rate, 1)
                                         if oracle_rate else None),
        "host_tokenize_topics_per_s": round(tok_rate, 1),
        "host_tokenize_warm_topics_per_s": round(
            batch / (float(np.percentile(phases["tok_ms"], 50)) / 1e3), 1),
        "tok_cache_hit_rate": (round(tok_cache.hits / max(
            1, tok_cache.hits + tok_cache.misses), 3)
            if tok_cache is not None else None),
        "e2e_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "e2e_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "phase_ms_p50": {k: round(float(np.percentile(v, 50)), 2)
                         for k, v in phases.items()},
        "tunnel_rtt_ms_p50": round(rtt_ms, 3),
        "device_kernel_ms_p50": round(max(0.0, float(np.percentile(
            phases["walk_read_ms"], 50)) - rtt_ms), 2),
        "batch": batch,
        "k_states": k_states,
        "max_intervals": max_intervals,
        "compile_s": round(compile_s, 1),
    }
    log(f"[{name}] routes-e2e {json.dumps(out)}")
    return out


def _measure_expand_ab(tries, probe_fn, *, name, compiled,
                       k_states=None, iters=None, batch=None,
                       max_intervals=None):
    """Device-vs-host fan-out A/B (ISSUE 19 headline): end-to-end
    matched-routes/s over walk + expansion + per-peer bucketing, tokenize
    excluded (identical on every leg). Three legs, same probe sets, same
    walk kernel:

    - ``host``: the pre-ISSUE-19 serving shape — read back the full
      [B, A] interval grids, ``expand_intervals`` on host, then the
      per-route ``setdefault(...).append`` delivery grouping the dist
      service does (dist/service.py BatchDeliveryCall grouping), its rate
      measured on a bounded pair sample and extrapolated (the loop at
      full c2 fan-out is minutes per batch — the very wall this A/B
      documents).
    - ``host_vectorized``: strongest host contender — same expansion,
      then ``bucket_pairs_host`` (numpy stable-argsort grouping). Not
      what the pre-change code did, reported so the headline is not a
      strawman ratio.
    - ``device``: fused ``expand_routes`` (ragged-arange expansion +
      counting-sort bucketing on device); the host reads back only the
      compact pre-bucketed pair buffers. ``trunc`` rows re-expand on
      host from the grids — the exact serving cold path.

    The expansion cap is sized from the warmup batches' MEASURED fan-out
    (1.25x margin, 64k-rounded — NOT pow2, and NOT batch x
    BIFROMQ_EXPAND_CAP: device expansion is O(cap) whatever the live
    pair count, so an oversized buffer charges the device leg for lanes
    the workload never fills).
    """
    import jax

    from bifromq_tpu.dist.deliverer import build_peer_table
    from bifromq_tpu.models.automaton import tokenize
    from bifromq_tpu.ops.match import (Probes, bucket_pairs_host,
                                       expand_intervals, expand_routes,
                                       walk_routes)
    k_states = k_states or K_STATES
    iters = int(os.environ.get("BENCH_EXPAND_AB_ITERS",
                               str(min(iters or ITERS, 6))))
    # B=4096 at c2 fan-out is the measured sweet spot for the full-route
    # walk: walk_routes (unlike the headline's walk_count_only) scales
    # superlinearly with batch (measured ~36 us/topic at 4096 vs ~116 at
    # 8192), and the expand stage is linear in cap through ~90M lanes
    # with a ~2.5x per-pair cliff above (multi-GB working set on the
    # single-core backend). Batch is a tuning knob, not part of the A/B
    # contract: every leg serves the same batches either way.
    batch = int(os.environ.get("BENCH_EXPAND_AB_BATCH",
                               str(min(batch or BATCH, 4096))))
    max_intervals = max_intervals or INTERVALS

    ct, dev, _ = compiled
    tab = build_peer_table(ct.matchings_arr)
    n_peers = tab.n_peers
    dev_slot_peer = jax.device_put(tab.slot_peer)

    n_batches = 2
    all_queries = [probe_fn(i, batch) for i in range(n_batches)]
    toks = [tokenize([q[0] for q in queries],
                     [ct.root_of(q[1]) for q in queries],
                     max_levels=ct.max_levels, salt=ct.salt, batch=batch)
            for queries in all_queries]
    probe_sets = [Probes.from_tokenized(t) for t in toks]
    for p in probe_sets:
        for a in (p.tok_h1, p.tok_h2, p.lengths, p.roots, p.sys_mask):
            np.asarray(a[:1])  # true upload sync (see _measure_match)
    compaction = os.environ.get("BENCH_COMPACTION", "sort")
    run = lambda p: walk_routes(dev, p, probe_len=ct.probe_len,
                                k_states=k_states,
                                max_intervals=max_intervals,
                                compaction=compaction)

    # ---- warmup + cap sizing from measured fan-out -----------------------
    t0 = time.perf_counter()
    max_pairs = 1
    grids = []
    for p in probe_sets:
        r = run(p)
        c_np = np.asarray(r.count).copy()
        c_np[np.asarray(r.overflow)] = 0
        np.maximum(c_np, 0, out=c_np)
        grids.append((np.asarray(r.start), c_np))
        max_pairs = max(max_pairs, int(c_np.sum(dtype=np.int64)))
    cap = max(65536, -(-int(max_pairs * 1.25) // 65536) * 65536)
    er = expand_routes(run(probe_sets[0]), dev_slot_peer, cap=cap,
                       n_peers=n_peers)
    np.asarray(er.peer_offsets)  # jit + readback-path warmup
    log(f"[{name}] expand-ab warmup {time.perf_counter() - t0:.1f}s; "
        f"max_pairs={max_pairs} cap={cap} n_peers={n_peers}")

    def host_expand(gs, gc):
        slots, offs = expand_intervals(gs, gc)
        rows = np.repeat(np.arange(offs.size - 1, dtype=np.int32),
                         np.diff(offs))
        return slots, rows, offs

    # ---- one-shot bucket parity check (warmup batch, untimed) ------------
    gs0, gc0 = grids[0]
    h_slots, h_rows, _ = host_expand(gs0, gc0)
    hps, hpr, hpo = bucket_pairs_host(h_slots, h_rows, tab.slot_peer,
                                      n_peers)
    live = int(np.asarray(er.peer_offsets)[n_peers + 1])
    parity = (not np.asarray(er.trunc).any()
              and live == int(hpo[n_peers + 1])
              and np.array_equal(np.asarray(er.peer_slots)[:live],
                                 hps[:live])
              and np.array_equal(np.asarray(er.peer_rows)[:live],
                                 hpr[:live]))
    if not parity:
        log(f"[{name}] expand-ab WARNING: device/host bucket MISMATCH")

    # ---- device leg ------------------------------------------------------
    ab_debug = os.environ.get("BENCH_EXPAND_AB_DEBUG", "0") != "0"
    dev_lat = []
    dev_routes = 0
    trunc_rows = 0
    for it in range(iters):
        s0 = time.perf_counter()
        r = run(probe_sets[it % n_batches])
        if ab_debug:
            jax.block_until_ready(r.count)
            t_walk = time.perf_counter() - s0
        er = expand_routes(r, dev_slot_peer, cap=cap, n_peers=n_peers)
        if ab_debug:
            jax.block_until_ready(er.peer_slots)
            t_expand = time.perf_counter() - s0 - t_walk
        # the delivery surface serving reads: pre-bucketed pairs + the
        # per-topic offsets + the escalation flags
        ps = np.asarray(er.peer_slots)
        pr = np.asarray(er.peer_rows)
        po = np.asarray(er.peer_offsets)
        ro = np.asarray(er.row_offsets)
        n_live = int(np.asarray(er.n_pairs))
        tr = np.asarray(er.trunc)
        np.asarray(er.overflow)
        if tr.any():
            # cold path: trunc rows re-expand from the grids, exactly
            # like serving's escalation fetch
            first = int(np.argmax(tr))
            n_live = int(ro[first])
            g_s = np.asarray(er.start)
            g_c = np.maximum(np.asarray(er.count), 0)
            g_c[~tr] = 0
            esc_slots, _ = expand_intervals(g_s, g_c)
            n_live += esc_slots.size
            trunc_rows += int(tr.sum())
        dev_routes += n_live
        dev_lat.append(time.perf_counter() - s0)
        if ab_debug:
            log(f"[{name}] expand-ab dbg it{it}: walk {t_walk * 1e3:.0f}ms"
                f" expand {t_expand * 1e3:.0f}ms"
                f" readback {(dev_lat[-1] - t_walk - t_expand) * 1e3:.0f}ms")
    dev_elapsed = float(np.sum(dev_lat))
    del ps, pr, po

    # ---- host leg: walk + grid readback + expand (timed), python
    # delivery grouping folded from a sampled rate ------------------------
    host_lat = []
    host_routes = 0
    for it in range(iters):
        s0 = time.perf_counter()
        r = run(probe_sets[it % n_batches])
        gs = np.asarray(r.start)
        gc = np.asarray(r.count).copy()
        gc[np.asarray(r.overflow)] = 0
        np.maximum(gc, 0, out=gc)
        slots, rows, offs = host_expand(gs, gc)
        host_routes += slots.size
        host_lat.append(time.perf_counter() - s0)
    host_expand_elapsed = float(np.sum(host_lat))
    # per-route python grouping rate, sampled (generously: peer ids are
    # pre-gathered vectorized; the dist service hashes a (broker, str)
    # tuple per route on top of this)
    n_slot = tab.slot_peer.shape[0]
    sample = min(h_slots.size, 2_000_000)
    if sample:
        peer_of = (tab.slot_peer[np.clip(h_slots[:sample], 0, n_slot - 1)]
                   if n_slot else np.zeros(sample, np.int32)).tolist()
        sl_list = h_slots[:sample].tolist()
        s0 = time.perf_counter()
        by_peer = {}
        for pe, sl in zip(peer_of, sl_list):
            by_peer.setdefault(pe, []).append(sl)
        py_rate = sample / (time.perf_counter() - s0)
        del by_peer, peer_of, sl_list
    else:
        py_rate = float("inf")
    host_elapsed = host_expand_elapsed + host_routes / py_rate

    # ---- host vectorized leg --------------------------------------------
    viters = max(2, iters // 2)
    vec_lat = []
    vec_routes = 0
    for it in range(viters):
        s0 = time.perf_counter()
        r = run(probe_sets[it % n_batches])
        gs = np.asarray(r.start)
        gc = np.asarray(r.count).copy()
        gc[np.asarray(r.overflow)] = 0
        np.maximum(gc, 0, out=gc)
        slots, rows, offs = host_expand(gs, gc)
        bucket_pairs_host(slots, rows, tab.slot_peer, n_peers)
        vec_routes += slots.size
        vec_lat.append(time.perf_counter() - s0)
    vec_elapsed = float(np.sum(vec_lat))

    dev_rate = dev_routes / dev_elapsed
    host_rate = host_routes / host_elapsed
    vec_rate = vec_routes / vec_elapsed
    out = {
        "device_matched_routes_per_s": round(dev_rate, 1),
        "host_matched_routes_per_s": round(host_rate, 1),
        "host_vectorized_matched_routes_per_s": round(vec_rate, 1),
        "speedup_vs_host": round(dev_rate / host_rate, 2),
        "speedup_vs_host_vectorized": round(dev_rate / vec_rate, 2),
        "routes_per_topic": round(dev_routes / (batch * iters), 2),
        "device_ms_p50": round(
            float(np.percentile(dev_lat, 50)) * 1e3, 1),
        "host_expand_ms_p50": round(
            float(np.percentile(host_lat, 50)) * 1e3, 1),
        "host_python_group_pairs_per_s": (round(py_rate, 1)
                                          if sample else None),
        "bucket_parity": parity,
        "cap": cap,
        "cap_fill": round(max_pairs / cap, 3),
        "trunc_row_frac": round(trunc_rows / (batch * iters), 6),
        "n_peers": n_peers,
        "batch": batch,
        "iters": iters,
        "k_states": k_states,
        "max_intervals": max_intervals,
        "basis": ("walk + expand + per-peer bucketing, tokenize excluded"
                  " (identical all legs); host grouping rate sampled at"
                  f" {sample} pairs then extrapolated"),
    }
    log(f"[{name}] expand-ab {json.dumps(out)}")
    return out


def _latency_frontier(tries, probe_fn, *, name, compiled,
                      k_states=None):
    """Small-batch latency mode (VERDICT r4 #4): per-batch sync p50/p99
    and topics/s across B ∈ BENCH_LATENCY_B, count walk + route walk, with
    a phase breakdown to root-cause the latency floor (dispatch vs
    transfer vs walk)."""
    from bifromq_tpu.models.automaton import tokenize
    from bifromq_tpu.ops.match import (Probes, expand_intervals,
                                       walk_count_only, walk_routes)
    k_states = k_states or K_STATES
    ct, dev, _ = compiled
    sweep_b = [int(x) for x in os.environ.get(
        "BENCH_LATENCY_B", "256,1024,4096").split(",") if x]
    compaction = os.environ.get("BENCH_COMPACTION", "sort")
    grid = {}
    for b in sweep_b:
        queries = probe_fn(0, b)
        tok = tokenize([q[0] for q in queries],
                       [ct.root_of(q[1]) for q in queries],
                       max_levels=ct.max_levels, salt=ct.salt, batch=b)
        p = Probes.from_tokenized(tok)
        np.asarray(p.tok_h1[:1])
        runs = {
            "count": lambda: walk_count_only(
                dev, p, probe_len=ct.probe_len, k_states=k_states,
                compaction=compaction),
            "routes": lambda: walk_routes(
                dev, p, probe_len=ct.probe_len, k_states=k_states,
                max_intervals=INTERVALS, compaction=compaction),
        }
        cell = {}
        for kind, fn in runs.items():
            fn()  # jit warmup
            np.asarray(fn()[0] if kind == "count" else fn().start)
            lat, disp = [], []
            for _ in range(20):
                s0 = time.perf_counter()
                r = fn()
                s1 = time.perf_counter()
                if kind == "count":
                    np.asarray(r[0])
                else:
                    s_np = np.asarray(r.start)
                    c_np = np.asarray(r.count)
                    expand_intervals(s_np, c_np)
                lat.append(time.perf_counter() - s0)
                disp.append(s1 - s0)
            lat = np.array(lat)
            cell[kind] = {
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
                "dispatch_p50_ms": round(
                    float(np.percentile(disp, 50)) * 1e3, 2),
                "topics_per_s": round(b / float(np.percentile(lat, 50)), 1),
            }
        grid[f"B{b}"] = cell
        log(f"[{name}] latency B={b}: {json.dumps(cell)}")
    return grid


def _run_modes(tries, probe, *, name, compiled, out, **kw):
    """Shared per-config mode fan-out: e2e routes + expand A/B + latency
    frontier."""
    if ROUTES_MODE:
        out["routes"] = _measure_routes(tries, probe, name=name,
                                        compiled=compiled, **kw)
    if EXPAND_AB_MODE:
        out["expand_ab"] = _measure_expand_ab(tries, probe, name=name,
                                              compiled=compiled, **kw)
    if LATENCY_MODE:
        out["latency"] = _latency_frontier(
            tries, probe, name=name, compiled=compiled,
            k_states=kw.get("k_states"))
    return out


def bench_config1():
    from bifromq_tpu import workloads
    tries = workloads.config_exact(10_000, seed=SEED)
    topics = workloads.probe_topics(BATCH * 4, seed=SEED + 1,
                                    n_level_names=max(64, 10_000 // 100))

    def probe(i, batch):
        return [(t, "tenant0") for t in topics[i * batch:(i + 1) * batch]]
    name = "c1_exact_10K"
    compiled = _compile(tries, name=name)
    out = _measure_match(tries, probe, name=name, compiled=compiled)
    return _run_modes(tries, probe, name=name, compiled=compiled, out=out)


def bench_config2():
    from bifromq_tpu import workloads
    tries = workloads.config_wildcard(N_SUBS, seed=SEED)
    name = f"c2_wildcard_{N_SUBS}"
    if os.environ.get("BENCH_SWEEP"):
        sweep_b = [int(x) for x in os.environ.get(
            "BENCH_SWEEP_B", "8192,16384,32768").split(",") if x]
        sweep_k = [int(x) for x in os.environ.get(
            "BENCH_SWEEP_K", "8,16").split(",") if x]
        # one compile, a (batch × k_states) grid of measurements; the best
        # cell becomes the headline (VERDICT-r3 sweep: B∈{8192,32768} ×
        # K∈{8,16} on the sort-compaction kernel)
        compiled = _compile(tries, name=name)
        best, grid = None, {}
        for b in sweep_b:
            topics = workloads.probe_topics(b * 4, seed=SEED + 1)

            def probe(i, batch, topics=topics):
                return [(t, "tenant0")
                        for t in topics[i * batch:(i + 1) * batch]]
            for k in sweep_k:
                r = _measure_match(tries, probe,
                                   name=f"{name}_B{b}_K{k}",
                                   batch=b, k_states=k, compiled=compiled)
                grid[f"B{b}_K{k}"] = r
                if best is None or r["topics_per_s"] > best["topics_per_s"]:
                    best = r
        log(f"[{name}] sweep grid: {json.dumps(grid)}")
        log(f"[{name}] best cell: B={best['batch']} K={best['k_states']}")
        bb, bk = best["batch"], best["k_states"]
        btopics = workloads.probe_topics(bb * 4, seed=SEED + 1)

        def bprobe(i, batch, topics=btopics):
            return [(t, "tenant0") for t in topics[i * batch:(i + 1) * batch]]
        return _run_modes(tries, bprobe, name=name, compiled=compiled,
                          out=best, k_states=bk, batch=bb)

    topics = workloads.probe_topics(BATCH * 4, seed=SEED + 1)

    def probe(i, batch):
        return [(t, "tenant0") for t in topics[i * batch:(i + 1) * batch]]
    compiled = _compile(tries, name=name)
    out = _measure_match(tries, probe, name=name, compiled=compiled)
    return _run_modes(tries, probe, name=name, compiled=compiled, out=out)


def bench_config3():
    from bifromq_tpu import workloads
    tries = workloads.config_shared(SHARED_TENANTS, SHARED_SUBS, seed=SEED)
    topics = workloads.probe_topics(BATCH * 4, seed=SEED + 1,
                                    n_level_names=500)
    tenants = sorted(tries)

    def probe(i, batch):
        ts = topics[i * batch:(i + 1) * batch]
        return [(t, tenants[(i * batch + j) % len(tenants)])
                for j, t in enumerate(ts)]
    name = f"c3_shared_{SHARED_TENANTS}x{SHARED_SUBS}"
    compiled = _compile(tries, name=name)
    out = _measure_match(tries, probe, name=name, compiled=compiled)
    return _run_modes(tries, probe, name=name, compiled=compiled, out=out)


def bench_config4():
    """Retained path: concrete-topic trie probed by wildcard filters."""
    import jax

    from bifromq_tpu import workloads
    from bifromq_tpu.models.retained import RetainedIndex

    t0 = time.time()
    topics = workloads.config_retained(N_RETAINED, seed=SEED)["tenant0"]
    idx = RetainedIndex(max_levels=18, k_states=K_STATES)
    for levels in topics:
        idx.add_topic("tenant0", levels, "/".join(levels))
    ct = idx.refresh()
    t1 = time.time()
    log(f"[c4_retained_{N_RETAINED}] built+compiled {t1 - t0:.1f}s "
        f"nodes={ct.n_nodes}")

    filters = workloads.probe_filters(BATCH * 4, seed=SEED + 2)
    batches = [[("tenant0", f) for f in filters[i * BATCH:(i + 1) * BATCH]]
               for i in range(4)]
    # ---- device-only walk rate (pipelined, like _measure_match) -----------
    probe_sets = [idx.device_probes(batches[i], batch=BATCH)[0]
                  for i in range(4)]
    run = idx.walk_device
    for p in probe_sets:
        np.asarray(run(p)[0])  # true sync (block_until_ready is a no-op)
    dev_iters = ITERS
    s = time.perf_counter()
    for it in range(dev_iters - 1):
        run(probe_sets[it % 4])
    r_last, _ = run(probe_sets[(dev_iters - 1) % 4])
    np.asarray(r_last)
    dev_rate = BATCH * dev_iters / (time.perf_counter() - s)

    # ---- end-to-end (device walk + host range expansion, sync per call) ---
    # production semantics FIRST: every serving lookup passes
    # RetainMessageMatchLimit (default 10, retain/service.py), which also
    # scan-bounds the host fallback for '+'-exploded filters; the
    # unlimited full-enumeration rate is the stress number
    res = idx.match_batch(batches[0], batch=BATCH, limit=10)  # warmup
    iters = max(4, ITERS // 4)
    s = time.perf_counter()
    matched_lim = 0
    for it in range(iters):
        res = idx.match_batch(batches[it % 4], batch=BATCH, limit=10)
        matched_lim += sum(len(r) for r in res)
    lim_elapsed = time.perf_counter() - s

    res = idx.match_batch(batches[0], batch=BATCH)  # warmup (unlimited)
    s = time.perf_counter()
    matched = 0
    for it in range(iters):
        res = idx.match_batch(batches[it % 4], batch=BATCH)
        matched += sum(len(r) for r in res)
    elapsed = time.perf_counter() - s
    out = {
        "filters_per_s_limit10": round(BATCH * iters / lim_elapsed, 1),
        "matched_retained_per_s_limit10": round(matched_lim / lim_elapsed,
                                                1),
        "filters_per_s": round(BATCH * iters / elapsed, 1),
        "device_filters_per_s": round(dev_rate, 1),
        "matched_retained_per_s": round(matched / elapsed, 1),
        "n_retained": N_RETAINED,
        "compile_s": round(t1 - t0, 1),
    }
    log(f"[c4_retained_{N_RETAINED}] {json.dumps(out)}")
    return out


def bench_config5():
    import random

    from bifromq_tpu import workloads
    tries = workloads.config_multi_tenant(MT_TENANTS, MT_SUBS, seed=SEED)
    topics = workloads.probe_topics(BATCH * 4, seed=SEED + 1)
    tenants = sorted(tries)
    # Zipf tenant traffic: heavier tenants see proportionally more queries
    rng = random.Random(SEED + 3)
    cum = []
    acc = 0.0
    for i in range(len(tenants)):
        acc += 1.0 / (i + 1)
        cum.append(acc)
    tenant_seq = rng.choices(tenants, cum_weights=cum, k=BATCH * 4)

    def probe(i, batch):
        ts = topics[i * batch:(i + 1) * batch]
        return [(t, tenant_seq[i * batch + j]) for j, t in enumerate(ts)]
    name = f"c5_multitenant_{MT_TENANTS}x{MT_SUBS}"
    compiled = _compile(tries, name=name)
    out = _measure_match(tries, probe, name=name, compiled=compiled)
    return _run_modes(tries, probe, name=name, compiled=compiled, out=out)


def bench_config6():
    """Match-result cache A/B (ISSUE 4): the full TpuMatcher.match_batch
    serving plane — cache probe + in-batch dedup + device walk + host
    expansion — on (a) a Zipf repeated-topic workload (the dominant MQTT
    pattern: the acceptance bar is cache-on ≥2× cache-off) and (b) a
    unique-topic workload (the miss path: probe/dedup overhead must stay
    in the noise). Prints hit rate + dedup ratio per mode."""
    import random as _random

    from bifromq_tpu import workloads
    from bifromq_tpu.models.matcher import TpuMatcher
    from bifromq_tpu.utils.metrics import MATCH_CACHE

    tries = workloads.config_wildcard(N_SUBS, seed=SEED)
    batch = min(BATCH, 4096)
    iters = max(8, ITERS // 2)
    n_batches = 4
    hot = int(os.environ.get("BENCH_CACHE_HOT_TOPICS", "512"))
    pool = workloads.probe_topics(hot, seed=SEED + 1)
    rng = _random.Random(SEED + 7)
    cum, acc = [], 0.0
    for i in range(hot):
        acc += 1.0 / (i + 1)
        cum.append(acc)
    zipf_sets = [[("tenant0", pool[j]) for j in rng.choices(
        range(hot), cum_weights=cum, k=batch)] for _ in range(n_batches)]
    # TRULY unique topics (probe_topics draws Zipf names and repeats):
    # duplicates would hand the cache-on leg in-batch dedup wins the
    # cache-off leg can't have, biasing the miss-path comparison
    seen = set()
    uniq_topics = []
    gen = 2
    while len(uniq_topics) < batch * n_batches:
        for t in workloads.probe_topics(batch * n_batches, seed=SEED + gen):
            k = tuple(t)
            if k not in seen:
                seen.add(k)
                uniq_topics.append(t)
        gen += 1
    uniq_sets = [[("tenant0", t)
                  for t in uniq_topics[i * batch:(i + 1) * batch]]
                 for i in range(n_batches)]
    name = f"c6_match_cache_{N_SUBS}"
    out = {}
    for mode in ("off", "on"):
        MATCH_CACHE.reset()
        m = TpuMatcher.from_tries(tries, match_cache=(mode == "on"),
                                  auto_compact=False)
        cell = {}
        for wl, sets in (("repeated", zipf_sets), ("unique", uniq_sets)):
            if m.match_cache is not None:
                m.match_cache.clear()
            # warm a FULL cycle: every probe set's miss pattern gets its
            # device shapes jit-compiled (the pow2-snapped miss sub-batch
            # is a new shape class the off path never sees), and the
            # repeated workload's cache reaches steady state — the regime
            # the acceptance bar speaks about
            for ws in sets:
                m.match_batch(ws)
            h0 = m.match_cache.counts() if m.match_cache else (0, 0)
            lat = []
            s = time.perf_counter()
            for it in range(iters):
                if wl == "unique" and m.match_cache is not None:
                    # keep "unique" honest across cycles: every timed
                    # iteration is a pure miss pass (probe + dedup + put
                    # overhead on top of the full device walk)
                    m.match_cache.clear()
                s0 = time.perf_counter()
                m.match_batch(sets[it % n_batches])
                lat.append(time.perf_counter() - s0)
            elapsed = time.perf_counter() - s
            lat = np.array(lat)
            cell[wl] = {
                "topics_per_s": round(batch * iters / elapsed, 1),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            }
            if m.match_cache is not None:
                h1 = m.match_cache.counts()
                lookups = (h1[0] - h0[0]) + (h1[1] - h0[1])
                cell[wl]["hit_rate"] = round(
                    (h1[0] - h0[0]) / lookups, 4) if lookups else 0.0
        if m.match_cache is not None:
            cell["cache"] = m.match_cache.snapshot()
            cell["dedup"] = MATCH_CACHE.snapshot()["dedup"]
        out[mode] = cell
        log(f"[{name}] cache={mode}: {json.dumps(cell)}")
    on, off = out.get("on"), out.get("off")
    if on and off:
        out["repeated_speedup"] = round(
            on["repeated"]["topics_per_s"]
            / max(1e-9, off["repeated"]["topics_per_s"]), 2)
        out["unique_p99_ratio"] = round(
            on["unique"]["p99_ms"] / max(1e-9, off["unique"]["p99_ms"]), 2)
        log(f"[{name}] repeated speedup {out['repeated_speedup']}x, "
            f"unique p99 ratio {out['unique_p99_ratio']}")
    return out


def bench_config7():
    """Device-pipeline A/B (ISSUE 6): per-batch serving latency through
    the full TpuMatcher plane.

    - **sync leg** — the BENCH_r01 shape: every batch is a blocking
      full-size `match_batch` round trip (queue → pow2 pad → dispatch →
      device_get), so every topic's latency is the whole batch's.
    - **pipelined leg** — the same topic stream as SMALL adaptive batches
      (the shallow-queue floor the ring emits) through
      `match_batch_async`: `pipeline_depth` workers keep the ring full,
      dispatch overlaps fetch, and per-batch latency is what a publish
      actually waits.

    Prints both legs' topics/s + batch p50/p99 and the p99 speedup (the
    acceptance bar is ≥10×), plus the dispatch/ready/fetch stage
    histograms that replace the old blocking `device.sync` stage.
    """
    import asyncio

    from bifromq_tpu import workloads
    from bifromq_tpu.models.matcher import TpuMatcher
    from bifromq_tpu.models.pipeline import pipeline_depth
    from bifromq_tpu.utils.metrics import STAGES

    n_subs = min(N_SUBS, int(os.environ.get("BENCH_PIPE_SUBS", "200000")))
    tries = workloads.config_wildcard(n_subs, seed=SEED)
    big = min(BATCH, 4096)
    iters = max(8, ITERS // 2)
    try:
        small = int(os.environ.get("BENCH_PIPE_SMALL", "16"))
    except ValueError:
        small = 16
    # clamp to [1, big]: small > big would compute an empty pipelined
    # workload (n_small = 0 → sm[0] IndexError), small < 1 divides by zero
    small = max(1, min(small, big))
    topics = workloads.probe_topics(big * 4, seed=SEED + 1)
    name = f"c7_pipeline_{n_subs}"
    m = TpuMatcher.from_tries(tries, match_cache=False,
                              auto_compact=False)

    batches = [[("tenant0", t) for t in topics[i * big:(i + 1) * big]]
               for i in range(4)]
    # ---- sync leg ---------------------------------------------------------
    m.match_batch(batches[0])   # warm the big-batch shape
    lat = []
    s = time.perf_counter()
    for it in range(iters):
        s0 = time.perf_counter()
        m.match_batch(batches[it % 4])
        lat.append(time.perf_counter() - s0)
    sync_elapsed = time.perf_counter() - s
    lat = np.array(lat)
    sync = {
        "batch": big,
        "topics_per_s": round(big * iters / sync_elapsed, 1),
        "batch_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "batch_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
    }
    log(f"[{name}] sync: {json.dumps(sync)}")

    # ---- pipelined leg ----------------------------------------------------
    n_small = max(1, min(big * iters // small, 2048))
    sm = [[("tenant0", topics[(j * small + k) % len(topics)])
           for k in range(small)] for j in range(n_small)]
    STAGES.reset()

    async def run_pipe():
        lats = []
        nxt = {"i": 0}
        peak = {"v": 0}

        async def worker():
            while nxt["i"] < len(sm):
                b = sm[nxt["i"]]
                nxt["i"] += 1
                s0 = time.perf_counter()
                await m.match_batch_async(b, batch=None)
                lats.append(time.perf_counter() - s0)
                ring = m._ring
                if ring is not None:
                    peak["v"] = max(peak["v"], ring.peak_inflight)

        # warm the small shapes before timing
        await m.match_batch_async(sm[0])
        s = time.perf_counter()
        workers = [asyncio.ensure_future(worker())
                   for _ in range(pipeline_depth())]
        await asyncio.gather(*workers)
        return lats, time.perf_counter() - s, peak["v"]

    lats, pipe_elapsed, peak_inflight = asyncio.run(run_pipe())
    lats = np.array(lats)
    pipe = {
        "batch": small,
        "topics_per_s": round(small * len(sm) / pipe_elapsed, 1),
        "batch_p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 2),
        "batch_p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 2),
        "peak_in_flight": peak_inflight,
        "ring_depth": pipeline_depth(),
    }
    log(f"[{name}] pipelined: {json.dumps(pipe)}")
    stages = {k: v for k, v in STAGES.snapshot().items()
              if k.startswith("device")}
    out = {
        "sync": sync,
        "pipelined": pipe,
        "batch_p99_speedup": round(
            sync["batch_p99_ms"] / max(1e-9, pipe["batch_p99_ms"]), 2),
        "stage_latency_ms": stages,
    }
    log(f"[{name}] p99 speedup {out['batch_p99_speedup']}x; "
        f"stages: {json.dumps(stages)}")
    return out


def bench_config8():
    """Subscription-churn config (ISSUE 9): sustained subscribe /
    unsubscribe at rate against a full-size base, interleaved with
    publishes — measuring single-mutation patch-apply latency (host plan
    + narrow device update, ``_flush_patches`` forced per op so every
    sample is one mutation end-to-end) and match p99 DURING churn, next
    to the full-rebuild cost the same mutation used to amortize.

    The acceptance bar: patch apply ≥100× faster than the full rebuild
    at 1M subs on CPU; steady churn below the tombstone threshold does
    ZERO full rebuilds and ZERO match-cache generation bumps; results
    stay row-identical to the host oracle. The cell persists to
    bench_results/churn_last.json so the measurement survives the run.
    """
    from bifromq_tpu import workloads
    from bifromq_tpu.models.matcher import TpuMatcher
    from bifromq_tpu.models.oracle import Route
    from bifromq_tpu.obs import OBS
    from bifromq_tpu.types import RouteMatcher

    n_subs = int(os.environ.get("BENCH_CHURN_SUBS", str(N_SUBS)))
    n_ops = int(os.environ.get("BENCH_CHURN_OPS", "256"))
    name = f"c8_churn_{n_subs}"

    def mk(tf, rid, inc=0):
        return Route(matcher=RouteMatcher.from_topic_filter(tf),
                     broker_id=0, receiver_id=rid, deliverer_key="d0",
                     incarnation=inc)

    t0 = time.perf_counter()
    tries = workloads.config_wildcard(n_subs, seed=SEED)
    build_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    m = TpuMatcher.from_tries(tries, match_cache=False)
    install_s = time.perf_counter() - t0
    # the cost every compact_threshold'th mutation used to pay: the full
    # compile + device upload + walk warm of this exact population
    rebuild_s = m._last_compile_s
    log(f"[{name}] base: build {build_s:.1f}s, compile+install "
        f"{rebuild_s:.1f}s, patchable={type(m._base_ct).__name__}")
    ledger = OBS.profiler.ledger
    compiles0 = m.compile_count
    bumps0 = ledger.generation_bumps

    batch = 64
    topics = workloads.probe_topics(batch * 8, seed=SEED + 1)
    mb = [[("tenant0", t) for t in topics[i * batch:(i + 1) * batch]]
          for i in range(8)]
    # warm the match shapes AND the patch-scatter jit outside the timing —
    # every probe batch once, so the lazily-compiled escalation walk (an
    # overflow row's first dispatch pays its XLA compile) lands in warmup,
    # not in the churn-window p99
    for wb in mb:
        m.match_batch(wb)
    m.add_route("tenant0", mk("bench/churn/warm/+", "w0"))
    m._flush_patches()
    m.match_batch(mb[0])

    patch_lat, unsub_lat, match_lat = [], [], []
    added = []
    for i in range(n_ops):
        tf = f"bench/churn/{i}/+"
        s0 = time.perf_counter()
        m.add_route("tenant0", mk(tf, f"c{i}", inc=1))
        m._flush_patches()
        patch_lat.append(time.perf_counter() - s0)
        added.append((tf, f"c{i}"))
        if i % 8 == 4:
            s0 = time.perf_counter()
            m.match_batch(mb[(i // 8) % 8])
            match_lat.append(time.perf_counter() - s0)
    for i, (tf, rid) in enumerate(added[:n_ops // 2]):
        s0 = time.perf_counter()
        m.remove_route("tenant0", RouteMatcher.from_topic_filter(tf),
                       (0, rid, "d0"), incarnation=1)
        m._flush_patches()
        unsub_lat.append(time.perf_counter() - s0)

    # oracle parity after the storm: device serving vs authoritative tries
    probe = [("tenant0", t) for t in topics[:256]]
    probe += [("tenant0", ["bench", "churn", str(i), "x"])
              for i in range(0, n_ops, 7)]
    got = m.match_batch(probe)
    want = m.match_from_tries(probe)

    def canon(r):
        return (sorted((x.matcher.mqtt_topic_filter, x.receiver_url)
                       for x in r.normal),
                {f: sorted(x.receiver_url for x in ms)
                 for f, ms in r.groups.items()})
    parity = all(canon(a) == canon(b) for a, b in zip(got, want))

    patch_lat = np.array(patch_lat)
    # degenerate BENCH_CHURN_OPS (<8) can leave the sampled legs empty;
    # report zeros instead of crashing the whole bench run
    unsub_lat = np.array(unsub_lat) if unsub_lat else np.zeros(1)
    match_lat = np.array(match_lat) if match_lat else np.zeros(1)
    p99 = float(np.percentile(patch_lat, 99))
    out = {
        "n_subs": n_subs,
        "churn_ops": n_ops,
        "build_s": round(build_s, 1),
        "full_rebuild_s": round(rebuild_s, 2),
        "patch_apply_ms": {
            "p50": round(float(np.percentile(patch_lat, 50)) * 1e3, 3),
            "p99": round(p99 * 1e3, 3),
            "mean": round(float(patch_lat.mean()) * 1e3, 3),
        },
        "unsubscribe_ms": {
            "p50": round(float(np.percentile(unsub_lat, 50)) * 1e3, 3),
            "p99": round(float(np.percentile(unsub_lat, 99)) * 1e3, 3),
        },
        "patch_vs_rebuild_speedup": round(rebuild_s / max(1e-9, p99), 1),
        "match_p50_ms_during_churn": round(
            float(np.percentile(match_lat, 50)) * 1e3, 2),
        "match_p99_ms_during_churn": round(
            float(np.percentile(match_lat, 99)) * 1e3, 2),
        "match_batch": batch,
        "full_rebuilds_in_window": m.compile_count - compiles0,
        "generation_bumps_in_window": ledger.generation_bumps - bumps0,
        "oracle_parity": parity,
        "patch": m._base_ct.patch_stats()
        if hasattr(m._base_ct, "patch_stats") else None,
        "patch_ledger": {
            "flushes": ledger.patch_flushes,
            "mutations": ledger.patch_mutations,
            "rows": ledger.patch_rows,
            "bytes": ledger.patch_bytes,
        },
        "install_s": round(install_s, 1),
    }
    log(f"[{name}] {json.dumps(out)}")
    try:
        path = os.path.join(_REPO, "bench_results", "churn_last.json")
        # same guard as last_good: a down-scaled smoke run must never
        # clobber the full-population churn record
        keep = True
        try:
            with open(path) as f:
                if n_subs < json.load(f).get("n_subs", 0):
                    keep = False
        except (OSError, ValueError):
            pass
        if keep:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump(dict(out, measured_at=time.strftime(
                    "%Y-%m-%dT%H:%M:%S")), f, indent=1)
    except OSError as e:  # noqa: BLE001 — persistence is best-effort
        log(f"churn record write failed: {e}")
    return out


def bench_config9():
    """Ingest byte-plane A/B (ISSUE 11): publish-side topic prep measured
    on the topic-diversity corpus (realistic level-count / byte-length /
    unicode mix, not `bench/a/b`) across the three tokenizer paths —

    - **python** — the per-message loop (split + per-level hashlib), the
      r01 138K-topics/s wall;
    - **native** — the byte plane: one contiguous TopicBytes pack + the
      C++ tokenizer (numpy-vectorized BLAKE2b as the no-toolchain leg);
    - **device** — raw bytes shipped to the Pallas hash kernel
      (interpret mode on CPU: a correctness surface; its rate is
      reported, not gated).

    The acceptance bar: byte-plane prep ≥10× the python loop at batch
    ≥1024, exact three-way parity, and the matcher-integrated leg must
    attribute a `tokenize` stage on every device batch in the profiler
    split. Stamps record["tokenize"].
    """
    import asyncio

    from bifromq_tpu import workloads
    from bifromq_tpu.models import bytetok
    from bifromq_tpu.models.automaton import tokenize
    from bifromq_tpu.models.bytetok import TopicBytes
    from bifromq_tpu.models.matcher import TpuMatcher

    n_subs = min(N_SUBS, int(os.environ.get("BENCH_TOK_SUBS", "50000")))
    batch = max(1024, min(BATCH, 4096))
    iters = max(8, ITERS // 2)
    name = f"c9_ingest_{n_subs}"
    tries = workloads.config_wildcard(n_subs, seed=SEED)
    m = TpuMatcher.from_tries(tries, match_cache=False, auto_compact=False)
    ct = m._base_ct
    corpus = workloads.diverse_topics(batch * 4, seed=SEED + 11)
    batches = [corpus[i * batch:(i + 1) * batch] for i in range(4)]
    roots = [ct.root_of("tenant0")] * batch

    def timed(fn, legs=iters):
        fn(0)   # warm (jit / native lib load / cache shape)
        s = time.perf_counter()
        for it in range(legs):
            fn(it)
        return batch * legs / (time.perf_counter() - s)

    # --- per-message python loop (the r01 wall: one tokenize per
    # publish, split + per-level hashlib — the pre-batching shape) -----
    def py_leg(it):
        for t in batches[it % 4]:
            tokenize([t], roots[:1], max_levels=ct.max_levels,
                     salt=ct.salt, native=False)
    py_rate = timed(py_leg, legs=2)
    # batched python loop (one call per batch, still per-row inside):
    # reported for transparency, not the A/B baseline
    py_batched = timed(lambda it: tokenize(
        batches[it % 4], roots, max_levels=ct.max_levels, salt=ct.salt,
        native=False), legs=max(4, iters // 4))
    # --- byte plane, native C++ (pack cost included — honest) -------------
    nat_rate = timed(lambda it: tokenize(
        TopicBytes.from_topics(batches[it % 4]), roots,
        max_levels=ct.max_levels, salt=ct.salt))
    # --- byte plane, vectorized numpy (no-toolchain fallback) -------------
    np_rate = timed(lambda it: bytetok.tokenize_bytes(
        TopicBytes.from_topics(batches[it % 4]), roots,
        max_levels=ct.max_levels, salt=ct.salt))
    # --- device kernel (interpret on CPU) ---------------------------------
    from bifromq_tpu.ops.tokenize import device_tokenize

    def dev_leg(it):
        _, p = device_tokenize(TopicBytes.from_topics(batches[it % 4]),
                               roots, max_levels=ct.max_levels,
                               salt=ct.salt, batch=batch)
        np.asarray(p.tok_h1)
    dev_rate = timed(dev_leg, legs=max(4, iters // 4))

    # --- three-way parity on one batch ------------------------------------
    tb0 = TopicBytes.from_topics(batches[0])
    py = tokenize(batches[0], roots, max_levels=ct.max_levels,
                  salt=ct.salt, native=False)
    nat = tokenize(tb0, roots, max_levels=ct.max_levels, salt=ct.salt)
    h1, h2, ln, _, sm = bytetok.tokenize_bytes(
        tb0, roots, max_levels=ct.max_levels, salt=ct.salt)
    mirror, probes = device_tokenize(tb0, roots, max_levels=ct.max_levels,
                                     salt=ct.salt, batch=batch)
    sup = mirror.lengths >= 0
    parity = (np.array_equal(py.tok_h1, nat.tok_h1)
              and np.array_equal(py.tok_h1, h1)
              and np.array_equal(py.tok_h2, h2)
              and np.array_equal(py.lengths, ln)
              and np.array_equal(py.sys_mask, sm)
              and np.array_equal(np.asarray(probes.tok_h1)[sup],
                                 py.tok_h1[sup]))

    # --- matcher-integrated leg: tokenize stage on every device batch -----
    from bifromq_tpu.obs import OBS
    prev = os.environ.get("BIFROMQ_DEVICE_TOKENIZE")
    os.environ["BIFROMQ_DEVICE_TOKENIZE"] = "1"
    try:
        rec0 = OBS.profiler.batches_total

        async def run():
            for i in range(8):
                sub = [("tenant0", t)
                       for t in batches[i % 4][:256]]
                await m.match_batch_async(sub, batch=256)
        asyncio.run(run())
    finally:
        if prev is None:
            os.environ.pop("BIFROMQ_DEVICE_TOKENIZE", None)
        else:
            os.environ["BIFROMQ_DEVICE_TOKENIZE"] = prev
    n_new = OBS.profiler.batches_total - rec0
    # n_new == 0 must yield an EMPTY window, not the whole ring ([-0:]):
    # stale records from earlier configs would let the tokenize-stage
    # verdict pass vacuously on exactly the regression it exists to catch
    recs = OBS.profiler.records()[-n_new:] if n_new else []
    dev_batches = [r for r in recs if r.kernel != "oracle"]
    tokenized_all = bool(dev_batches) and all(
        r.tokenize_s > 0 for r in dev_batches)
    split = OBS.profiler.split_snapshot(probe=False)

    out = {
        "batch": batch,
        "corpus": "diverse_topics",
        "python_topics_per_s": round(py_rate, 1),
        "python_batched_topics_per_s": round(py_batched, 1),
        "native_topics_per_s": round(nat_rate, 1),
        "numpy_topics_per_s": round(np_rate, 1),
        "device_topics_per_s": round(dev_rate, 1),
        "speedup_native_vs_python": round(nat_rate / max(1e-9, py_rate),
                                          2),
        "speedup_numpy_vs_python": round(np_rate / max(1e-9, py_rate), 2),
        "three_way_parity": parity,
        "device_supported_frac": round(float(sup.mean()), 4),
        "tokenize_stage_on_every_device_batch": tokenized_all,
        "profiler_tokenize_ms_p50": split.get("tokenize_ms_p50"),
    }
    log(f"[{name}] {json.dumps(out)}")
    return out


def bench_config10():
    """Mixed million-client workload (ISSUE 13 tentpole part 4): every
    serving plane measured under one realistic population instead of
    isolation — see the module docstring for the leg list. The retained
    flood leg IS the acceptance gate shape: >=10k SET/CLEAR mutations
    against the patched index with zero full rebuilds and exact scan
    parity before, during and after the storm."""
    import asyncio
    import random as _random
    from collections import Counter

    from bifromq_tpu import workloads
    from bifromq_tpu.dist.service import GroupFanoutBalancer
    from bifromq_tpu.models.matcher import TpuMatcher
    from bifromq_tpu.models.retained import RetainedIndex, match_filter_host
    from bifromq_tpu.obs import OBS
    from bifromq_tpu.retained_plane import DrainGovernor, RetainedScanPlane
    from bifromq_tpu.types import RouteMatcher, RouteMatcherType
    from bifromq_tpu.models.oracle import Route

    n_clients = int(os.environ.get("BENCH_MIX_CLIENTS", "100000"))
    retained_ops = int(os.environ.get("BENCH_MIX_RETAIN_OPS", "10000"))
    name = f"c10_mixed_{n_clients}"
    t0 = time.perf_counter()
    plan = workloads.config_mixed(n_clients, seed=SEED,
                                  retained_ops=retained_ops)
    gen_s = time.perf_counter() - t0
    log(f"[{name}] plan: {plan['n_clients']} clients, qos {plan['qos_mix']}, "
        f"{len(plan['retained_seed'])} retained base, "
        f"{len(plan['retained_flood'])} flood ops ({gen_s:.1f}s)")

    # ---- leg 1: route table (transient + persistent + $share) -------------
    t0 = time.perf_counter()
    m = TpuMatcher.from_tries(plan["subscriptions"], match_cache=True)
    build_s = time.perf_counter() - t0

    # ---- leg 2: retained flood against the PATCHED index ------------------
    idx = RetainedIndex(k_states=K_STATES)
    t0 = time.perf_counter()
    for tenant, levels in plan["retained_seed"]:
        idx.add_topic(tenant, levels, "/".join(levels))
    ct = idx.refresh()
    retained_compile_s = time.perf_counter() - t0
    plane = RetainedScanPlane(lambda: idx)
    rebuilds0, compactions0 = idx.rebuilds, idx.compactions

    sample = plan["scan_filters"][:32]

    def parity_sample():
        got = idx.match_batch(sample)
        for (tenant, f), g in zip(sample, got):
            trie = idx.tries.get(tenant)
            want = sorted(match_filter_host(trie, list(f))) if trie else []
            if sorted(g) != want:
                return False
        return True

    parity_before = parity_sample()
    flood = plan["retained_flood"]
    scan_lat_during = []
    t0 = time.perf_counter()
    for i, (op, tenant, levels) in enumerate(flood):
        if op == "set":
            idx.add_topic(tenant, levels, "/".join(levels))
        else:
            idx.remove_topic(tenant, levels, "/".join(levels))
        if i % 1024 == 512:
            s0 = time.perf_counter()
            idx.match_batch(sample[:8], limit=10)
            scan_lat_during.append(time.perf_counter() - s0)
    flood_s = time.perf_counter() - t0
    parity_during = parity_sample()
    zero_rebuilds = idx.rebuilds == rebuilds0
    parity_after = parity_sample()

    # ---- leg 3: async wildcard scans through the retain.scan plane --------
    batches = [plan["scan_filters"][i:i + 64]
               for i in range(0, len(plan["scan_filters"]), 64)]

    async def scan_all():
        lats = []
        for b in batches:
            s0 = time.perf_counter()
            await plane.scan_batch(b, limit=10)
            lats.append(time.perf_counter() - s0)
        return lats

    asyncio.run(scan_all())        # warm (jit + cache fill probes)
    scan_lats = asyncio.run(scan_all())
    cache0 = dict(plane.cache.snapshot()) if plane.cache else {}
    repeat_lats = asyncio.run(scan_all())   # repeat pass: cache hits
    cache1 = dict(plane.cache.snapshot()) if plane.cache else {}
    rpt_hits = cache1.get("hits", 0) - cache0.get("hits", 0)
    rpt_miss = cache1.get("misses", 0) - cache0.get("misses", 0)

    # ---- leg 4: publish matching under concurrent session churn -----------
    pub_batches = [[(t, topic) for t, topic, _q in plan["publishes"][i:i + 64]]
                   for i in range(0, min(len(plan["publishes"]), 1024), 64)]
    for b in pub_batches:
        m.match_batch(b)           # warm
    churn = plan["session_churn"]
    match_lat, churn_lat = [], []
    ci = 0
    t0 = time.perf_counter()
    for bi, b in enumerate(pub_batches * 4):
        for _ in range(4):
            if ci < len(churn):
                op, tenant, levels, rid = churn[ci]
                ci += 1
                mt = RouteMatcher(type=RouteMatcherType.NORMAL,
                                  filter_levels=tuple(levels),
                                  mqtt_topic_filter="/".join(levels))
                s0 = time.perf_counter()
                if op == "sub":
                    m.add_route(tenant, Route(matcher=mt, broker_id=0,
                                              receiver_id=rid,
                                              deliverer_key="d0"))
                else:
                    m.remove_route(tenant, mt, (0, rid, "d0"))
                m._flush_patches()
                churn_lat.append(time.perf_counter() - s0)
        s0 = time.perf_counter()
        m.match_batch(b)
        match_lat.append(time.perf_counter() - s0)
    mix_s = time.perf_counter() - t0

    # ---- leg 5: $share election balance (balanced vs random) --------------
    members = [Route(matcher=RouteMatcher(
                        type=RouteMatcherType.UNORDERED_SHARE,
                        filter_levels=("t", "#"),
                        mqtt_topic_filter="$share/g/t/#", group="g"),
                     broker_id=0, receiver_id=f"w{i}",
                     deliverer_key="d0") for i in range(16)]
    bal = GroupFanoutBalancer(_random.Random(SEED))
    for _ in range(4096):
        bal.pick("T", "$share/g/t/#", members)
    bspread = bal.spread("T", "$share/g/t/#")
    rng = _random.Random(SEED)
    rcounts = Counter(members[rng.randrange(16)].receiver_id
                      for _ in range(4096))

    # ---- leg 6: governed reconnect drain storm ----------------------------
    async def drain_storm():
        gov = DrainGovernor(slots=16, per_tenant=4,
                            noisy_fn=lambda t: False)
        waits = {}

        async def one(tenant, _inbox, backlog):
            s0 = time.perf_counter()
            async with gov.slot(tenant):
                await asyncio.sleep(backlog * 2e-5)  # simulated page pump
            waits.setdefault(tenant, []).append(time.perf_counter() - s0)

        await asyncio.gather(*(one(*d) for d in plan["drain_plan"]))
        herd = waits.pop("tenant0", [0.0])
        quiet = [w for ws in waits.values() for w in ws] or [0.0]
        return {
            "herd_sessions": len(herd),
            "quiet_sessions": len(quiet),
            "herd_mean_ms": round(1e3 * sum(herd) / len(herd), 2),
            "quiet_mean_ms": round(1e3 * sum(quiet) / len(quiet), 2),
            "tenant_fair": (sum(quiet) / len(quiet))
            <= (sum(herd) / len(herd)) * 1.5 + 0.005,
            "governor": gov.snapshot(),
        }

    drain = asyncio.run(drain_storm())

    def pct(xs, q):
        return round(float(np.percentile(np.array(xs or [0.0]), q)) * 1e3, 3)

    out = {
        "n_clients": plan["n_clients"],
        "qos_mix": plan["qos_mix"],
        "plan_gen_s": round(gen_s, 1),
        "route_table_build_s": round(build_s, 1),
        "retained": {
            "base_topics": len(plan["retained_seed"]),
            "flood_ops": len(flood),
            "compile_s": round(retained_compile_s, 1),
            "flood_ops_per_s": round(len(flood) / max(1e-9, flood_s), 1),
            "full_rebuilds_in_flood": idx.rebuilds - rebuilds0,
            "compactions_in_flood": idx.compactions - compactions0,
            "zero_rebuilds": zero_rebuilds,
            "patch_fallbacks": idx.patch_fallbacks,
            "scan_parity_before_during_after": [
                parity_before, parity_during, parity_after],
            "scan_p99_ms_during_flood": pct(scan_lat_during, 99),
            "patch": (idx._compiled.patch_stats()
                      if hasattr(idx._compiled, "patch_stats") else None),
        },
        "scan": {
            "filters": len(plan["scan_filters"]),
            "batch_p50_ms": pct(scan_lats, 50),
            "batch_p99_ms": pct(scan_lats, 99),
            "repeat_batch_p50_ms": pct(repeat_lats, 50),
            "repeat_hit_rate": round(
                rpt_hits / max(1, rpt_hits + rpt_miss), 3),
            "degraded": dict(plane.degraded_total),
        },
        "publish_mix": {
            "match_p50_ms": pct(match_lat, 50),
            "match_p99_ms": pct(match_lat, 99),
            "churn_patch_p99_ms": pct(churn_lat, 99),
            "churn_ops": ci,
            "wall_s": round(mix_s, 1),
            "matcher_rebuilds": m.compile_count,
        },
        "share_balance": {
            "members": 16, "elections": 4096,
            "balanced_spread": bspread["max"] - bspread["min"],
            "random_spread": max(rcounts.values()) - min(rcounts.values()),
        },
        "drain_storm": drain,
        "slo_top5": [
            {"tenant": r.get("tenant"), "score": r.get("score")}
            for r in OBS.tenants_snapshot(top_k=5,
                                          emit=False)["tenants"]],
    }
    log(f"[{name}] {json.dumps(out)}")
    return out


def bench_config11():
    """Sharded-mesh serving config (ISSUE 15): the multi-chip matcher as
    a first-class serving plane on the (emulated or real) device mesh —

    - builds BENCH_MESH_SUBS logical subscriptions across
      BENCH_MESH_SHARDS shards (BENCH_MESH_REPLICAS replica rows; on CPU
      run under XLA_FLAGS=--xla_force_host_platform_device_count=8) with
      one HOT TENANT replicated into every shard,
    - asserts per-shard ``ShardedTables.device_bytes()`` stays ≤ the
      ``CapacityPlanner.fits`` per-shard prediction (the ISSUE 9
      multichip gate, at serving scale),
    - measures async mesh match p50/p99 through the shared dispatch
      ring, per-shard patch-apply p99 under an interleaved
      BENCH_MESH_CHURN_OPS churn storm (acceptance: ZERO full rebuilds,
      ZERO match-cache generation bumps, ≥100× cheaper than the mesh
      rebuild, exact oracle parity after the storm), and the
      replicated-hot-tenant fan-out spread over the grid.

    Stamps record["mesh"].
    """
    import asyncio

    from bifromq_tpu import workloads
    from bifromq_tpu.models.oracle import Route
    from bifromq_tpu.obs import OBS
    from bifromq_tpu.obs.capacity import CapacityPlanner
    from bifromq_tpu.parallel.sharded import MeshMatcher, make_mesh
    from bifromq_tpu.types import RouteMatcher

    import jax

    n_subs = int(os.environ.get("BENCH_MESH_SUBS", "200000"))
    n_shards = int(os.environ.get("BENCH_MESH_SHARDS", "8"))
    n_replicas = int(os.environ.get("BENCH_MESH_REPLICAS", "1"))
    churn_ops = int(os.environ.get("BENCH_MESH_CHURN_OPS", "400"))
    need = n_shards * n_replicas
    if len(jax.devices()) < need:
        log(f"[c11_mesh] SKIP: {need} devices needed, "
            f"{len(jax.devices())} present (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} on CPU)")
        return {"skipped": True, "devices": len(jax.devices())}
    name = f"c11_mesh_{n_subs}x{n_replicas}r{n_shards}s"
    mesh = make_mesh(n_replicas, n_shards)

    def mk(tf, rid, inc=0):
        return Route(matcher=RouteMatcher.from_topic_filter(tf),
                     broker_id=0, receiver_id=rid, deliverer_key="d0",
                     incarnation=inc)

    t0 = time.perf_counter()
    tries = workloads.config_multi_tenant(
        n_tenants=max(n_shards * 4,
                      int(os.environ.get("BENCH_MESH_TENANTS", "64"))),
        total_subs=n_subs, seed=SEED)
    # hot tenant to replicate across every shard: a mid-rank tenant —
    # big enough to matter, small enough that S physical copies don't
    # dominate the per-shard byte budget (tenant0 under Zipf is ~20%)
    hot = sorted(tries, key=lambda t: -len(tries[t]))[
        min(7, len(tries) - 1)]
    build_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    m = MeshMatcher.from_tries(tries, mesh=mesh, match_cache=False,
                               replicate={hot})
    install_s = time.perf_counter() - t0
    rebuild_s = m._last_compile_s
    tables = m._base_ct
    logical = sum(len(t) for t in tries.values())
    log(f"[{name}] base: gen {build_s:.1f}s, compile+install "
        f"{install_s:.1f}s (mesh rebuild {rebuild_s:.1f}s), "
        f"logical_subs={logical} hot={hot} ({len(tries[hot])} subs "
        f"replicated x{n_shards})")

    # --- capacity: per-shard padded bytes vs the planner prediction ----
    db = tables.device_bytes()
    worst = max(p["padded_bytes"] for p in db["per_shard"])
    slots_ref = max(1, max(ct.n_slots for ct in tables.compiled))
    n_max = max(ct.node_tab.shape[0] for ct in tables.compiled)
    e_max = max(1, max(
        int(np.count_nonzero(ct.edge_tab.reshape(-1, 4)[:, 0] >= 0))
        for ct in tables.compiled))
    buckets = tables.edge_tab.shape[1]
    planner = CapacityPlanner(
        nodes_per_sub=n_max / slots_ref, edges_per_sub=e_max / slots_ref,
        slots_per_sub=1.0,
        edge_load=e_max / (buckets * tables.probe_len),
        calibrated_from=f"c11:{slots_ref}subs/shard")
    fits = planner.fits(slots_ref * n_shards, mesh=(n_replicas, n_shards),
                        probe_len=tables.probe_len)
    predicted = fits["tables"]["total"]
    cap_ok = worst <= predicted

    # --- serving: async mesh match latency through the dispatch ring ---
    ledger = OBS.profiler.ledger
    compiles0, bumps0 = m.compile_count, ledger.generation_bumps
    tenants = sorted(tries)
    topics = workloads.probe_topics(1024, seed=SEED + 1)
    batch = 256
    rng = np.random.default_rng(SEED)

    def probe_batch(i, tenant=None):
        rows = topics[(i * batch) % 512:(i * batch) % 512 + batch]
        ts = ([tenant] * batch if tenant else
              [tenants[int(j)] for j in rng.integers(0, len(tenants),
                                                     batch)])
        return list(zip(ts, rows))

    async def serve():
        match_lat, hot_lat, patch_lat = [], [], []
        for wb in range(2):     # warm the grid shapes + scatter jits
            await m.match_batch_async(probe_batch(wb))
        # the hot-tenant batch concentrates rows into fewer slots → a
        # different pow2 grid shape; warm it too or its first serve
        # pays the XLA trace inside the measured window
        await m.match_batch_async(probe_batch(0, tenant=hot))
        m.add_route(hot, mk("bench/mesh/warm/+", "w0"))
        m._flush_patches()
        added = []
        for i in range(churn_ops):
            tf = f"bench/mesh/{i}/+"
            tenant = tenants[i % len(tenants)]
            s0 = time.perf_counter()
            m.add_route(tenant, mk(tf, f"c{i}", inc=1))
            m._flush_patches()
            patch_lat.append(time.perf_counter() - s0)
            added.append((tenant, tf, f"c{i}"))
            if i % 8 == 4:
                s0 = time.perf_counter()
                await m.match_batch_async(probe_batch(i))
                match_lat.append(time.perf_counter() - s0)
            if i % 16 == 8:
                s0 = time.perf_counter()
                await m.match_batch_async(probe_batch(i, tenant=hot))
                hot_lat.append(time.perf_counter() - s0)
        for tenant, tf, rid in added[:churn_ops // 2]:
            s0 = time.perf_counter()
            m.remove_route(tenant, RouteMatcher.from_topic_filter(tf),
                           (0, rid, "d0"), incarnation=1)
            m._flush_patches()
            patch_lat.append(time.perf_counter() - s0)
        return match_lat, hot_lat, patch_lat

    match_lat, hot_lat, patch_lat = asyncio.run(serve())

    # --- oracle parity after the storm ---------------------------------
    probe = probe_batch(3)[:128]
    probe += [(tenants[i % len(tenants)], f"bench/mesh/{i}/x")
              for i in range(0, churn_ops, 7)]
    got = m.match_batch(probe)
    want = m.match_from_tries(probe)

    def canon(r):
        return (sorted((x.matcher.mqtt_topic_filter, x.receiver_url)
                       for x in r.normal),
                {f: sorted(x.receiver_url for x in ms)
                 for f, ms in r.groups.items()})
    parity = all(canon(a) == canon(b) for a, b in zip(got, want))

    # --- expand A/B: device-bucketed serve vs host-expansion serve -----
    # (ISSUE 19) same pre-generated batches through the full serving path
    # under BIFROMQ_DEVICE_EXPAND=0 (legacy psum merge + host expansion)
    # vs =1 (walk-only step + device expand step returning per-peer
    # buckets, no full-grid host merge). The common MatchedRoutes
    # materialization dilutes the ratio — the undiluted kernel-level A/B
    # is config 2's expand_ab record.
    expand_ab = None
    if EXPAND_AB_MODE:
        ab_iters = int(os.environ.get("BENCH_MESH_AB_ITERS", "8"))
        ab_batches = [probe_batch(100 + i) for i in range(ab_iters)]
        prev_mode = os.environ.get("BIFROMQ_DEVICE_EXPAND")

        def _serve_leg(mode):
            os.environ["BIFROMQ_DEVICE_EXPAND"] = mode
            m.match_batch(ab_batches[0])   # warm this mode's traces
            n = 0
            s0 = time.perf_counter()
            for b in ab_batches:
                for r in m.match_batch(b):
                    n += len(r.normal) + sum(len(ms) for ms
                                             in r.groups.values())
            return n, time.perf_counter() - s0

        try:
            host_n, host_s = _serve_leg("0")
            dev_n, dev_s = _serve_leg("1")
        finally:
            if prev_mode is None:
                os.environ.pop("BIFROMQ_DEVICE_EXPAND", None)
            else:
                os.environ["BIFROMQ_DEVICE_EXPAND"] = prev_mode
        expand_ab = {
            "device_matched_routes_per_s": round(dev_n / dev_s, 1),
            "host_matched_routes_per_s": round(host_n / host_s, 1),
            "speedup": round((dev_n / dev_s)
                             / max(1e-9, host_n / host_s), 2),
            "route_count_parity": host_n == dev_n,
            "device_peer_buckets": m.last_expanded is not None,
            "iters": ab_iters,
            "batch": batch,
            "basis": ("full mesh serve incl host MatchedRoutes"
                      " materialization (common to both legs)"),
        }
        log(f"[{name}] expand-ab {json.dumps(expand_ab)}")

    def pct(xs, q):
        return round(float(np.percentile(np.array(xs or [0.0]), q)) * 1e3,
                     3)
    patch_p99 = pct(patch_lat, 99)
    out = {
        "n_subs": n_subs,
        "logical_subs": logical,
        "mesh": {"replicas": n_replicas, "shards": n_shards},
        "build_s": round(build_s, 1),
        "mesh_rebuild_s": round(rebuild_s, 2),
        "capacity": {
            "worst_shard_padded_bytes": worst,
            "predicted_per_shard_bytes": predicted,
            "per_shard_under_prediction": cap_ok,
            "pad_waste_ratio": db["pad_waste_ratio"],
            "per_shard": db["per_shard"],
        },
        "match_ms": {"batch": batch, "p50": pct(match_lat, 50),
                     "p99": pct(match_lat, 99)},
        "hot_tenant_fanout_ms": {"tenant": hot, "p50": pct(hot_lat, 50),
                                 "p99": pct(hot_lat, 99)},
        "patch_apply_ms": {"p50": pct(patch_lat, 50), "p99": patch_p99},
        "patch_vs_rebuild_speedup": round(
            rebuild_s / max(1e-9, patch_p99 / 1e3), 1),
        "churn_ops": len(patch_lat),
        "full_rebuilds_in_window": m.compile_count - compiles0,
        "generation_bumps_in_window": ledger.generation_bumps - bumps0,
        "oracle_parity": parity,
        "expand_ab": expand_ab,
        "patch_flushes": m.patch_flushes,
        "patch_fallbacks": m.patch_fallbacks,
        "shard_breakers": [br.state if br else None
                           for br in m.shard_breakers],
    }
    log(f"[{name}] {json.dumps(out)}")
    return out


def bench_config12():
    """Config 12 — c12_reshard (ISSUE 17): live tenant migration vs the
    full mesh rebuild. A Zipf-skewed population on a replicas x shards
    mesh; the whale tenant live-migrates off its hot shard through the
    begin/copy/ready/cutover/tombstone ladder while async match batches
    keep serving THROUGH the dual-serve window. Reports migration
    wall-clock vs the mesh rebuild (the zero-rebuild dividend), match
    p50/p99 during the window, skew before/after, and the zero-rebuild /
    zero-generation-bump acceptance bits. Stamps record["reshard"]."""
    import asyncio

    from bifromq_tpu import workloads
    from bifromq_tpu.models.oracle import Route
    from bifromq_tpu.obs import OBS
    from bifromq_tpu.parallel.reshard import ShardLoadModel
    from bifromq_tpu.parallel.sharded import MeshMatcher, make_mesh
    from bifromq_tpu.types import RouteMatcher

    import jax

    n_subs = int(os.environ.get("BENCH_RESHARD_SUBS", "200000"))
    n_shards = int(os.environ.get("BENCH_RESHARD_SHARDS", "8"))
    n_replicas = int(os.environ.get("BENCH_RESHARD_REPLICAS", "1"))
    chunk = int(os.environ.get("BENCH_RESHARD_CHUNK", "256"))
    need = n_shards * n_replicas
    if len(jax.devices()) < need:
        log(f"[c12_reshard] SKIP: {need} devices needed, "
            f"{len(jax.devices())} present (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} on CPU)")
        return {"skipped": True, "devices": len(jax.devices())}
    name = f"c12_reshard_{n_subs}x{n_replicas}r{n_shards}s"
    mesh = make_mesh(n_replicas, n_shards)

    def mk(tf, rid):
        return Route(matcher=RouteMatcher.from_topic_filter(tf),
                     broker_id=0, receiver_id=rid, deliverer_key="d0",
                     incarnation=0)

    tries = workloads.config_multi_tenant(
        n_tenants=max(n_shards * 4,
                      int(os.environ.get("BENCH_RESHARD_TENANTS", "64"))),
        total_subs=n_subs, seed=SEED)
    whale = max(tries, key=lambda t: len(tries[t]))
    t0 = time.perf_counter()
    m = MeshMatcher.from_tries(tries, mesh=mesh, match_cache=False)
    install_s = time.perf_counter() - t0
    rebuild_s = m._last_compile_s
    m.query_heat[whale] = 65536
    tables = m._base_ct
    tenants = sorted(tries)
    logical = sum(len(t) for t in tries.values())
    src = tables.shard_of(whale)
    per_shard = [0] * n_shards
    for t in tenants:
        per_shard[tables.shard_of(t)] += len(tries[t])
    dst = min((s for s in range(n_shards) if s != src),
              key=lambda s: per_shard[s])
    log(f"[{name}] base: compile+install {install_s:.1f}s (mesh rebuild "
        f"{rebuild_s:.1f}s), logical_subs={logical}, whale={whale} "
        f"({len(tries[whale])} subs) shard{src} -> shard{dst}")

    model = ShardLoadModel()
    skew0 = model.skew(model.rows(m))
    topics = workloads.probe_topics(1024, seed=SEED + 1)
    batch = 256
    rng = np.random.default_rng(SEED)

    def probe_batch(i):
        rows = topics[(i * batch) % 512:(i * batch) % 512 + batch]
        return [(tenants[int(j)], t) for j, t in
                zip(rng.integers(0, len(tenants), batch), rows)]

    ledger = OBS.profiler.ledger
    compiles0, bumps0 = m.compile_count, ledger.generation_bumps

    async def migrate_and_serve():
        for wb in range(2):      # warm grid shapes outside the window
            await m.match_batch_async(probe_batch(wb))
        window_lat = []
        t0 = time.perf_counter()
        mig = m.migrate_tenant(whale, src, dst, run=False)
        i = 0
        while mig.state == "copying":
            done = mig.step(chunk)
            s0 = time.perf_counter()
            await m.match_batch_async(probe_batch(i))
            window_lat.append(time.perf_counter() - s0)
            i += 1
            if done:
                break
        # dual-serve window: both shards answer for the whale
        s0 = time.perf_counter()
        await m.match_batch_async(probe_batch(i))
        window_lat.append(time.perf_counter() - s0)
        mig.cutover()
        while not mig.finish():
            await asyncio.sleep(0)
        migrate_s = time.perf_counter() - t0
        return mig, migrate_s, window_lat

    mig, migrate_s, window_lat = asyncio.run(migrate_and_serve())
    # the ladder's own cost: the window wall-clock minus the serving
    # batches deliberately interleaved into it (those are the point of a
    # LIVE migration, but they are serving time, not migration time)
    ladder_s = max(1e-9, migrate_s - sum(window_lat))
    skew1 = model.skew(model.rows(m))

    probe = probe_batch(5)[:192]
    got = m.match_batch(probe)
    want = m.match_from_tries(probe)

    def canon(r):
        return (sorted((x.matcher.mqtt_topic_filter, x.receiver_url)
                       for x in r.normal),
                {f: sorted(x.receiver_url for x in ms)
                 for f, ms in r.groups.items()})
    parity = all(canon(a) == canon(b) for a, b in zip(got, want))

    def pct(xs, q):
        return round(float(np.percentile(np.array(xs or [0.0]), q)) * 1e3,
                     3)
    out = {
        "n_subs": n_subs,
        "logical_subs": logical,
        "mesh": {"replicas": n_replicas, "shards": n_shards},
        "mesh_rebuild_s": round(rebuild_s, 2),
        "whale": {"tenant": whale, "subs": len(tries[whale]),
                  "src": src, "dst": dst},
        "migrate_s": round(migrate_s, 3),
        "migrate_ladder_s": round(ladder_s, 3),
        "migrated_routes": mig.copied_n,
        "migrate_vs_rebuild_speedup": round(rebuild_s / ladder_s, 1),
        "match_during_window_ms": {"batch": batch,
                                   "p50": pct(window_lat, 50),
                                   "p99": pct(window_lat, 99)},
        "skew": {"before": round(skew0, 3), "after": round(skew1, 3)},
        "full_rebuilds_in_window": m.compile_count - compiles0,
        "generation_bumps_in_window": ledger.generation_bumps - bumps0,
        "oracle_parity": parity,
        "patch_fallbacks": m.patch_fallbacks,
        "map_version": tables.map_version,
    }
    log(f"[{name}] {json.dumps(out)}")
    return out


def bench_broker():
    """End-to-end MQTT broker throughput over loopback TCP: QoS0/QoS1
    publish → dist match (device matcher) → local fan-out → delivery.
    The BROKER-plane number (supplement to the match-kernel configs);
    enable with "b" in BENCH_CONFIGS."""
    import asyncio

    from bifromq_tpu.mqtt.broker import MQTTBroker
    from bifromq_tpu.mqtt.client import MQTTClient

    n_subs = int(os.environ.get("BENCH_BROKER_SUBS", "20"))
    n_msgs = int(os.environ.get("BENCH_BROKER_MSGS", "2000"))
    n_pubs = max(1, int(os.environ.get("BENCH_BROKER_PUBS", "4")))

    from bifromq_tpu.plugin.settings import DefaultSettingProvider, Setting

    class BenchSettings(DefaultSettingProvider):
        """Raise the per-session publish-rate guard (MsgPubPerSec defaults
        to 200/s — the throughput bench would trip ExceedPubRate)."""

        def provide(self, setting, tenant_id):
            if setting is Setting.MsgPubPerSec:
                return 100_000_000
            return super().provide(setting, tenant_id)

    # per-stage latency breakdown (ISSUE 2): the hot path feeds the
    # always-on stage histograms (ingest / queue_wait / device / deliver,
    # + rpc in clustered mode) whether or not span sampling is enabled —
    # reset here so the breakdown covers exactly this run
    from bifromq_tpu.utils.metrics import MATCH_CACHE, STAGES
    STAGES.reset()
    MATCH_CACHE.reset()
    # ISSUE 20: e2e delivery-latency plane — reset so the per-qos
    # publish->deliver rollup stamped below covers exactly this run
    from bifromq_tpu.obs import OBS
    OBS.e2e.reset()

    async def run():
        broker = MQTTBroker(host="127.0.0.1", port=0,
                            settings=BenchSettings())
        await broker.start()
        subs = []
        for i in range(n_subs):
            c = MQTTClient("127.0.0.1", broker.port, client_id=f"bs{i}")
            await c.connect()
            await c.subscribe(f"bench/{i}/t", qos=0)
            subs.append(c)
        pubs = []
        for i in range(n_pubs):
            p = MQTTClient("127.0.0.1", broker.port, client_id=f"bp{i}")
            await p.connect()
            pubs.append(p)
        pub = pubs[0]
        # QoS0 ingest: n_pubs concurrent publishers fire n_msgs total,
        # one matching subscriber each
        per_pub = n_msgs // n_pubs

        async def fire(p, base):
            for i in range(per_pub):
                await p.publish(f"bench/{(base + i) % n_subs}/t", b"x",
                                qos=0)
        t0 = time.perf_counter()
        await asyncio.gather(*[fire(p, j * per_pub)
                               for j, p in enumerate(pubs)])
        sent = per_pub * n_pubs
        # barrier: all deliveries drained
        got = 0
        deadline = asyncio.get_event_loop().time() + 60
        while got < sent and asyncio.get_event_loop().time() < deadline:
            pending = sum(s.messages.qsize() for s in subs)
            if pending >= sent:
                got = pending
                break
            await asyncio.sleep(0.01)
        qos0_dt = time.perf_counter() - t0
        delivered = sum(s.messages.qsize() for s in subs)
        # QoS1 round-trips (ack-gated, serial per publisher)
        t0 = time.perf_counter()
        for i in range(min(n_msgs, 500)):
            await pub.publish(f"bench/{i % n_subs}/t", b"x", qos=1)
        qos1_dt = time.perf_counter() - t0
        for c in subs + pubs:
            await c.disconnect()
        await broker.stop()
        return {
            # honest rate: only messages that actually ARRIVED count
            "qos0_pub_to_deliver_msgs_per_s": round(delivered / qos0_dt, 1),
            "qos0_delivered": delivered,
            "qos0_published": sent,
            "qos1_acked_pubs_per_s": round(min(n_msgs, 500) / qos1_dt, 1),
            "subscribers": n_subs,
            "publishers": n_pubs,
        }

    out = asyncio.run(run())
    out["stage_latency_ms"] = STAGES.snapshot()
    # ISSUE 4: hit rate + dedup ratio next to the stage breakdown — how
    # much of the publish path the match-result cache actually absorbed
    out["match_cache"] = MATCH_CACHE.snapshot()
    # ISSUE 20: per-qos e2e snapshot (p50/p99 publish->deliver + SLO
    # violations) rides the bench record next to the stage breakdown
    out["e2e"] = OBS.e2e.qos_rollup()
    log(f"[broker_e2e] {json.dumps(out)}")
    return out


def main():
    import subprocess

    # the bench honors a JAX_PLATFORMS pin exactly like every other
    # entrypoint (env alone doesn't beat the sitecustomize plugin) — the
    # probe below and the real run must agree on the platform
    from bifromq_tpu.utils.jaxenv import pin_jax_platform
    pin_jax_platform()

    # device-init watchdog: a dead axon tunnel makes jax.devices() hang
    # FOREVER inside C++ PJRT init (uninterruptible by signals) — probe
    # in a SUBPROCESS with a hard timeout so the driver gets a clean
    # failure line instead of a wedged run (BENCH_r02 died this way).
    # The tunnel also flaps for stretches (r3 observed multi-hour
    # outages), so keep re-probing for BENCH_DEVICE_WAIT seconds before
    # giving up — a patient bench beats an rc=1 round record.
    timeout_s = int(os.environ.get("BENCH_DEVICE_TIMEOUT", "180"))
    wait_s = int(os.environ.get("BENCH_DEVICE_WAIT", "900"))
    deadline = time.time() + wait_s
    attempt = 0
    same_err = 0
    last_err = None
    while True:
        attempt += 1
        try:
            # the probe must honor a JAX_PLATFORMS pin the same way our
            # entrypoints do (env alone doesn't beat the sitecustomize-
            # registered plugin; the config knob does)
            subprocess.run(
                [sys.executable, "-c",
                 "from bifromq_tpu.utils.jaxenv import pin_jax_platform; "
                 "pin_jax_platform(); import jax; jax.devices()"],
                timeout=timeout_s, check=True, capture_output=True,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            break
        except (subprocess.TimeoutExpired,
                subprocess.CalledProcessError) as e:
            detail = ""
            if isinstance(e, subprocess.CalledProcessError) and e.stderr:
                detail = " :: " + e.stderr.decode(
                    "utf-8", "replace").strip()[-400:]
            # a tunnel flap looks like a timeout or a changing stderr; the
            # SAME CalledProcessError stderr over and over is a permanent
            # failure (ImportError, bad platform pin) — fail fast instead
            # of burning the whole wait window on it
            if isinstance(e, subprocess.CalledProcessError):
                same_err = same_err + 1 if detail == last_err else 1
                last_err = detail
                if same_err >= 5:
                    deadline = 0.0
            remaining = deadline - time.time()
            if remaining <= 0:
                msg = (f"jax device init failed/hung through {attempt} "
                       f"probes over {wait_s}s ({type(e).__name__}) — "
                       f"TPU tunnel down?{detail}")
                log(f"FATAL: {msg}")
                # degrade to the last-known-good record, clearly marked
                # stale, instead of an rc=1 round record (VERDICT r4 #5:
                # three rounds of driver records lost to tunnel flaps)
                try:
                    with open(LAST_GOOD_PATH) as f:
                        lg = json.load(f)
                    lg["stale"] = True
                    lg["stale_reason"] = msg[:300]
                    # ISSUE 3 satellite: a degraded run still localizes
                    # regressions — surface the last-known per-stage
                    # breakdown and the live device gauges (which show
                    # exactly why the device path is down), tagged stale,
                    # instead of only the headline number
                    stage = lg.get("stage_latency_ms")
                    if stage:
                        log("stale stage breakdown: "
                            f"{json.dumps(stage)}")
                    # keep the last-good record's REAL device gauges
                    # (tagged stale); the current process never ran the
                    # broker, so its own probe only documents why the
                    # device is unreachable — stderr, not the record
                    if isinstance(lg.get("device"), dict):
                        lg["device"]["stale"] = True
                        log("stale device gauges (last good): "
                            f"{json.dumps(lg['device'])}")
                    try:
                        from bifromq_tpu.obs import OBS
                        log("device probe now: "
                            f"{json.dumps(OBS.device_snapshot())}")
                    except Exception as dev_e:  # noqa: BLE001
                        log(f"device gauges unavailable: {dev_e!r}")
                    print(json.dumps(lg), flush=True)
                    sys.exit(0)
                except (OSError, ValueError):
                    print(json.dumps({"metric": "device_init", "value": 0,
                                      "unit": "error", "error": msg}),
                          flush=True)
                    sys.exit(1)
            log(f"device probe {attempt} failed ({type(e).__name__}); "
                f"retrying for another {remaining:.0f}s")
            time.sleep(min(30, max(1, remaining)))
    import jax
    log(f"devices: {jax.devices()}")
    results = {}
    if "1" in CONFIGS:
        results["c1"] = bench_config1()
    headline = None
    if "2" in CONFIGS:
        results["c2"] = bench_config2()
        headline = results["c2"]
    if "3" in CONFIGS:
        results["c3"] = bench_config3()
    if "4" in CONFIGS:
        results["c4"] = bench_config4()
    if "5" in CONFIGS:
        results["c5"] = bench_config5()
    if "6" in CONFIGS:
        results["c6"] = bench_config6()
    if "7" in CONFIGS:
        results["c7"] = bench_config7()
    if "8" in CONFIGS:
        results["c8"] = bench_config8()
    if "9" in CONFIGS:
        results["c9"] = bench_config9()
    if "10" in CONFIGS:
        results["c10"] = bench_config10()
    if "11" in CONFIGS:
        results["c11"] = bench_config11()
    if "12" in CONFIGS:
        results["c12"] = bench_config12()
    if "b" in CONFIGS:
        results["broker"] = bench_broker()

    log(f"extras: {json.dumps(results)}")
    stock_topics, stock_routes, basis = load_stock_baseline()
    record = None
    if headline is not None and "routes" in headline:
        # THE honest headline (VERDICT r4 #1): e2e matched routes/s vs the
        # measured stock matched-routes rate, identical c2 workload
        r = headline["routes"]
        value = r["e2e_matched_routes_per_s"]
        record = {
            "metric": f"e2e_matched_routes@{N_SUBS}_wildcard_subs",
            "value": value,
            "unit": "routes/s",
            "vs_baseline": round(value / stock_routes, 3),
            "baseline_basis": basis,
            "stock_matched_routes_per_s": stock_routes,
            "e2e_topics_per_s": r["e2e_topics_per_s"],
            "vs_stock_topics": round(r["e2e_topics_per_s"] / stock_topics,
                                     3),
            "e2e_p50_ms": r["e2e_p50_ms"],
            "e2e_p99_ms": r["e2e_p99_ms"],
        }
    elif headline is not None:
        value = headline["topics_per_s"]
        record = {
            "metric": f"device_match_throughput@{N_SUBS}_wildcard_subs",
            "value": value,
            "unit": "topics/s",
            "vs_baseline": round(value / stock_topics, 3),
            "baseline_basis": basis,
        }
    else:
        # no config-2 run: fall back to any config with a comparable rate
        for key, r in results.items():
            if "topics_per_s" in r:
                record = {
                    "metric": f"device_match_throughput_{key}",
                    "value": r["topics_per_s"],
                    "unit": "topics/s",
                    "vs_baseline": round(r["topics_per_s"] / stock_topics,
                                         3),
                    "baseline_basis": basis,
                }
                break
        else:
            if "c4" in results:
                r = results["c4"]
                record = {
                    "metric": "retained_match_throughput_c4",
                    "value": r.get("filters_per_s", 0.0),
                    "unit": "filters/s",
                    "vs_baseline": round(r.get("filters_per_s", 0.0)
                                         / stock_topics, 3),
                    "baseline_basis": basis,
                }
            else:
                r = results.get("broker", {})
                record = {
                    "metric": "broker_e2e_qos0",
                    "value": r.get("qos0_pub_to_deliver_msgs_per_s", 0.0),
                    "unit": "msgs/s",
                    "vs_baseline": 0.0,
                    "baseline_basis": "broker-plane loopback (no stock "
                                      "broker in image)",
                }
    record["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    record["platform"] = jax.devices()[0].platform
    # ISSUE 6 satellite: stamp the hardware + freshness so CPU-fallback
    # trajectory rounds (the r02–r05 failure mode) are self-describing
    try:
        record["device_kind"] = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — CPU backends may lack the attr
        record["device_kind"] = record["platform"]
    record["stale"] = False
    record["n_subs"] = N_SUBS
    # pipeline A/B next to the headline (ISSUE 6): the dispatch/ready/
    # fetch stage split + the sync-vs-pipelined batch-latency comparison
    if "c7" in results:
        record["pipeline"] = {
            "batch_p99_speedup": results["c7"]["batch_p99_speedup"],
            "sync_batch_p99_ms": results["c7"]["sync"]["batch_p99_ms"],
            "pipelined_batch_p99_ms":
                results["c7"]["pipelined"]["batch_p99_ms"],
            "stage_latency_ms": results["c7"]["stage_latency_ms"],
        }
    # churn cell next to the headline (ISSUE 9): patch-apply latency vs
    # the full rebuild, zero-rebuild/zero-bump window, oracle parity
    if "c8" in results:
        c8 = results["c8"]
        record["churn"] = {
            "n_subs": c8["n_subs"],
            "full_rebuild_s": c8["full_rebuild_s"],
            "patch_apply_ms": c8["patch_apply_ms"],
            "patch_vs_rebuild_speedup": c8["patch_vs_rebuild_speedup"],
            "match_p99_ms_during_churn": c8["match_p99_ms_during_churn"],
            "full_rebuilds_in_window": c8["full_rebuilds_in_window"],
            "generation_bumps_in_window":
                c8["generation_bumps_in_window"],
            "oracle_parity": c8["oracle_parity"],
        }
    # ingest byte-plane cell next to the headline (ISSUE 11): the
    # three-way prep A/B + parity verdict and the profiler's tokenize
    # attribution — every record carries the tokenize story
    if "c9" in results:
        c9 = results["c9"]
        record["tokenize"] = {
            "python_topics_per_s": c9["python_topics_per_s"],
            "native_topics_per_s": c9["native_topics_per_s"],
            "device_topics_per_s": c9["device_topics_per_s"],
            "speedup_native_vs_python": c9["speedup_native_vs_python"],
            "three_way_parity": c9["three_way_parity"],
            "tokenize_stage_on_every_device_batch":
                c9["tokenize_stage_on_every_device_batch"],
        }
    # mixed-workload breakdown next to the headline (ISSUE 13): the
    # retained-flood zero-rebuild verdict, scan parity/latency, drain
    # fairness and share balance under the realistic population
    if "c10" in results:
        c10 = results["c10"]
        record["mixed"] = {
            "n_clients": c10["n_clients"],
            "retained": {k: c10["retained"][k] for k in (
                "flood_ops", "flood_ops_per_s", "full_rebuilds_in_flood",
                "compactions_in_flood", "zero_rebuilds",
                "scan_parity_before_during_after")},
            "scan": c10["scan"],
            "publish_mix": c10["publish_mix"],
            "share_balance": c10["share_balance"],
            "drain_tenant_fair": c10["drain_storm"]["tenant_fair"],
        }
    # sharded-mesh cell next to the headline (ISSUE 15): mesh match
    # latency, per-shard patch p99 under the churn storm, shard count,
    # per-shard bytes vs the planner prediction — the numbers ready to
    # re-run the moment the TPU tunnel returns
    if "c11" in results and not results["c11"].get("skipped"):
        c11 = results["c11"]
        record["mesh"] = {
            "logical_subs": c11["logical_subs"],
            "shards": c11["mesh"]["shards"],
            "replicas": c11["mesh"]["replicas"],
            "match_p50_ms": c11["match_ms"]["p50"],
            "match_p99_ms": c11["match_ms"]["p99"],
            "patch_p99_ms": c11["patch_apply_ms"]["p99"],
            "patch_vs_rebuild_speedup": c11["patch_vs_rebuild_speedup"],
            "full_rebuilds_in_window": c11["full_rebuilds_in_window"],
            "generation_bumps_in_window":
                c11["generation_bumps_in_window"],
            "oracle_parity": c11["oracle_parity"],
            "per_shard_bytes": [p["padded_bytes"] for p in
                                c11["capacity"]["per_shard"]],
            "per_shard_under_prediction":
                c11["capacity"]["per_shard_under_prediction"],
            "hot_tenant_fanout_p99_ms":
                c11["hot_tenant_fanout_ms"]["p99"],
        }
    # elastic-mesh cell (ISSUE 17): live-migration wall-clock vs the
    # full mesh rebuild, match p99 THROUGH the dual-serve window, skew
    # before/after — the zero-rebuild dividend as a standing number
    if "c12" in results and not results["c12"].get("skipped"):
        c12 = results["c12"]
        record["reshard"] = {
            "logical_subs": c12["logical_subs"],
            "shards": c12["mesh"]["shards"],
            "whale_subs": c12["whale"]["subs"],
            "migrate_s": c12["migrate_s"],
            "migrate_ladder_s": c12["migrate_ladder_s"],
            "mesh_rebuild_s": c12["mesh_rebuild_s"],
            "migrate_vs_rebuild_speedup":
                c12["migrate_vs_rebuild_speedup"],
            "match_window_p99_ms": c12["match_during_window_ms"]["p99"],
            "skew_before": c12["skew"]["before"],
            "skew_after": c12["skew"]["after"],
            "full_rebuilds_in_window": c12["full_rebuilds_in_window"],
            "generation_bumps_in_window":
                c12["generation_bumps_in_window"],
            "oracle_parity": c12["oracle_parity"],
        }
    # per-stage p50/p99 next to the headline (ISSUE 2): where the broker
    # plane actually spends its time (queue-wait vs device vs deliver)
    stage = results.get("broker", {}).get("stage_latency_ms")
    if stage:
        record["stage_latency_ms"] = stage
    # match-cache disposition next to the stage breakdown (ISSUE 4)
    mc = results.get("broker", {}).get("match_cache")
    if mc:
        record["match_cache"] = mc
    # device-pipeline gauges next to the headline (ISSUE 3): XLA compile
    # count/time, dispatch queue depth, device memory watermarks — the
    # same "device" section /metrics serves
    try:
        from bifromq_tpu.obs import OBS
        record["device"] = OBS.device_snapshot()
        log(f"device gauges: {json.dumps(record['device'])}")
    except Exception as e:  # noqa: BLE001 — gauges must not fail the bench
        log(f"device gauges unavailable: {e!r}")
    # continuous-profiler snapshot on every record (ISSUE 8): the
    # rtt/kernel split, padding waste / dedup / cache-bypass efficiency
    # and the compile-event ledger — the same data GET /profile serves,
    # so trajectory records stay analyzable post-hoc
    try:
        from bifromq_tpu.obs import OBS
        record["profile"] = OBS.profiler.snapshot(brief=True)
        log(f"profile: {json.dumps(record['profile'])}")
    except Exception as e:  # noqa: BLE001 — must not fail the bench
        log(f"profile snapshot unavailable: {e!r}")
    # capacity accounting next to it (ISSUE 8): model-vs-live parity for
    # every registered matcher + the planner's verdict for the HEADLINE
    # subscription count on this device
    try:
        from bifromq_tpu.obs.capacity import capacity_report
        record["capacity"] = capacity_report(n_subs=N_SUBS)
        cap = record["capacity"]
        log(f"capacity: table_bytes={cap.get('table_bytes')} "
            f"parity_error={cap.get('parity_error')} "
            f"fits={json.dumps(cap.get('fits', {}).get('fused_vmem'))}")
    except Exception as e:  # noqa: BLE001 — must not fail the bench
        log(f"capacity report unavailable: {e!r}")
    # persist the profile into the segment store when one is configured
    # (BIFROMQ_OBS_STORE): post-hoc analysis survives the TPU session
    try:
        from bifromq_tpu.obs import OBS
        if OBS.start_persistence():
            OBS.persist_now()
            OBS.stop_persistence(final_flush=False)
    except Exception as e:  # noqa: BLE001
        log(f"profile persistence failed: {e!r}")
    # persist last-known-good for a real headline only (a partial
    # broker-only or error-path run must never clobber it). A CPU-platform
    # headline IS a valid record — the stock baseline ran on the same
    # host CPU, so vs_baseline stays same-hardware honest and the
    # platform label tells the reader exactly what it is — but it never
    # OVERWRITES a device-measured record.
    if record.get("value", 0) > 0 and "matched_routes" in record["metric"]:
        keep = True
        try:
            with open(LAST_GOOD_PATH) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = None     # nothing recorded yet
        if isinstance(existing, dict):
            if record["platform"] == "cpu" \
                    and existing.get("platform") != "cpu":
                keep = False
            # a small smoke run (BENCH_SUBS down-scaled for a drive-by
            # verification) must never clobber the full-population
            # headline either — the record is only last-KNOWN-GOOD if
            # it measures at least the population the existing one did
            if record.get("n_subs", 0) < existing.get("n_subs", 0):
                keep = False
        if keep:
            try:
                os.makedirs(os.path.dirname(LAST_GOOD_PATH), exist_ok=True)
                with open(LAST_GOOD_PATH, "w") as f:
                    json.dump(record, f)
            except OSError as e:  # noqa: BLE001 — best-effort
                log(f"last_good write failed: {e}")
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
