"""Workload generators for the BASELINE.md measurement configs.

The five configs (BASELINE.json `configs`):
1. 1 tenant, 10K exact-topic subscriptions
2. 1 tenant, 1M wildcard subscriptions, Zipf-skewed topic tree
3. 1K tenants × 10K subs each, $share fan-out
4. retained: 5M retained topics, wildcard SUBSCRIBE probes
5. 10K tenants, 10M total subs, tenant-sharded across the mesh

Generation is deterministic per seed. Filters are built directly as
RouteMatcher tuples (bypassing string validation) for speed at the 10M scale.
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence, Tuple

from .models.oracle import Route, SubscriptionTrie
from .types import RouteMatcher, RouteMatcherType
from .utils import topic as topic_util


def _zipf_levels(n_levels: int) -> Tuple[List[str], List[float]]:
    """Returns (names, CUMULATIVE weights) — cumulative so random.choices
    skips its per-call accumulate pass (it dominates 10M-scale generation)."""
    names = [f"l{i}" for i in range(n_levels)]
    acc, cum = 0.0, []
    for i in range(n_levels):
        acc += 1.0 / (i + 1)
        cum.append(acc)
    return names, cum


def _mk_matcher(levels: Sequence[str], share_group: str = "",
                ordered: bool = False) -> RouteMatcher:
    if share_group:
        prefix = topic_util.ORDERED_SHARE if ordered else topic_util.UNORDERED_SHARE
        tf = f"{prefix}/{share_group}/" + "/".join(levels)
        return RouteMatcher(
            type=(RouteMatcherType.ORDERED_SHARE if ordered
                  else RouteMatcherType.UNORDERED_SHARE),
            filter_levels=tuple(levels), mqtt_topic_filter=tf,
            group=share_group)
    return RouteMatcher(type=RouteMatcherType.NORMAL,
                        filter_levels=tuple(levels),
                        mqtt_topic_filter="/".join(levels))


def gen_filter_levels(rng: random.Random, names: List[str],
                      weights: List[float], *, max_depth: int = 6,
                      p_plus: float = 0.15, p_hash: float = 0.1) -> List[str]:
    depth = rng.randint(1, max_depth)
    levels = rng.choices(names, cum_weights=weights, k=depth)
    for j in range(depth):
        if rng.random() < p_plus:
            levels[j] = topic_util.SINGLE_WILDCARD
    if rng.random() < p_hash:
        levels.append(topic_util.MULTI_WILDCARD)
    return levels


def gen_topic_levels(rng: random.Random, names: List[str],
                     weights: List[float], *, max_depth: int = 6) -> List[str]:
    depth = rng.randint(1, max_depth)
    return rng.choices(names, cum_weights=weights, k=depth)


def config_exact(n_subs: int = 10_000, *, seed: int = 0,
                 persistent_ratio: float = 0.0) -> Dict[str, SubscriptionTrie]:
    """Config 1: one tenant, exact-topic subscriptions."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(max(64, n_subs // 100))
    trie = SubscriptionTrie()
    for i in range(n_subs):
        levels = gen_topic_levels(rng, names, weights)
        broker = 1 if rng.random() < persistent_ratio else 0
        trie.add(Route(matcher=_mk_matcher(levels), broker_id=broker,
                       receiver_id=f"r{i}", deliverer_key=f"d{i % 64}"))
    return {"tenant0": trie}


def config_wildcard(n_subs: int = 1_000_000, *, seed: int = 0,
                    n_level_names: int = 1000, max_depth: int = 6,
                    persistent_ratio: float = 0.1
                    ) -> Dict[str, SubscriptionTrie]:
    """Config 2: one tenant, wildcard-heavy Zipf subscriptions."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    trie = SubscriptionTrie()
    for i in range(n_subs):
        levels = gen_filter_levels(rng, names, weights, max_depth=max_depth)
        broker = 1 if rng.random() < persistent_ratio else 0
        trie.add(Route(matcher=_mk_matcher(levels), broker_id=broker,
                       receiver_id=f"r{i}", deliverer_key=f"d{i % 64}"))
    return {"tenant0": trie}


def config_shared(n_tenants: int = 1000, subs_per_tenant: int = 10_000, *,
                  seed: int = 0, n_groups: int = 16
                  ) -> Dict[str, SubscriptionTrie]:
    """Config 3: many tenants, $share shared-subscription fan-out."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(500)
    out: Dict[str, SubscriptionTrie] = {}
    for t in range(n_tenants):
        trie = SubscriptionTrie()
        for i in range(subs_per_tenant):
            levels = gen_filter_levels(rng, names, weights, p_plus=0.05,
                                       p_hash=0.05)
            group = f"g{rng.randrange(n_groups)}"
            ordered = rng.random() < 0.3
            trie.add(Route(matcher=_mk_matcher(levels, group, ordered),
                           broker_id=0, receiver_id=f"t{t}m{i}",
                           deliverer_key=f"d{i % 64}"))
        out[f"tenant{t}"] = trie
    return out


def config_multi_tenant(n_tenants: int = 10_000, total_subs: int = 10_000_000,
                        *, seed: int = 0) -> Dict[str, SubscriptionTrie]:
    """Config 5: tenant-sharded: Zipf tenant sizes summing to total_subs."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(1000)
    tenant_weights = [1.0 / (i + 1) for i in range(n_tenants)]
    wsum = sum(tenant_weights)
    out: Dict[str, SubscriptionTrie] = {}
    for t in range(n_tenants):
        n = max(1, int(total_subs * tenant_weights[t] / wsum))
        trie = SubscriptionTrie()
        for i in range(n):
            levels = gen_filter_levels(rng, names, weights)
            trie.add(Route(matcher=_mk_matcher(levels), broker_id=0,
                           receiver_id=f"t{t}r{i}", deliverer_key=f"d{i % 64}"))
        out[f"tenant{t}"] = trie
    return out


def config_retained(n_topics: int = 5_000_000, *, seed: int = 0,
                    n_level_names: int = 1000, max_depth: int = 6
                    ) -> Dict[str, List[List[str]]]:
    """Config 4: retained-message store — concrete topics per tenant.

    The retained path stores *topics* (not filters) and probes with wildcard
    FILTERS (roles-swapped walk, models/retained.py); returns unique topic
    level-lists for one tenant.
    """
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    seen = set()
    topics: List[List[str]] = []
    for i in range(n_topics):
        levels = gen_topic_levels(rng, names, weights, max_depth=max_depth)
        if tuple(levels) in seen:
            # disambiguate with a device-id tail (realistic retained-topic
            # shape: per-device leaves under shared prefixes); may exceed
            # max_depth by one level
            levels = levels + [f"d{i}"]
        seen.add(tuple(levels))
        topics.append(levels)
    return {"tenant0": topics}


def probe_filters(n: int, *, seed: int = 2, n_level_names: int = 1000,
                  max_depth: int = 6) -> List[List[str]]:
    """Wildcard SUBSCRIBE filters probing the retained store (config 4)."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    return [gen_filter_levels(rng, names, weights, max_depth=max_depth)
            for _ in range(n)]


def probe_topics(n: int, *, seed: int = 1, n_level_names: int = 1000,
                 max_depth: int = 6) -> List[List[str]]:
    """Concrete PUBLISH topics drawn from the same Zipf tree."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    return [gen_topic_levels(rng, names, weights, max_depth=max_depth)
            for _ in range(n)]


# ---------------------- topic-diversity generator (ISSUE 11) ----------------
#
# The paper benchmarks its broker against tenant populations whose TOPIC
# SHAPES differ wildly — short flat telemetry channels, deep per-device
# vehicle paths, i18n retail catalogs, $SYS operational streams — while
# `probe_topics` emits uniform `l<i>/l<j>/...` strings whose levels are
# 2-5 ASCII bytes. Tokenizer cost is byte- and level-count-shaped, so the
# ingest bench (config 9) must measure on realistic strings, not
# `bench/a/b`. Profiles mix level counts, level byte lengths, multi-byte
# UTF-8 density, numeric device-id leaves, and the '$'-root class.

TENANT_TOPIC_PROFILES: dict = {
    # flat sensor telemetry: shallow, short ASCII levels, numeric leaf
    "telemetry": dict(weight=0.40, depth=(3, 6), seg_len=(3, 10),
                      unicode_p=0.0, numeric_leaf_p=0.8, sys_p=0.0),
    # fleet/vehicle: deep paths, mid-size levels, uuid-ish leaves
    "fleet": dict(weight=0.25, depth=(6, 12), seg_len=(6, 18),
                  unicode_p=0.02, numeric_leaf_p=0.5, sys_p=0.0),
    # retail/i18n: shallow but multi-byte-UTF-8-heavy long levels
    "retail_i18n": dict(weight=0.20, depth=(2, 5), seg_len=(4, 24),
                        unicode_p=0.6, numeric_leaf_p=0.1, sys_p=0.0),
    # operational $SYS streams (exercises the sys-root walk rule)
    "sysmon": dict(weight=0.05, depth=(2, 4), seg_len=(4, 12),
                   unicode_p=0.0, numeric_leaf_p=0.0, sys_p=1.0),
    # adversarial edge: empty levels / separator runs / deep shapes
    "edge": dict(weight=0.10, depth=(1, 15), seg_len=(0, 8),
                 unicode_p=0.1, numeric_leaf_p=0.2, sys_p=0.0),
}

_UNICODE_SEGS = ["日本語", "センサー", "größe", "müller", "caféteria",
                 "датчик", "température", "aßßen", "चैनल", "중계기"]
_ASCII = "abcdefghijklmnopqrstuvwxyz"


def diverse_topics(n: int, *, seed: int = 0,
                   profiles: dict = None) -> List[str]:
    """``n`` topic STRINGS drawn from the tenant profiles above (byte
    plane: the serving path ships strings/bytes, so the generator does
    too). Deterministic per seed; used by bench config 9 and the
    ingest tier-2 gate."""
    rng = random.Random(seed)
    profs = profiles or TENANT_TOPIC_PROFILES
    names = list(profs)
    cum: List[float] = []
    acc = 0.0
    for p in names:
        acc += profs[p]["weight"]
        cum.append(acc)
    out: List[str] = []
    for _ in range(n):
        p = profs[rng.choices(names, cum_weights=cum, k=1)[0]]
        depth = rng.randint(*p["depth"])
        levels: List[str] = []
        for j in range(depth):
            lo, hi = p["seg_len"]
            seg_len = rng.randint(lo, hi)
            if seg_len == 0:
                levels.append("")       # empty level / separator run
            elif rng.random() < p["unicode_p"]:
                levels.append(rng.choice(_UNICODE_SEGS))
            else:
                levels.append("".join(rng.choice(_ASCII)
                                      for _ in range(seg_len)))
        if p["sys_p"] and rng.random() < p["sys_p"]:
            levels.insert(0, "$SYS")
        if levels and rng.random() < p["numeric_leaf_p"]:
            levels.append(f"d{rng.randrange(1 << 20)}")
        out.append("/".join(levels) if levels else "x")
    return out
