"""Workload generators for the BASELINE.md measurement configs.

The five configs (BASELINE.json `configs`):
1. 1 tenant, 10K exact-topic subscriptions
2. 1 tenant, 1M wildcard subscriptions, Zipf-skewed topic tree
3. 1K tenants × 10K subs each, $share fan-out
4. retained: 5M retained topics, wildcard SUBSCRIBE probes
5. 10K tenants, 10M total subs, tenant-sharded across the mesh

Generation is deterministic per seed. Filters are built directly as
RouteMatcher tuples (bypassing string validation) for speed at the 10M scale.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from .models.oracle import Route, SubscriptionTrie
from .types import RouteMatcher, RouteMatcherType
from .utils import topic as topic_util


def _zipf_levels(n_levels: int) -> Tuple[List[str], List[float]]:
    """Returns (names, CUMULATIVE weights) — cumulative so random.choices
    skips its per-call accumulate pass (it dominates 10M-scale generation)."""
    names = [f"l{i}" for i in range(n_levels)]
    acc, cum = 0.0, []
    for i in range(n_levels):
        acc += 1.0 / (i + 1)
        cum.append(acc)
    return names, cum


def _mk_matcher(levels: Sequence[str], share_group: str = "",
                ordered: bool = False) -> RouteMatcher:
    if share_group:
        prefix = topic_util.ORDERED_SHARE if ordered else topic_util.UNORDERED_SHARE
        tf = f"{prefix}/{share_group}/" + "/".join(levels)
        return RouteMatcher(
            type=(RouteMatcherType.ORDERED_SHARE if ordered
                  else RouteMatcherType.UNORDERED_SHARE),
            filter_levels=tuple(levels), mqtt_topic_filter=tf,
            group=share_group)
    return RouteMatcher(type=RouteMatcherType.NORMAL,
                        filter_levels=tuple(levels),
                        mqtt_topic_filter="/".join(levels))


def gen_filter_levels(rng: random.Random, names: List[str],
                      weights: List[float], *, max_depth: int = 6,
                      p_plus: float = 0.15, p_hash: float = 0.1) -> List[str]:
    depth = rng.randint(1, max_depth)
    levels = rng.choices(names, cum_weights=weights, k=depth)
    for j in range(depth):
        if rng.random() < p_plus:
            levels[j] = topic_util.SINGLE_WILDCARD
    if rng.random() < p_hash:
        levels.append(topic_util.MULTI_WILDCARD)
    return levels


def gen_topic_levels(rng: random.Random, names: List[str],
                     weights: List[float], *, max_depth: int = 6) -> List[str]:
    depth = rng.randint(1, max_depth)
    return rng.choices(names, cum_weights=weights, k=depth)


def config_exact(n_subs: int = 10_000, *, seed: int = 0,
                 persistent_ratio: float = 0.0) -> Dict[str, SubscriptionTrie]:
    """Config 1: one tenant, exact-topic subscriptions."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(max(64, n_subs // 100))
    trie = SubscriptionTrie()
    for i in range(n_subs):
        levels = gen_topic_levels(rng, names, weights)
        broker = 1 if rng.random() < persistent_ratio else 0
        trie.add(Route(matcher=_mk_matcher(levels), broker_id=broker,
                       receiver_id=f"r{i}", deliverer_key=f"d{i % 64}"))
    return {"tenant0": trie}


def config_wildcard(n_subs: int = 1_000_000, *, seed: int = 0,
                    n_level_names: int = 1000, max_depth: int = 6,
                    persistent_ratio: float = 0.1
                    ) -> Dict[str, SubscriptionTrie]:
    """Config 2: one tenant, wildcard-heavy Zipf subscriptions."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    trie = SubscriptionTrie()
    for i in range(n_subs):
        levels = gen_filter_levels(rng, names, weights, max_depth=max_depth)
        broker = 1 if rng.random() < persistent_ratio else 0
        trie.add(Route(matcher=_mk_matcher(levels), broker_id=broker,
                       receiver_id=f"r{i}", deliverer_key=f"d{i % 64}"))
    return {"tenant0": trie}


def config_shared(n_tenants: int = 1000, subs_per_tenant: int = 10_000, *,
                  seed: int = 0, n_groups: int = 16
                  ) -> Dict[str, SubscriptionTrie]:
    """Config 3: many tenants, $share shared-subscription fan-out."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(500)
    out: Dict[str, SubscriptionTrie] = {}
    for t in range(n_tenants):
        trie = SubscriptionTrie()
        for i in range(subs_per_tenant):
            levels = gen_filter_levels(rng, names, weights, p_plus=0.05,
                                       p_hash=0.05)
            group = f"g{rng.randrange(n_groups)}"
            ordered = rng.random() < 0.3
            trie.add(Route(matcher=_mk_matcher(levels, group, ordered),
                           broker_id=0, receiver_id=f"t{t}m{i}",
                           deliverer_key=f"d{i % 64}"))
        out[f"tenant{t}"] = trie
    return out


def config_multi_tenant(n_tenants: int = 10_000, total_subs: int = 10_000_000,
                        *, seed: int = 0) -> Dict[str, SubscriptionTrie]:
    """Config 5: tenant-sharded: Zipf tenant sizes summing to total_subs."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(1000)
    tenant_weights = [1.0 / (i + 1) for i in range(n_tenants)]
    wsum = sum(tenant_weights)
    out: Dict[str, SubscriptionTrie] = {}
    for t in range(n_tenants):
        n = max(1, int(total_subs * tenant_weights[t] / wsum))
        trie = SubscriptionTrie()
        for i in range(n):
            levels = gen_filter_levels(rng, names, weights)
            trie.add(Route(matcher=_mk_matcher(levels), broker_id=0,
                           receiver_id=f"t{t}r{i}", deliverer_key=f"d{i % 64}"))
        out[f"tenant{t}"] = trie
    return out


def config_retained(n_topics: int = 5_000_000, *, seed: int = 0,
                    n_level_names: int = 1000, max_depth: int = 6
                    ) -> Dict[str, List[List[str]]]:
    """Config 4: retained-message store — concrete topics per tenant.

    The retained path stores *topics* (not filters) and probes with wildcard
    FILTERS (roles-swapped walk, models/retained.py); returns unique topic
    level-lists for one tenant.
    """
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    seen = set()
    topics: List[List[str]] = []
    for i in range(n_topics):
        levels = gen_topic_levels(rng, names, weights, max_depth=max_depth)
        if tuple(levels) in seen:
            # disambiguate with a device-id tail (realistic retained-topic
            # shape: per-device leaves under shared prefixes); may exceed
            # max_depth by one level
            levels = levels + [f"d{i}"]
        seen.add(tuple(levels))
        topics.append(levels)
    return {"tenant0": topics}


def probe_filters(n: int, *, seed: int = 2, n_level_names: int = 1000,
                  max_depth: int = 6) -> List[List[str]]:
    """Wildcard SUBSCRIBE filters probing the retained store (config 4)."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    return [gen_filter_levels(rng, names, weights, max_depth=max_depth)
            for _ in range(n)]


def probe_topics(n: int, *, seed: int = 1, n_level_names: int = 1000,
                 max_depth: int = 6) -> List[List[str]]:
    """Concrete PUBLISH topics drawn from the same Zipf tree."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    return [gen_topic_levels(rng, names, weights, max_depth=max_depth)
            for _ in range(n)]


# ---------------------- topic-diversity generator (ISSUE 11) ----------------
#
# The paper benchmarks its broker against tenant populations whose TOPIC
# SHAPES differ wildly — short flat telemetry channels, deep per-device
# vehicle paths, i18n retail catalogs, $SYS operational streams — while
# `probe_topics` emits uniform `l<i>/l<j>/...` strings whose levels are
# 2-5 ASCII bytes. Tokenizer cost is byte- and level-count-shaped, so the
# ingest bench (config 9) must measure on realistic strings, not
# `bench/a/b`. Profiles mix level counts, level byte lengths, multi-byte
# UTF-8 density, numeric device-id leaves, and the '$'-root class.

TENANT_TOPIC_PROFILES: dict = {
    # flat sensor telemetry: shallow, short ASCII levels, numeric leaf
    "telemetry": dict(weight=0.40, depth=(3, 6), seg_len=(3, 10),
                      unicode_p=0.0, numeric_leaf_p=0.8, sys_p=0.0),
    # fleet/vehicle: deep paths, mid-size levels, uuid-ish leaves
    "fleet": dict(weight=0.25, depth=(6, 12), seg_len=(6, 18),
                  unicode_p=0.02, numeric_leaf_p=0.5, sys_p=0.0),
    # retail/i18n: shallow but multi-byte-UTF-8-heavy long levels
    "retail_i18n": dict(weight=0.20, depth=(2, 5), seg_len=(4, 24),
                        unicode_p=0.6, numeric_leaf_p=0.1, sys_p=0.0),
    # operational $SYS streams (exercises the sys-root walk rule)
    "sysmon": dict(weight=0.05, depth=(2, 4), seg_len=(4, 12),
                   unicode_p=0.0, numeric_leaf_p=0.0, sys_p=1.0),
    # adversarial edge: empty levels / separator runs / deep shapes
    "edge": dict(weight=0.10, depth=(1, 15), seg_len=(0, 8),
                 unicode_p=0.1, numeric_leaf_p=0.2, sys_p=0.0),
}

_UNICODE_SEGS = ["日本語", "センサー", "größe", "müller", "caféteria",
                 "датчик", "température", "aßßen", "चैनल", "중계기"]
_ASCII = "abcdefghijklmnopqrstuvwxyz"


def diverse_topics(n: int, *, seed: int = 0,
                   profiles: dict = None) -> List[str]:
    """``n`` topic STRINGS drawn from the tenant profiles above (byte
    plane: the serving path ships strings/bytes, so the generator does
    too). Deterministic per seed; used by bench config 9 and the
    ingest tier-2 gate."""
    rng = random.Random(seed)
    profs = profiles or TENANT_TOPIC_PROFILES
    names = list(profs)
    cum: List[float] = []
    acc = 0.0
    for p in names:
        acc += profs[p]["weight"]
        cum.append(acc)
    out: List[str] = []
    for _ in range(n):
        p = profs[rng.choices(names, cum_weights=cum, k=1)[0]]
        depth = rng.randint(*p["depth"])
        levels: List[str] = []
        for j in range(depth):
            lo, hi = p["seg_len"]
            seg_len = rng.randint(lo, hi)
            if seg_len == 0:
                levels.append("")       # empty level / separator run
            elif rng.random() < p["unicode_p"]:
                levels.append(rng.choice(_UNICODE_SEGS))
            else:
                levels.append("".join(rng.choice(_ASCII)
                                      for _ in range(seg_len)))
        if p["sys_p"] and rng.random() < p["sys_p"]:
            levels.insert(0, "$SYS")
        if levels and rng.random() < p["numeric_leaf_p"]:
            levels.append(f"d{rng.randrange(1 << 20)}")
        out.append("/".join(levels) if levels else "x")
    return out


# ---------------------- mixed million-client workload (ISSUE 13) ------------
#
# Configs 1-5 each exercise ONE plane in isolation; real broker
# populations are a MIX — transient and persistent sessions, QoS spread,
# $share worker pools, retained floods, churny connections, reconnect
# drain storms — and the SLO / noisy-neighbor / shed / cache planes only
# mean anything under that diversity. `config_mixed` generates one
# deterministic plan covering all of it; bench config 10 executes the
# plan leg by leg and reports the per-plane breakdown.

def config_mixed(n_clients: int = 1_000_000, *, seed: int = 0,
                 n_tenants: int = 100, persistent_ratio: float = 0.3,
                 share_ratio: float = 0.1, n_groups: int = 16,
                 retained_base: Optional[int] = None,
                 retained_ops: int = 10_000,
                 scan_filters: int = 512,
                 churn_ops: int = 2_048,
                 drain_sessions: int = 256,
                 publishes: int = 4_096) -> dict:
    """One deterministic mixed-workload plan for ``n_clients`` clients.

    Returns a dict of per-plane inputs:

    - ``subscriptions``: per-tenant SubscriptionTrie route table (one
      filter per client; Zipf tenant sizes, ~``persistent_ratio``
      persistent receivers, ~``share_ratio`` $share group members)
    - ``qos_mix``: per-client QoS histogram {0,1,2} (0.7/0.25/0.05)
    - ``retained_seed`` / ``retained_flood``: the retained store's base
      topic population and the SET/CLEAR flood ops (≥ ``retained_ops``,
      re-SET/CLEAR mix with per-device leaf diversity)
    - ``scan_filters``: wildcard SUBSCRIBE filters probing the retained
      store (per tenant)
    - ``publishes``: (tenant, topic, qos) publish stream over the same
      Zipf tree
    - ``session_churn``: ("sub"|"unsub", tenant, filter levels,
      receiver) connect/disconnect route churn
    - ``drain_plan``: (tenant, inbox_id, backlog) reconnect-storm
      population — one HERD tenant holding most sessions plus quiet
      tenants, the shape tenant-fairness must survive
    """
    rng = random.Random(seed)
    names, weights = _zipf_levels(1000)
    tenant_w = [1.0 / (i + 1) for i in range(n_tenants)]
    wsum = sum(tenant_w)
    tenants = [f"tenant{i}" for i in range(n_tenants)]

    subs: Dict[str, SubscriptionTrie] = {}
    qos_mix = {0: 0, 1: 0, 2: 0}
    client = 0
    for ti, tenant in enumerate(tenants):
        n = max(1, int(n_clients * tenant_w[ti] / wsum))
        trie = SubscriptionTrie()
        for i in range(n):
            roll = rng.random()
            qos = 0 if roll < 0.70 else (1 if roll < 0.95 else 2)
            qos_mix[qos] += 1
            levels = gen_filter_levels(rng, names, weights, p_plus=0.10,
                                       p_hash=0.05)
            share = rng.random() < share_ratio
            group = f"g{rng.randrange(n_groups)}" if share else ""
            broker = 1 if (not share
                           and rng.random() < persistent_ratio) else 0
            trie.add(Route(
                matcher=_mk_matcher(levels, group, share
                                    and rng.random() < 0.3),
                broker_id=broker, receiver_id=f"c{client}",
                deliverer_key=f"d{client % 64}"))
            client += 1
        subs[tenant] = trie

    # retained plane: base population + flood (device-leaf diversity,
    # re-SET/CLEAR mix, a '$SYS' slice for the root rules)
    if retained_base is None:
        retained_base = max(1024, n_clients // 10)
    seen = set()
    retained_seed: List[Tuple[str, List[str]]] = []
    for i in range(retained_base):
        tenant = tenants[rng.randrange(n_tenants)]
        levels = gen_topic_levels(rng, names, weights)
        if rng.random() < 0.02:
            levels = ["$SYS"] + levels
        if (tenant, tuple(levels)) in seen:
            levels = levels + [f"d{i}"]
        seen.add((tenant, tuple(levels)))
        retained_seed.append((tenant, levels))
    flood: List[Tuple[str, str, List[str]]] = []
    live = list(retained_seed)
    for i in range(retained_ops):
        roll = rng.random()
        if roll < 0.55 or not live:
            tenant = tenants[rng.randrange(n_tenants)]
            levels = gen_topic_levels(rng, names, weights) + [f"f{i}"]
            flood.append(("set", tenant, levels))
            live.append((tenant, levels))
        elif roll < 0.85:
            tenant, levels = live.pop(rng.randrange(len(live)))
            flood.append(("clear", tenant, levels))
        else:   # re-SET of a live topic (payload replace, index no-op)
            tenant, levels = live[rng.randrange(len(live))]
            flood.append(("set", tenant, levels))

    filters = [(tenants[rng.randrange(n_tenants)],
                gen_filter_levels(rng, names, weights))
               for _ in range(scan_filters)]

    pubs = []
    for _ in range(publishes):
        roll = rng.random()
        qos = 0 if roll < 0.70 else (1 if roll < 0.95 else 2)
        pubs.append((tenants[rng.randrange(n_tenants)],
                     "/".join(gen_topic_levels(rng, names, weights)), qos))

    churn = []
    for i in range(churn_ops):
        tenant = tenants[rng.randrange(n_tenants)]
        levels = gen_filter_levels(rng, names, weights)
        churn.append(("sub", tenant, levels, f"churn{i}"))
        if rng.random() < 0.5:
            churn.append(("unsub", tenant, levels, f"churn{i}"))

    # drain storm: tenant0 reconnects a HERD, the tail tenants a handful
    drain_plan = []
    herd = max(1, int(drain_sessions * 0.8))
    for i in range(herd):
        drain_plan.append(("tenant0", f"inbox-h{i}",
                           rng.randint(32, 128)))
    rest = drain_sessions - herd
    for i in range(rest):
        tenant = tenants[1 + rng.randrange(max(1, n_tenants - 1))]
        drain_plan.append((tenant, f"inbox-q{i}", rng.randint(8, 32)))

    return {"tenants": tenants, "subscriptions": subs,
            "qos_mix": qos_mix, "n_clients": client,
            "retained_seed": retained_seed, "retained_flood": flood,
            "scan_filters": filters, "publishes": pubs,
            "session_churn": churn, "drain_plan": drain_plan,
            "n_groups": n_groups, "seed": seed}
