"""Workload generators for the BASELINE.md measurement configs.

The five configs (BASELINE.json `configs`):
1. 1 tenant, 10K exact-topic subscriptions
2. 1 tenant, 1M wildcard subscriptions, Zipf-skewed topic tree
3. 1K tenants × 10K subs each, $share fan-out
4. retained: 5M retained topics, wildcard SUBSCRIBE probes
5. 10K tenants, 10M total subs, tenant-sharded across the mesh

Generation is deterministic per seed. Filters are built directly as
RouteMatcher tuples (bypassing string validation) for speed at the 10M scale.
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence, Tuple

from .models.oracle import Route, SubscriptionTrie
from .types import RouteMatcher, RouteMatcherType
from .utils import topic as topic_util


def _zipf_levels(n_levels: int) -> Tuple[List[str], List[float]]:
    """Returns (names, CUMULATIVE weights) — cumulative so random.choices
    skips its per-call accumulate pass (it dominates 10M-scale generation)."""
    names = [f"l{i}" for i in range(n_levels)]
    acc, cum = 0.0, []
    for i in range(n_levels):
        acc += 1.0 / (i + 1)
        cum.append(acc)
    return names, cum


def _mk_matcher(levels: Sequence[str], share_group: str = "",
                ordered: bool = False) -> RouteMatcher:
    if share_group:
        prefix = topic_util.ORDERED_SHARE if ordered else topic_util.UNORDERED_SHARE
        tf = f"{prefix}/{share_group}/" + "/".join(levels)
        return RouteMatcher(
            type=(RouteMatcherType.ORDERED_SHARE if ordered
                  else RouteMatcherType.UNORDERED_SHARE),
            filter_levels=tuple(levels), mqtt_topic_filter=tf,
            group=share_group)
    return RouteMatcher(type=RouteMatcherType.NORMAL,
                        filter_levels=tuple(levels),
                        mqtt_topic_filter="/".join(levels))


def gen_filter_levels(rng: random.Random, names: List[str],
                      weights: List[float], *, max_depth: int = 6,
                      p_plus: float = 0.15, p_hash: float = 0.1) -> List[str]:
    depth = rng.randint(1, max_depth)
    levels = rng.choices(names, cum_weights=weights, k=depth)
    for j in range(depth):
        if rng.random() < p_plus:
            levels[j] = topic_util.SINGLE_WILDCARD
    if rng.random() < p_hash:
        levels.append(topic_util.MULTI_WILDCARD)
    return levels


def gen_topic_levels(rng: random.Random, names: List[str],
                     weights: List[float], *, max_depth: int = 6) -> List[str]:
    depth = rng.randint(1, max_depth)
    return rng.choices(names, cum_weights=weights, k=depth)


def config_exact(n_subs: int = 10_000, *, seed: int = 0,
                 persistent_ratio: float = 0.0) -> Dict[str, SubscriptionTrie]:
    """Config 1: one tenant, exact-topic subscriptions."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(max(64, n_subs // 100))
    trie = SubscriptionTrie()
    for i in range(n_subs):
        levels = gen_topic_levels(rng, names, weights)
        broker = 1 if rng.random() < persistent_ratio else 0
        trie.add(Route(matcher=_mk_matcher(levels), broker_id=broker,
                       receiver_id=f"r{i}", deliverer_key=f"d{i % 64}"))
    return {"tenant0": trie}


def config_wildcard(n_subs: int = 1_000_000, *, seed: int = 0,
                    n_level_names: int = 1000, max_depth: int = 6,
                    persistent_ratio: float = 0.1
                    ) -> Dict[str, SubscriptionTrie]:
    """Config 2: one tenant, wildcard-heavy Zipf subscriptions."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    trie = SubscriptionTrie()
    for i in range(n_subs):
        levels = gen_filter_levels(rng, names, weights, max_depth=max_depth)
        broker = 1 if rng.random() < persistent_ratio else 0
        trie.add(Route(matcher=_mk_matcher(levels), broker_id=broker,
                       receiver_id=f"r{i}", deliverer_key=f"d{i % 64}"))
    return {"tenant0": trie}


def config_shared(n_tenants: int = 1000, subs_per_tenant: int = 10_000, *,
                  seed: int = 0, n_groups: int = 16
                  ) -> Dict[str, SubscriptionTrie]:
    """Config 3: many tenants, $share shared-subscription fan-out."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(500)
    out: Dict[str, SubscriptionTrie] = {}
    for t in range(n_tenants):
        trie = SubscriptionTrie()
        for i in range(subs_per_tenant):
            levels = gen_filter_levels(rng, names, weights, p_plus=0.05,
                                       p_hash=0.05)
            group = f"g{rng.randrange(n_groups)}"
            ordered = rng.random() < 0.3
            trie.add(Route(matcher=_mk_matcher(levels, group, ordered),
                           broker_id=0, receiver_id=f"t{t}m{i}",
                           deliverer_key=f"d{i % 64}"))
        out[f"tenant{t}"] = trie
    return out


def config_multi_tenant(n_tenants: int = 10_000, total_subs: int = 10_000_000,
                        *, seed: int = 0) -> Dict[str, SubscriptionTrie]:
    """Config 5: tenant-sharded: Zipf tenant sizes summing to total_subs."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(1000)
    tenant_weights = [1.0 / (i + 1) for i in range(n_tenants)]
    wsum = sum(tenant_weights)
    out: Dict[str, SubscriptionTrie] = {}
    for t in range(n_tenants):
        n = max(1, int(total_subs * tenant_weights[t] / wsum))
        trie = SubscriptionTrie()
        for i in range(n):
            levels = gen_filter_levels(rng, names, weights)
            trie.add(Route(matcher=_mk_matcher(levels), broker_id=0,
                           receiver_id=f"t{t}r{i}", deliverer_key=f"d{i % 64}"))
        out[f"tenant{t}"] = trie
    return out


def config_retained(n_topics: int = 5_000_000, *, seed: int = 0,
                    n_level_names: int = 1000, max_depth: int = 6
                    ) -> Dict[str, List[List[str]]]:
    """Config 4: retained-message store — concrete topics per tenant.

    The retained path stores *topics* (not filters) and probes with wildcard
    FILTERS (roles-swapped walk, models/retained.py); returns unique topic
    level-lists for one tenant.
    """
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    seen = set()
    topics: List[List[str]] = []
    for i in range(n_topics):
        levels = gen_topic_levels(rng, names, weights, max_depth=max_depth)
        if tuple(levels) in seen:
            # disambiguate with a device-id tail (realistic retained-topic
            # shape: per-device leaves under shared prefixes); may exceed
            # max_depth by one level
            levels = levels + [f"d{i}"]
        seen.add(tuple(levels))
        topics.append(levels)
    return {"tenant0": topics}


def probe_filters(n: int, *, seed: int = 2, n_level_names: int = 1000,
                  max_depth: int = 6) -> List[List[str]]:
    """Wildcard SUBSCRIBE filters probing the retained store (config 4)."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    return [gen_filter_levels(rng, names, weights, max_depth=max_depth)
            for _ in range(n)]


def probe_topics(n: int, *, seed: int = 1, n_level_names: int = 1000,
                 max_depth: int = 6) -> List[List[str]]:
    """Concrete PUBLISH topics drawn from the same Zipf tree."""
    rng = random.Random(seed)
    names, weights = _zipf_levels(n_level_names)
    return [gen_topic_levels(rng, names, weights, max_depth=max_depth)
            for _ in range(n)]
