"""Unattended mesh autoscaler (ISSUE 18 leg 4 — closes the ROADMAP
elastic-mesh follow-up (a)).

PR 17 built the mechanism: ``MeshRebalancer`` plans one-tenant moves,
``resize_mesh`` grows/shrinks the shard axis — but a human still had to
call them. ``MeshAutoscaler`` is the policy loop: it rides the ObsHub
advisory tick (the same cadence the noisy-neighbor detector and gossip
digest refresh on), consumes the *windowed* signals the digest already
carries — shard skew, device queue pressure, replication lag — and
decides grow / rebalance / shrink with explicit hysteresis:

- **grow/rebalance** only after ``K`` CONSECUTIVE over-threshold ticks
  (``BIFROMQ_MESH_AUTOSCALE_K``) — a one-tick spike never scales;
- **shrink** only after a sustained quiet window
  (``BIFROMQ_MESH_AUTOSCALE_QUIET_S``) of low skew AND low pressure;
- a **cooldown** (``BIFROMQ_MESH_AUTOSCALE_COOLDOWN_S``) after ANY
  action blocks the next — at most one action per cooldown, no
  flapping;
- it DEFERS (vetoes) while a migration is in flight or any replication
  stream is flagged stale — scaling under a half-moved tenant or a
  lagging replica compounds the problem it is trying to fix;
- ``BIFROMQ_MESH_AUTOSCALE=0`` is the kill-switch.

Every decision — acted or vetoed — is recorded with the exact signal
snapshot that justified it (decision provenance) in a bounded ring
served at ``GET /mesh/autoscaler``, and appended to the delta-plane
event journal so the PR 8 segment store persists it.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from ..utils.env import env_bool, env_float, env_int
from .reshard import (MeshRebalancer, ShardLoadModel, reshard_max_skew,
                      resize_mesh)

log = logging.getLogger(__name__)


def autoscale_enabled() -> bool:
    """Kill-switch: ``BIFROMQ_MESH_AUTOSCALE=0`` disables the loop."""
    return env_bool("BIFROMQ_MESH_AUTOSCALE", True)


def autoscale_k() -> int:
    """Consecutive over-threshold ticks before grow/rebalance."""
    return max(1, env_int("BIFROMQ_MESH_AUTOSCALE_K", 3))


def autoscale_cooldown_s() -> float:
    """Quiet period after ANY action before the next may fire."""
    return max(0.0, env_float("BIFROMQ_MESH_AUTOSCALE_COOLDOWN_S", 60.0))


def autoscale_quiet_s() -> float:
    """Sustained low-skew/low-pressure window before a shrink."""
    return max(0.0, env_float("BIFROMQ_MESH_AUTOSCALE_QUIET_S", 300.0))


def autoscale_pressure() -> float:
    """Device queue-pressure fraction treated as over-threshold."""
    return max(0.0, env_float("BIFROMQ_MESH_AUTOSCALE_PRESSURE", 0.75))


def autoscale_min_shards() -> int:
    return max(1, env_int("BIFROMQ_MESH_AUTOSCALE_MIN_SHARDS", 1))


def autoscale_max_shards() -> int:
    return max(1, env_int("BIFROMQ_MESH_AUTOSCALE_MAX_SHARDS", 64))


class MeshAutoscaler:
    """Hysteresis policy loop over one mesh matcher's signals.

    ``signals_fn`` is injectable so the policy tests drive synthetic
    skew/pressure sequences through the REAL decision machinery with a
    fake clock; the default reads the live ShardLoadModel rows, the
    ObsHub device gauge and the ISSUE 18 lag plane.
    """

    MAX_DECISIONS = 64

    def __init__(self, matcher, *, rebalancer: Optional[MeshRebalancer]
                 = None, signals_fn: Optional[Callable[[], dict]] = None,
                 clock=time.monotonic) -> None:
        self.matcher = matcher
        self.rebalancer = rebalancer
        self._signals_fn = signals_fn or self._live_signals
        self._clock = clock
        self._over_ticks = 0
        self._quiet_since: Optional[float] = None
        self._last_action_at: Optional[float] = None
        self.ticks = 0
        self.actions = 0
        self.decisions: List[dict] = []
        self._hooked = False
        matcher.mesh_autoscaler = self

    # ---------------- signal collection --------------------------------

    def _live_signals(self) -> dict:
        m = self.matcher
        base = getattr(m, "_base_ct", None)
        model = ShardLoadModel()
        rows = model.rows(m)
        try:
            from ..obs import OBS
            pressure = float(OBS.device.queue_pressure())
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pressure = 0.0
        from ..obs.lag import LAG
        lag = LAG.summary()
        return {
            "skew": model.skew(rows),
            "pressure": round(pressure, 6),
            "n_shards": int(getattr(base, "n_shards", 0) or 0),
            "migrating": len(getattr(base, "migrating", None) or {}),
            "stale_streams": int(lag.get("stale", 0)),
            "worst_lag_s": float(lag.get("worst_lag_s", 0.0)),
        }

    # ---------------- decision machinery -------------------------------

    def _record(self, action: str, acted: bool, reason: str,
                signals: dict, outcome: object = None) -> dict:
        decision = {"action": action, "acted": acted, "reason": reason,
                    "signals": dict(signals), "outcome": outcome,
                    "tick": self.ticks}
        self.decisions.append(decision)
        del self.decisions[:-self.MAX_DECISIONS]
        from ..obs.lag import REPL_EVENTS
        REPL_EVENTS.append("autoscale_decision", **decision)
        if acted:
            self.actions += 1
            self._last_action_at = self._clock()
            self._over_ticks = 0
            self._quiet_since = None
        return decision

    def _in_cooldown(self) -> bool:
        return (self._last_action_at is not None
                and self._clock() - self._last_action_at
                < autoscale_cooldown_s())

    def tick(self) -> Optional[dict]:
        """One policy evaluation; returns the decision recorded this
        tick, or None when nothing was even worth recording (disabled /
        signals nominal and no window armed)."""
        if not autoscale_enabled():
            return None
        self.ticks += 1
        try:
            sig = self._signals_fn()
        except Exception as e:  # noqa: BLE001 — a broken signal source
            log.debug("autoscaler signals failed: %r", e)  # must not kill
            return None                                    # the tick loop
        now = self._clock()
        over = (sig["skew"] > reshard_max_skew()
                or sig["pressure"] > autoscale_pressure())
        quiet = (sig["skew"] <= 1.0 + (reshard_max_skew() - 1.0) / 2
                 and sig["pressure"] < autoscale_pressure() / 2)

        # defer outright while the delta plane is unsettled: a half-
        # moved tenant or a stale replica makes every signal a lie
        if sig.get("migrating"):
            self._over_ticks = 0
            return self._record("defer", False,
                                "migration in flight", sig)
        if sig.get("stale_streams"):
            self._over_ticks = 0
            return self._record("defer", False,
                                "stale replication stream", sig)

        if over:
            self._quiet_since = None
            self._over_ticks += 1
            if self._over_ticks < autoscale_k():
                return self._record(
                    "arm", False,
                    f"over-threshold tick {self._over_ticks}/"
                    f"{autoscale_k()}", sig)
            if self._in_cooldown():
                return self._record("grow", False, "cooldown", sig)
            return self._scale_up(sig)

        self._over_ticks = 0
        if quiet and sig["n_shards"] > autoscale_min_shards():
            if self._quiet_since is None:
                self._quiet_since = now
            if now - self._quiet_since < autoscale_quiet_s():
                return self._record(
                    "quiet", False,
                    f"quiet window "
                    f"{round(now - self._quiet_since, 1)}s/"
                    f"{autoscale_quiet_s()}s", sig)
            if self._in_cooldown():
                return self._record("shrink", False, "cooldown", sig)
            return self._shrink(sig)
        self._quiet_since = None
        return None

    def _scale_up(self, sig: dict) -> dict:
        """Over-threshold for K ticks: prefer moving ONE tenant off the
        hot shard (cheap, no new arenas); grow the mesh when no move is
        plannable (every shard hot / capacity vetoes / single tenant)."""
        reb = self.rebalancer
        if reb is None:
            reb = self.rebalancer = MeshRebalancer(self.matcher)
        try:
            move = reb.plan()
        except Exception as e:  # noqa: BLE001 — plan must not kill the loop
            move = None
            log.debug("autoscaler rebalance plan failed: %r", e)
        if move is not None and move.get("tenant"):
            outcome = reb.step()
            return self._record(
                "rebalance", True,
                f"skew {sig['skew']} for {autoscale_k()} ticks; "
                f"moving {move['tenant']}", sig, outcome)
        n = sig["n_shards"]
        if n >= autoscale_max_shards():
            return self._record("grow", False,
                                "at BIFROMQ_MESH_AUTOSCALE_MAX_SHARDS",
                                sig)
        try:
            resize_mesh(self.matcher, n + 1)
        except Exception as e:  # noqa: BLE001 — a blocked actuator is a
            return self._record("grow", False,   # vetoed decision, not a
                                f"blocked: {e}", sig)   # dead tick loop
        return self._record(
            "grow", True,
            f"over-threshold for {autoscale_k()} ticks and no plannable "
            f"move", sig, {"n_shards": n + 1})

    def _shrink(self, sig: dict) -> dict:
        n = sig["n_shards"]
        try:
            resize_mesh(self.matcher, n - 1)
        except Exception as e:  # noqa: BLE001 — same contract as grow
            return self._record("shrink", False, f"blocked: {e}", sig)
        return self._record(
            "shrink", True,
            f"quiet for {autoscale_quiet_s()}s", sig,
            {"n_shards": n - 1})

    # ---------------- advisory-tick lifecycle --------------------------

    def attach(self) -> None:
        """Put the policy loop on the ObsHub advisory tick."""
        if not self._hooked:
            from ..obs import OBS
            OBS.on_advisory_tick(self._safe_tick)
            self._hooked = True

    def detach(self) -> None:
        if self._hooked:
            from ..obs import OBS
            OBS.remove_advisory_hook(self._safe_tick)
            self._hooked = False

    def _safe_tick(self) -> None:
        try:
            self.tick()
        except Exception:  # noqa: BLE001 — the advisory tick must survive
            log.exception("autoscaler tick failed")

    # ---------------- introspection -------------------------------------

    def status(self) -> Dict[str, object]:
        return {
            "enabled": autoscale_enabled(),
            "k": autoscale_k(),
            "cooldown_s": autoscale_cooldown_s(),
            "quiet_s": autoscale_quiet_s(),
            "pressure_threshold": autoscale_pressure(),
            "min_shards": autoscale_min_shards(),
            "max_shards": autoscale_max_shards(),
            "ticks": self.ticks,
            "actions": self.actions,
            "over_ticks": self._over_ticks,
            "in_cooldown": self._in_cooldown(),
            "decisions": list(self.decisions),
        }
