"""Elastic mesh (ISSUE 17): live tenant migration, online shard
rebalancing and mesh grow/shrink — with ZERO trie rebuilds and ZERO
match-cache generation bumps.

PR 15 froze tenant→shard placement at build time; under Zipf-skewed
multi-tenant traffic one shard saturates while the rest idle. This
module moves a live tenant between shards using only machinery the repo
already has:

- the tenant's arena rows stream to the target shard as **migration
  ops** riding the PR 12 delta hub (``DeltaRecord`` with a ``mig_*`` log
  op), replayed through the target ``PatchableTrie``'s find-or-append
  patch path — byte-deterministic by construction, so mesh standbys
  replaying the same op stream keep arena byte parity;
- during the copy the tenant serves from BOTH shards (the dual-serve
  window): ``ShardedTables.shards_of`` reports ``[src, dst]`` so
  mutations fold into both arenas, and once the copy cursor catches up
  (``mig_ready``) queries take either grid slot exactly like hot-tenant
  replication;
- cutover is one shard-map write (``pins[tenant] = dst`` +
  ``map_version`` bump) — no rebuild, no cache bump (the result set is
  identical from either shard);
- the source rows are tombstoned (``SLOT_DEAD``) once no batch is in
  flight, and the existing frag-compaction reclaims them.

The **abort ladder**: a target-shard breaker leaving "closed" mid-copy
(hang/timeout chaos), or any error in the copy loop, aborts back to
source-only serving — the partial target rows are killed via the
``MigrationState.copied`` ledger (exactly the slots this migration
created, ghost-route-proof even across repeated attempts), the shard map
never saw the tenant move, and nothing was lost or duplicated because
the source arena was never touched before cutover.

``resize_mesh`` grows/shrinks the shard axis of a live mesh: every
tenant is first pinned to its current shard (hash placement moves with
``n_shards``; pins don't), new shards join as empty patchable arenas at
the common edge capacity, evacuating shards drain tenant-by-tenant
through the same migration path, and the jax ``Mesh``/``NamedSharding``
plumbing is re-placed — never a recompile.

Env knobs: ``BIFROMQ_RESHARD_CHUNK`` (routes per copy step),
``BIFROMQ_RESHARD_MAX_SKEW`` (rebalancer trigger),
``BIFROMQ_RESHARD_MIN_HEAT`` (minimum hot-shard heat).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import trace
from ..models.automaton import PatchFallback, PatchableTrie, compile_tries
from ..types import RouteMatcher, RouteMatcherType
from ..utils.env import env_float, env_int
from ..utils.metrics import STAGES

RouteKey = Tuple[str, Tuple[int, str, str]]


def reshard_chunk() -> int:
    """Routes streamed per migration step (``BIFROMQ_RESHARD_CHUNK``) —
    the dual-fold/copy interleave granularity, and therefore the bound
    on how long one step holds the serving thread."""
    return max(1, env_int("BIFROMQ_RESHARD_CHUNK", 64))


def reshard_max_skew() -> float:
    """Shard skew (max/mean load score) above which the rebalancer plans
    a move (``BIFROMQ_RESHARD_MAX_SKEW``)."""
    return max(1.0, env_float("BIFROMQ_RESHARD_MAX_SKEW", 1.5))


def reshard_min_heat() -> int:
    """Minimum hot-shard query heat before a migration is worth the
    dual-serve window (``BIFROMQ_RESHARD_MIN_HEAT``)."""
    return max(0, env_int("BIFROMQ_RESHARD_MIN_HEAT", 64))


def _route_key(route) -> RouteKey:
    return (route.matcher.mqtt_topic_filter, route.receiver_url)


def canonical_routes(trie) -> list:
    """The tenant's routes in canonical (topic filter, receiver_url)
    order — the ONE iteration order for copy streams and tombstone
    sweeps, so leader and standby touch arena slots identically."""
    if trie is None:
        return []
    return sorted(trie.routes(), key=_route_key)


def _route_live(trie, route) -> bool:
    """Is this exact route still in the authoritative trie? The copy
    cursor consults this before emitting, so a route removed while it
    waited in the pending list is never resurrected on the target."""
    if trie is None:
        return False
    node = trie._root
    for level in route.matcher.filter_levels:
        node = node.children.get(level)
        if node is None:
            return False
    if route.matcher.type == RouteMatcherType.NORMAL:
        return route.receiver_url in node.routes
    g = node.groups.get((int(route.matcher.type), route.matcher.group or ""))
    return bool(g) and route.receiver_url in g


def is_migration_op(op: Tuple) -> bool:
    """Migration control ops share the delta hub with route mutations
    but never enter the matcher's logical log — they move rows, not
    routes."""
    return bool(op) and isinstance(op[0], str) and op[0].startswith("mig_")


class MigrationAborted(RuntimeError):
    """The migration fell back to source-only serving (target breaker
    opened mid-stream, copy error, or an explicit abort)."""


@dataclass
class MigrationState:
    """Per-tenant migration bookkeeping carried ON the serving snapshot
    (``ShardedTables.migrating``) so routing, mutation fan-out and the
    base-snapshot codec all read one source of truth.

    ``copied`` ledgers every route folded into the TARGET arena on this
    migration's behalf (copy stream + dual-fold adds; dual-fold removes
    retract). An abort kills exactly these slots — never a pre-existing
    row — so repeated migrate/abort cycles against the same target can
    not leave ghost routes.
    """
    tenant: str
    src: int
    dst: int
    ready: bool = False
    copied: Dict[RouteKey, object] = field(default_factory=dict)

    def digest(self) -> dict:
        return {"src": self.src, "dst": self.dst, "ready": self.ready,
                "copied": len(self.copied)}


# ---------------------------------------------------------------------------
# the ONE migration-op → mesh-state definition
# ---------------------------------------------------------------------------
#
# Op tuples (encoded by replication.records alongside add/rm):
#
#   ("mig_begin",     tenant, src, dst)   — open the dual-fold window
#   ("mig_copy",      tenant, dst, route) — fold one route into dst
#   ("mig_ready",     tenant)             — copy caught up: dual-SERVE
#   ("mig_cutover",   tenant, src, dst)   — shard map flips to dst
#   ("mig_abort",     tenant, src, dst)   — kill the copied ledger in dst
#   ("mig_tombstone", tenant, src)        — kill the moved rows in src

def apply_migration_op(matcher, op: Tuple) -> None:
    """Apply one migration op to a mesh matcher's serving state — the
    single definition the leader applies before emitting and mesh
    standbys replay verbatim. Both sides go through the same idempotent
    ``PatchableTrie`` patch calls at the same op-stream positions
    (group membership resolved from the authoritative trie, which the
    surrounding add/rm stream keeps identical), so arenas stay
    byte-identical. The match-cache is NEVER touched: migration moves
    rows between shards, the logical result set is unchanged."""
    base = matcher._base_ct
    if base is None or not hasattr(base, "compiled"):
        raise RuntimeError("migration ops require an installed mesh base")
    kind, tenant = op[0], op[1]
    mig = getattr(base, "migrating", None)
    if kind == "mig_begin":
        _, _, src, dst = op
        if mig is None:
            mig = base.migrating = {}
        if tenant in mig:
            raise RuntimeError(f"tenant {tenant!r} is already migrating")
        mig[tenant] = MigrationState(tenant=tenant, src=int(src),
                                     dst=int(dst))
        base.map_version += 1
    elif kind == "mig_copy":
        _, _, dst, route = op
        st = (mig or {}).get(tenant)
        if st is None:
            return  # copy raced an abort off the map: nothing to fold
        gm = None
        if route.matcher.type != RouteMatcherType.NORMAL:
            gm = matcher._group_members(tenant, route.matcher)
        pt = base.compiled[int(dst)]
        try:
            pt.patch_add(tenant, route, group_members=gm)
        except PatchFallback:
            # deterministic skip (e.g. an emptied group): both sides see
            # the same authoritative state, so both skip the same op
            matcher.patch_fallbacks += 1
            return
        st.copied[_route_key(route)] = route
        base.sync_edge_caps()
    elif kind == "mig_ready":
        st = (mig or {}).get(tenant)
        if st is not None and not st.ready:
            st.ready = True
            base.map_version += 1
    elif kind == "mig_cutover":
        _, _, src, dst = op
        st = (mig or {}).pop(tenant, None)
        if st is None:
            raise RuntimeError(f"cutover without a migration for {tenant!r}")
        pins = dict(base.pins or {})
        pins[tenant] = int(dst)
        base.pins = pins
        matcher._pins[tenant] = int(dst)
        base.map_version += 1
    elif kind == "mig_abort":
        _, _, src, dst = op
        st = (mig or {}).pop(tenant, None)
        if st is None:
            return
        pt = base.compiled[int(dst)]
        for key in sorted(st.copied):
            route = st.copied[key]
            try:
                pt.patch_remove(tenant, route.matcher, route.receiver_url)
            except PatchFallback:
                pass  # group slot died with its first member — same both sides
        base.map_version += 1
    elif kind == "mig_tombstone":
        _, _, src = op
        pt = base.compiled[int(src)]
        for route in canonical_routes(matcher.tries.get(tenant)):
            try:
                pt.patch_remove(tenant, route.matcher, route.receiver_url)
            except PatchFallback:
                pass
        # overlay-resident removes left live-but-masked rows in the
        # source arena (the rm fell back before it could kill the slot):
        # sweep those too so frag-compaction reclaims everything
        for tf, url in sorted(matcher._tomb.get(tenant, ())):
            try:
                pt.patch_remove(tenant, RouteMatcher.from_topic_filter(tf),
                                url)
            except PatchFallback:
                pass
        base.map_version += 1
    else:
        raise ValueError(f"unknown migration op {kind!r}")


def emit_migration_op(matcher, op: Tuple) -> None:
    """Apply locally, then ship on the delta hub (same ordered path as
    route mutations — standbys replay copy ops interleaved with the
    dual-fold add/rm stream in the exact leader order)."""
    apply_migration_op(matcher, op)
    matcher._emit_delta(op[1], (), op, None, False)


# ---------------------------------------------------------------------------
# migration observability (ISSUE 18 leg 3)
# ---------------------------------------------------------------------------

#: completed/aborted migrations kept per matcher for GET /mesh/migrations
MIGRATION_HISTORY_CAP = 32


def _inflight(matcher) -> Dict[str, "TenantMigration"]:
    mp = getattr(matcher, "migrations_inflight", None)
    if mp is None:
        mp = matcher.migrations_inflight = {}
    return mp


def _history(matcher) -> List[dict]:
    hist = getattr(matcher, "migration_history", None)
    if hist is None:
        hist = matcher.migration_history = []
    return hist


def migration_digest(matcher) -> dict:
    """Compact ``mesh.migrations`` digest field: active copy progress +
    completed/aborted tallies from the bounded history ring."""
    hist = getattr(matcher, "migration_history", None) or []
    active = [mig.progress()
              for mig in (getattr(matcher, "migrations_inflight", None)
                          or {}).values()]
    return {
        "active": len(active),
        "pct": (round(min(p["pct"] for p in active), 1)
                if active else 100.0),
        "completed": sum(1 for h in hist if h["outcome"] == "done"),
        "aborted": sum(1 for h in hist if h["outcome"] == "aborted"),
    }


# ---------------------------------------------------------------------------
# migration driver
# ---------------------------------------------------------------------------

class TenantMigration:
    """Drives ONE live tenant move: ``start`` → ``step``* → ``cutover``
    → ``finish``; ``abort`` at any pre-cutover point returns cleanly to
    source-only serving. ``run`` drives the whole ladder synchronously
    (the rebalancer's mode); services interleave ``step`` with serving.

    The driver is leader-side only — standbys see the emitted op stream,
    never this object."""

    def __init__(self, matcher, tenant_id: str, dst: int, *,
                 src: Optional[int] = None) -> None:
        base = matcher._base_ct
        if base is None or not hasattr(base, "compiled"):
            raise ValueError("migration requires an installed mesh base")
        if not base.patchable or not matcher._patching_enabled():
            raise ValueError("migration requires the per-shard patch plane "
                             "(BIFROMQ_MESH_PATCH)")
        if not 0 <= dst < base.n_shards:
            raise ValueError(f"target shard {dst} out of range")
        if base.replicated and tenant_id in base.replicated:
            raise ValueError("replicated tenants live on every shard "
                             "already — nothing to migrate")
        if tenant_id in (base.migrating or {}):
            raise ValueError(f"tenant {tenant_id!r} is already migrating")
        home = base.shard_of(tenant_id)
        if src is None:
            src = home
        elif src != home:
            raise ValueError(f"tenant {tenant_id!r} lives on shard {home}, "
                             f"not {src}")
        if dst == src:
            raise ValueError("source and target shard are the same")
        self.matcher = matcher
        self.tenant = tenant_id
        self.src = int(src)
        self.dst = int(dst)
        # the copy cursor's worklist: a point-in-time canonical snapshot;
        # routes removed while queued are filtered at emission, routes
        # added later dual-fold into both shards directly
        self.pending: List[object] = canonical_routes(
            matcher.tries.get(tenant_id))
        self._cursor = 0
        self.copied_n = 0
        self.state = "init"   # init→copying→ready→cutover→done | aborted
        self.abort_reason = ""
        # ISSUE 18 leg 3: per-rung wall timestamps + copy-stream volume
        # for GET /mesh/migrations, the mesh.migrations digest field and
        # the abort-attribution history record
        self.rung_at: Dict[str, float] = {}
        self.chunks = 0
        self.bytes_copied = 0

    # -------------- observability (ISSUE 18 leg 3) --------------------------

    def _stamp(self, rung: str) -> None:
        self.rung_at[rung] = time.monotonic()

    def dual_serve_s(self) -> Optional[float]:
        """Duration the tenant served from BOTH shards (ready→cutover;
        still-open windows measure up to now)."""
        t_ready = self.rung_at.get("ready")
        if t_ready is None:
            return None
        t_end = self.rung_at.get("cutover")
        return max(0.0, (t_end if t_end is not None
                         else time.monotonic()) - t_ready)

    def progress(self) -> dict:
        total = len(self.pending)
        dual = self.dual_serve_s()
        return {
            "tenant": self.tenant, "src": self.src, "dst": self.dst,
            "state": self.state,
            "rows": self.copied_n, "total": total,
            "pct": round(100.0 * min(self._cursor, total)
                         / max(1, total), 1),
            "chunks": self.chunks, "bytes": self.bytes_copied,
            "dual_serve_s": None if dual is None else round(dual, 6),
            "abort_reason": self.abort_reason,
        }

    def _retire(self, outcome: str) -> None:
        """Move this migration from the in-flight map into the bounded
        per-matcher history ring, with full rung/volume attribution."""
        _inflight(self.matcher).pop(self.tenant, None)
        t0 = self.rung_at.get("begin")
        durations = {}
        if t0 is not None:
            for rung, at in self.rung_at.items():
                durations[rung] = round(at - t0, 6)
        dual = self.dual_serve_s()
        hist = _history(self.matcher)
        hist.append({
            "tenant": self.tenant, "src": self.src, "dst": self.dst,
            "outcome": outcome, "abort_reason": self.abort_reason,
            "rows": self.copied_n, "total": len(self.pending),
            "chunks": self.chunks, "bytes": self.bytes_copied,
            "rung_s": durations,
            "dual_serve_s": None if dual is None else round(dual, 6),
        })
        del hist[:-MIGRATION_HISTORY_CAP]

    # -------------- abort ladder -------------------------------------------

    def _dst_breaker(self) -> str:
        brs = getattr(self.matcher, "shard_breakers", None)
        br = brs[self.dst] if brs and self.dst < len(brs) else None
        return "closed" if br is None else br.state

    def _check_target(self) -> None:
        state = self._dst_breaker()
        if state != "closed":
            self.abort(f"target shard {self.dst} breaker {state}")
            raise MigrationAborted(self.abort_reason)

    def abort(self, reason: str = "") -> None:
        """Back to source-only serving: the copied ledger is killed in
        the target arena, the shard map never changed, the source arena
        was never touched — zero lost, zero duplicated routes."""
        if self.state in ("cutover", "done"):
            raise RuntimeError("cannot abort after cutover")
        if self.state == "aborted":
            return
        self.abort_reason = reason or "aborted"
        if self.state in ("copying", "ready"):
            emit_migration_op(self.matcher, ("mig_abort", self.tenant,
                                             self.src, self.dst))
        self.state = "aborted"
        self._stamp("abort")
        self._retire("aborted")

    # -------------- the ladder ---------------------------------------------

    def start(self) -> "TenantMigration":
        if self.state != "init":
            raise RuntimeError(f"start() in state {self.state!r}")
        if self.matcher._compact_thread is not None:
            raise RuntimeError("compaction in flight — retry after the swap")
        inflight = self.matcher._base_ct.migrating or {}
        if inflight:
            # one live move at a time keeps the dual-serve window (and
            # the standby's replay surface) bounded and attributable
            raise RuntimeError(f"migration of {sorted(inflight)} in "
                               f"flight — one live move at a time")
        self._check_migratable_base()
        t0 = time.perf_counter()
        with trace.span("mesh.migrate.begin", tenant=self.tenant,
                        src=self.src, dst=self.dst):
            emit_migration_op(self.matcher, ("mig_begin", self.tenant,
                                             self.src, self.dst))
        STAGES.record("mesh.migrate.begin", time.perf_counter() - t0)
        self.state = "copying"
        self._stamp("begin")
        _inflight(self.matcher)[self.tenant] = self
        return self

    def _check_migratable_base(self) -> None:
        base = self.matcher._base_ct
        if base.shard_of(self.tenant) != self.src:
            raise RuntimeError("base swapped under the migration")

    def step(self, n: Optional[int] = None) -> bool:
        """Stream up to ``n`` (default ``BIFROMQ_RESHARD_CHUNK``) routes
        to the target; returns True once the copy cursor caught up and
        the dual-SERVE window opened (``mig_ready`` emitted). Aborts —
        raising :class:`MigrationAborted` — when the target shard's
        breaker left "closed"."""
        if self.state == "ready":
            return True
        if self.state != "copying":
            raise RuntimeError(f"step() in state {self.state!r}")
        self._check_target()
        t0 = time.perf_counter()
        from ..replication.records import encode_op
        chunk = reshard_chunk() if n is None else max(1, n)
        trie = self.matcher.tries.get(self.tenant)
        emitted = 0
        with trace.span("mesh.migrate", tenant=self.tenant,
                        src=self.src, dst=self.dst), \
                trace.span("mesh.migrate.copy", tenant=self.tenant,
                           chunk=self.chunks):
            try:
                while self._cursor < len(self.pending) and emitted < chunk:
                    route = self.pending[self._cursor]
                    self._cursor += 1
                    if not _route_live(trie, route):
                        continue
                    op = ("mig_copy", self.tenant, self.dst, route)
                    emit_migration_op(self.matcher, op)
                    emitted += 1
                    self.copied_n += 1
                    self.bytes_copied += len(encode_op(op))
            except MigrationAborted:
                raise
            except Exception as e:  # noqa: BLE001 — abort, never half-copy
                self.abort(f"copy error: {e!r}")
                raise MigrationAborted(self.abort_reason) from e
        self.chunks += 1
        dt = time.perf_counter() - t0
        STAGES.record("mesh.migrate", dt)
        STAGES.record("mesh.migrate.copy", dt)
        if self._cursor >= len(self.pending):
            t1 = time.perf_counter()
            with trace.span("mesh.migrate.ready", tenant=self.tenant,
                            rows=self.copied_n):
                emit_migration_op(self.matcher, ("mig_ready", self.tenant))
            STAGES.record("mesh.migrate.ready", time.perf_counter() - t1)
            self.state = "ready"
            self._stamp("ready")
            return True
        return False

    def cutover(self) -> "TenantMigration":
        """Atomic shard-map flip: pins[tenant]=dst + map_version bump.
        No rebuild, no cache bump — the result set is identical from
        either shard, which the dual-serve window just proved."""
        if self.state != "ready":
            raise RuntimeError(f"cutover() in state {self.state!r}")
        self._check_target()
        t0 = time.perf_counter()
        with trace.span("mesh.migrate.cutover", tenant=self.tenant,
                        src=self.src, dst=self.dst):
            emit_migration_op(self.matcher, ("mig_cutover", self.tenant,
                                             self.src, self.dst))
        STAGES.record("mesh.migrate.cutover", time.perf_counter() - t0)
        self.state = "cutover"
        self._stamp("cutover")
        return self

    def finish(self) -> bool:
        """Tombstone the moved source rows once NO batch is in flight
        (in-flight expansions read the live arenas through their
        ``_MeshInFlight`` snapshot — killing slots under them would drop
        routes). Returns False while the ring is busy; retry later —
        serving is already correct, this is reclamation."""
        if self.state == "done":
            return True
        if self.state != "cutover":
            raise RuntimeError(f"finish() in state {self.state!r}")
        ring = self.matcher._ring
        if ring is not None and ring.in_flight > 0:
            return False
        t0 = time.perf_counter()
        with trace.span("mesh.migrate.tombstone", tenant=self.tenant,
                        src=self.src):
            emit_migration_op(self.matcher, ("mig_tombstone", self.tenant,
                                             self.src))
        STAGES.record("mesh.migrate.tombstone", time.perf_counter() - t0)
        self.state = "done"
        self._stamp("tombstone")
        self._retire("done")
        return True

    def run(self) -> "TenantMigration":
        if self.state == "init":
            self.start()
        while not self.step():
            pass
        self.cutover()
        self.finish()
        return self


# ---------------------------------------------------------------------------
# skew detection
# ---------------------------------------------------------------------------

class ShardLoadModel:
    """Per-shard load rows from the signals already in the gossip digest
    — arena bytes (``ShardedTables.device_bytes``), logical subs, tenant
    count, query heat, queue pressure, breaker state — plus one scalar
    ``score`` per shard (byte fraction and heat fraction, equally
    weighted) and a ``skew`` = max(score)/mean(score). Operators
    (``/metrics`` → ``mesh.shard_load``, ClusterView digest) and the
    rebalancer read the SAME rows."""

    def __init__(self, *, bytes_weight: float = 0.5,
                 heat_weight: float = 0.5) -> None:
        self.bytes_weight = bytes_weight
        self.heat_weight = heat_weight

    def rows(self, matcher) -> List[dict]:
        base = matcher._base_ct
        if base is None or not hasattr(base, "compiled"):
            return []
        s = base.n_shards
        per_shard = base.device_bytes()["per_shard"]
        subs = [0] * s
        tenants = [0] * s
        heat = [0] * s
        for tenant_id, trie in matcher.tries.items():
            n = len(trie)
            shards = base.shards_of(tenant_id)
            h = matcher.query_heat.get(tenant_id, 0) // max(1, len(shards))
            for sh in shards:
                subs[sh] += n
                tenants[sh] += 1
                heat[sh] += h
        try:
            from ..obs import OBS
            pressure = float(OBS.device.queue_pressure())
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pressure = 0.0
        total_heat = max(1, sum(heat))
        total_bytes = max(1, sum(int(row["real_bytes"]) for row in per_shard))
        brs = getattr(matcher, "shard_breakers", None) or []
        out = []
        for sh in range(s):
            row = per_shard[sh]
            bytes_frac = int(row["real_bytes"]) / total_bytes
            heat_frac = heat[sh] / total_heat
            br = brs[sh] if sh < len(brs) else None
            out.append({
                "shard": sh,
                "padded_bytes": int(row["padded_bytes"]),
                "real_bytes": int(row["real_bytes"]),
                "logical_subs": subs[sh],
                "tenants": tenants[sh],
                "heat": heat[sh],
                # per-shard attribution of the global ring pressure by
                # heat share — a proxy until rings are per-shard
                "queue_pressure": round(pressure * heat_frac, 6),
                "breaker": "closed" if br is None else br.state,
                "score": round(self.bytes_weight * bytes_frac
                               + self.heat_weight * heat_frac, 6),
            })
        return out

    @staticmethod
    def skew(rows: List[dict]) -> float:
        if not rows:
            return 1.0
        scores = [row["score"] for row in rows]
        mean = sum(scores) / len(scores)
        return round(max(scores) / mean, 4) if mean > 0 else 1.0


# ---------------------------------------------------------------------------
# rebalancer
# ---------------------------------------------------------------------------

class MeshRebalancer:
    """Observe→plan→migrate controller: when shard skew crosses
    ``BIFROMQ_RESHARD_MAX_SKEW``, move ONE tenant from the hottest shard
    to the coldest via live migration (never a recompile). Candidate
    order: the PR 3 noisy-tenant ranking first (the detector already
    names who is burning the shard), then by query heat. The PR 8
    ``CapacityPlanner.fits`` vetoes any move that would overflow the
    target shard's HBM. Decisions (including vetoes and aborts) are kept
    for ``GET /mesh/rebalance`` and the gossip digest."""

    MAX_DECISIONS = 32

    def __init__(self, matcher, *, planner=None,
                 max_skew: Optional[float] = None,
                 min_heat: Optional[int] = None) -> None:
        self.matcher = matcher
        if planner is None:
            from ..obs.capacity import CapacityPlanner
            planner = CapacityPlanner()
        self.planner = planner
        self.model = ShardLoadModel()
        self.max_skew = max_skew
        self.min_heat = min_heat
        self.decisions: List[dict] = []
        matcher.mesh_rebalancer = self

    def _record(self, decision: dict) -> dict:
        self.decisions.append(decision)
        del self.decisions[:-self.MAX_DECISIONS]
        return decision

    def plan(self, noisy: Optional[List[str]] = None) -> Optional[dict]:
        """One planning round: returns the move decision (not yet
        executed) or None when balanced / blocked."""
        m = self.matcher
        base = m._base_ct
        if base is None or not hasattr(base, "compiled") \
                or base.n_shards < 2:
            return None
        if base.migrating:
            return None   # one migration at a time — convergence > thrash
        rows = self.model.rows(m)
        skew = self.model.skew(rows)
        max_skew = self.max_skew if self.max_skew is not None \
            else reshard_max_skew()
        min_heat = self.min_heat if self.min_heat is not None \
            else reshard_min_heat()
        hot = max(rows, key=lambda row: row["score"])
        cold = min(rows, key=lambda row: row["score"])
        if skew <= max_skew or hot["shard"] == cold["shard"]:
            return None
        if hot["heat"] < min_heat:
            return None
        movable = [t for t in m.tries
                   if base.shard_of(t) == hot["shard"]
                   and not (base.replicated and t in base.replicated)]
        ranked = [t for t in (noisy or []) if t in movable]
        ranked += sorted((t for t in movable if t not in ranked),
                         key=lambda t: -m.query_heat.get(t, 0))
        vetoed = []
        for tenant in ranked:
            projected = cold["logical_subs"] + len(m.tries[tenant])
            verdict = self.planner.fits(
                projected, mesh=(m.n_replicas, m.n_shards),
                max_levels=m.max_levels, probe_len=m.probe_len)
            if verdict["hbm"]["fits"] is False:
                vetoed.append(tenant)
                continue
            return self._record({
                "tenant": tenant, "src": hot["shard"], "dst": cold["shard"],
                "skew": skew, "max_skew": max_skew,
                "hot_score": hot["score"], "cold_score": cold["score"],
                "vetoed": vetoed,
                "reason": (f"shard {hot['shard']} score {hot['score']} vs "
                           f"mesh skew {skew} > {max_skew}")})
        if vetoed:
            self._record({"tenant": None, "skew": skew,
                          "vetoed": vetoed,
                          "reason": "every candidate vetoed by capacity"})
        return None

    def step(self, noisy: Optional[List[str]] = None) -> Optional[dict]:
        """One controller round: plan, then drive the migration to
        cutover synchronously. Abort outcomes are recorded, never
        raised — the next round replans."""
        decision = self.plan(noisy)
        if decision is None or decision.get("tenant") is None:
            return None
        try:
            mig = TenantMigration(self.matcher, decision["tenant"],
                                  decision["dst"],
                                  src=decision["src"]).run()
            decision["outcome"] = mig.state
            decision["copied"] = mig.copied_n
        except MigrationAborted as e:
            decision["outcome"] = f"aborted: {e}"
        except (RuntimeError, ValueError) as e:
            decision["outcome"] = f"blocked: {e}"
        rows = self.model.rows(self.matcher)
        decision["skew_after"] = self.model.skew(rows)
        return decision


# ---------------------------------------------------------------------------
# mesh grow / shrink
# ---------------------------------------------------------------------------

def resize_mesh(matcher, n_shards: int) -> None:
    """Grow or shrink the shard axis of a LIVE mesh with zero rebuilds.

    Both directions first pin every tenant to its current shard (hash
    placement is a function of ``n_shards``; pins are not). Growing
    appends empty ``PatchableTrie`` arenas at the common edge capacity
    and stocks them with the replicated hot tenants; shrinking drains
    each evacuating shard tenant-by-tenant through the live-migration
    path into the least-loaded survivor. Finally the jax Mesh /
    NamedSharding / step-trace plumbing is re-placed and the delta
    stream re-anchors (standbys resync the resized base).

    Requires: idle dispatch ring, no active migrations, no compaction in
    flight — resize is a control-plane action between batches."""
    base = matcher._base_ct
    if base is None or not hasattr(base, "compiled"):
        raise ValueError("resize requires an installed mesh base")
    if not base.patchable or not matcher._patching_enabled():
        raise ValueError("resize requires the per-shard patch plane")
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    if base.migrating:
        raise RuntimeError("migrations in flight — finish or abort first")
    if matcher._compact_thread is not None:
        raise RuntimeError("compaction in flight — retry after the swap")
    ring = matcher._ring
    if ring is not None and ring.in_flight > 0:
        raise RuntimeError("dispatch ring busy — resize between batches")
    old = base.n_shards
    if n_shards == old:
        return
    t0 = time.perf_counter()
    pins = dict(base.pins or {})
    for tenant_id in sorted(matcher.tries):
        if base.replicated and tenant_id in base.replicated:
            continue
        sh = base.shard_of(tenant_id)
        pins[tenant_id] = sh
        matcher._pins[tenant_id] = sh
    base.pins = pins
    if n_shards > old:
        cap = max(pt.edge_tab.shape[0] for pt in base.compiled)
        for _ in range(old, n_shards):
            ct = compile_tries({}, max_levels=base.max_levels,
                               probe_len=base.probe_len, min_edge_cap=cap)
            base.compiled.append(PatchableTrie(ct))
        base.n_shards = n_shards
        # replicated hot tenants live on EVERY shard: stock the new ones
        # through the same canonical-order patch path
        for tenant_id in sorted(base.replicated or ()):
            routes = canonical_routes(matcher.tries.get(tenant_id))
            for sh in range(old, n_shards):
                pt = base.compiled[sh]
                for route in routes:
                    gm = None
                    if route.matcher.type != RouteMatcherType.NORMAL:
                        gm = matcher._group_members(tenant_id, route.matcher)
                    try:
                        pt.patch_add(tenant_id, route, group_members=gm)
                    except PatchFallback:
                        matcher.patch_fallbacks += 1
        base.sync_edge_caps()
    else:
        # drain evacuating shards through the live-migration ladder
        survivor_subs = [0] * n_shards
        for tenant_id, trie in matcher.tries.items():
            sh = base.shard_of(tenant_id)
            if sh < n_shards:
                survivor_subs[sh] += len(trie)
        for sh in range(n_shards, old):
            evacuees = sorted(
                t for t in matcher.tries
                if base.shard_of(t) == sh
                and not (base.replicated and t in base.replicated))
            for tenant_id in evacuees:
                dst = min(range(n_shards), key=lambda i: survivor_subs[i])
                TenantMigration(matcher, tenant_id, dst, src=sh).run()
                survivor_subs[dst] += len(matcher.tries[tenant_id])
        del base.compiled[n_shards:]
        base.n_shards = n_shards
        # replicated tenants simply lose their evacuated copies
    base.map_version += 1
    matcher._rebuild_mesh_plumbing(n_shards)
    STAGES.record("mesh.migrate", time.perf_counter() - t0)
    # a resize changes the stacked shard-axis shape: standbys must
    # resync the resized base rather than scatter into the old one
    from ..models.matcher import _safe_hook
    _safe_hook(matcher.on_rebase, "rebase", matcher._base_salt(base),
               "resize_mesh")
