"""bifromq_tpu.parallel."""
