"""Tenant-sharded, replica-parallel match plane over a jax.sharding.Mesh.

This is the TPU-native analog of the reference's two scale-out axes for the
route table (SURVEY.md §2.8):

- KV **range partitioning** across dist-worker stores → here: tenants are
  hashed onto ``n_shards`` automaton shards; each mesh column holds one
  shard's tables in its HBM (sharded over the ``shard`` mesh axis).
- **Raft replication** for read scaling (replica-spread queries,
  BatchDistServerCall.replicaSelect:245) → here: every shard's tables are
  replicated over the ``replica`` mesh axis and probe batches are split
  across replicas. HOT tenants additionally replicate across the SHARD
  axis (``MeshMatcher.replicate_tenant``): their queries fan to the
  least-loaded slot of the whole grid instead of one home shard.

The per-device program is the same fixed-shape walk as single-chip
(ops.match.walk); cross-device communication is a single ``psum`` merging
the global fan-out count on device before the one host readback — probes
are routed host-side to their tenant's shard, so the match itself needs
no collective, exactly like the reference where a topic's query goes to
the one range replica that owns the tenant's key span.

ISSUE 15 makes this a first-class serving plane:

- **Per-shard patching** — every shard's automaton is a
  :class:`~bifromq_tpu.models.automaton.PatchableTrie`; route mutations
  fold into the owning shard's arenas in place and flush as NARROW
  per-shard ``idx+rows`` scatters into the stacked device tables
  (donated when the dispatch ring is idle). A churn storm at mesh scale
  runs zero rebuilds and zero match-cache generation bumps; only an
  arena reshape (node growth / edge regrow, pow2-amortized) restacks.
- **Async serving** — ``supports_async`` is on: the mesh leg rides the
  shared dispatch-ring/watchdog/profiler machinery (prep-before-
  admission, fetch-on-ready, tokenize/dispatch/ready/fetch stages
  stamped per mesh step).
- **Per-shard fault domains** — one device breaker per shard on the
  shared board: an open shard's rows serve from the host oracle while
  healthy shards stay on device; half-open re-closes on canary row
  parity; watchdog reclaims quarantine shard-tagged.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import time
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import trace
from ..models.automaton import (
    NODE_COLS, CompiledTrie, PatchableTrie, _build_edge_table,
    compile_tries, tokenize,
)
from ..models.matcher import TpuMatcher, _HostPairs, _parse_levels, \
    _pow2_batch
from ..models.oracle import UNCAPPED_FANOUT, MatchedRoutes, SubscriptionTrie
from ..ops.match import (
    RT_COLS, DeviceTrie, Probes, _bucket_pairs, _expand_pairs,
    _pad_patch_idx, _route_walk, device_expand_enabled, expand_cap_lanes,
    expand_intervals, route_cols_from_node_tab,
)
from ..obs import OBS
from ..obs.e2e import ShardCompletionBoard
from ..utils.env import env_bool
from ..utils.hlc import HLC
from ..utils.metrics import STAGES

REPLICA_AXIS = "replica"
SHARD_AXIS = "shard"


def mesh_patch_enabled() -> bool:
    """Kill-switch for the per-shard patch plane (``BIFROMQ_MESH_PATCH=0``
    restores the overlay+compaction mutation path on the mesh)."""
    return env_bool("BIFROMQ_MESH_PATCH", True)


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-compat shard_map across three jax API generations: the
    image's 0.4.x has only ``jax.experimental.shard_map`` with
    ``check_rep``; mid versions expose top-level ``jax.shard_map`` still
    with ``check_rep``; current ones renamed it ``check_vma``. Probe the
    signature rather than the module path."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    import inspect
    try:
        has_vma = "check_vma" in inspect.signature(sm).parameters
    except (TypeError, ValueError):  # C-accelerated / wrapped callables
        has_vma = True
    kw = {"check_vma": check_vma} if has_vma else {"check_rep": check_vma}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def tenant_shard(tenant_id: str, n_shards: int) -> int:
    """Stable tenant → shard assignment (≈ range ownership by tenant prefix)."""
    d = hashlib.blake2b(tenant_id.encode("utf-8"), digest_size=4).digest()
    return int.from_bytes(d, "little") % n_shards


@dataclass
class ShardedTables:
    """Per-shard compiled automata padded/stacked for mesh placement.

    ``pins`` is the tenant→shard OVERRIDE map this build was compiled
    with (load-driven re-placement, SURVEY §2.8 placement row): routing
    MUST consult the snapshot's own pins — a pin applied after this build
    only takes effect when the recompiled tables swap in, so queries
    always route to the shard that actually holds the tenant.
    ``replicated`` names the hot tenants compiled into EVERY shard
    (query fan-out balancing); ``compiled`` holds per-shard
    :class:`PatchableTrie` arenas once :meth:`make_patchable` ran.
    """
    node_tab: np.ndarray    # [S, N, NODE_COLS]
    edge_tab: np.ndarray    # [S, T, 4]
    child_list: np.ndarray  # [S, E]
    compiled: List[CompiledTrie]   # per-shard (for salt, matchings, roots)
    n_shards: int
    probe_len: int
    max_levels: int
    pins: Optional[Dict[str, int]] = None
    route_tab: Optional[np.ndarray] = None   # [S, N, RT_COLS]
    replicated: Optional[FrozenSet[str]] = None
    # ISSUE 17 elastic mesh: in-flight live migrations keyed by tenant
    # (reshard.MigrationState) and the shard-map version — every
    # routing-affecting transition (begin/ready/cutover/abort/resize)
    # bumps it, so operators and tests can watch the map move without
    # diffing pin dicts
    migrating: Optional[Dict[str, object]] = None
    map_version: int = 0

    def shard_of(self, tenant_id: str) -> int:
        """The tenant's HOME shard (hash placement unless pinned).
        Replicated tenants report their home shard too — callers that
        care about every copy use :meth:`shards_of`."""
        if self.pins:
            pin = self.pins.get(tenant_id)
            # same range guard as build_sharded: an out-of-range pin fell
            # back to hash placement at build time, so routing must too
            if pin is not None and 0 <= pin < self.n_shards:
                return pin
        return tenant_shard(tenant_id, self.n_shards)

    def shards_of(self, tenant_id: str) -> List[int]:
        """Every shard holding this tenant's automaton (all shards for a
        replicated hot tenant) — the mutation fan-out set."""
        if self.replicated and tenant_id in self.replicated:
            return list(range(self.n_shards))
        st = (self.migrating or {}).get(tenant_id)
        if st is not None:
            # dual-fold window (ISSUE 17): mutations land on BOTH the
            # source and the copy-in-progress target until cutover
            return [st.src, st.dst]
        return [self.shard_of(tenant_id)]

    def root_of(self, tenant_id: str) -> int:
        return self.compiled[self.shard_of(tenant_id)].root_of(tenant_id)

    def device_bytes(self) -> Dict[str, object]:
        """Per-shard HBM accounting (ISSUE 8): exact bytes of the stacks
        ``MeshMatcher._compile_shadow`` actually uploads (node_tab never
        ships), each shard's padded slice next to its real rows — the
        capacity plane the multi-chip ROADMAP item lands against."""
        from ..obs.capacity import sharded_tables_device_bytes
        return sharded_tables_device_bytes(self)

    # ------------- per-shard patchable arenas (ISSUE 15) -------------------

    @property
    def patchable(self) -> bool:
        return all(isinstance(ct, PatchableTrie) for ct in self.compiled)

    def make_patchable(self) -> "ShardedTables":
        """Wrap every shard in a :class:`PatchableTrie` arena and restack
        — the one-time conversion after a compile (in-place mutations
        then never rebuild). build_sharded already forced one common
        edge bucket count; node caps stay per-shard (pow2 + headroom)
        and the stacks pad to the max."""
        self.compiled = [ct if isinstance(ct, PatchableTrie)
                         else PatchableTrie(ct) for ct in self.compiled]
        self.restack()
        return self

    def sync_edge_caps(self) -> bool:
        """Regrow every shard's edge table to the COMMON bucket count
        (the device-side mixing mask reads one shared shape). Called on
        the MUTATION path right after a patch op — never from the flush
        — so cap changes are a pure function of the op stream: a replica
        applying the same ops regrows at the same op with the same live
        entry set, keeping arenas byte-identical (``_build_edge_table``
        is deterministic in (live set, cap)). Returns True when any
        shard regrew."""
        if not self.patchable:
            return False
        edge_cap = max(pt.edge_tab.shape[0] for pt in self.compiled)
        changed = False
        while True:
            for pt in self.compiled:
                if pt.edge_tab.shape[0] < edge_cap:
                    entries = pt.edge_tab.reshape(-1, 4)
                    live = entries[entries[:, 0] >= 0]
                    pt.edge_tab = _build_edge_table(
                        live, self.probe_len, min_cap=edge_cap)
                    pt._full.add("edge")
                    pt._dirty_edges.clear()
                    changed = True
            new_cap = max(pt.edge_tab.shape[0] for pt in self.compiled)
            if new_cap == edge_cap:
                break
            edge_cap = new_cap
        return changed

    def restack(self) -> None:
        """Rebuild the stacked host arrays from the (possibly patched)
        per-shard arenas — the full-re-upload half of a mesh reshape.
        Pure STACKING: per-shard arena shapes are never touched here
        (node caps are op-driven; edge caps sync on the mutation path),
        so replica arenas stay byte-identical to the leader's regardless
        of flush cadence. Drains every shard's dirty set: the fresh
        stacks subsume it."""
        assert len({pt.edge_tab.shape[0] for pt in self.compiled}) == 1, \
            "edge caps must be common (sync_edge_caps on the mutation path)"
        s = self.n_shards
        n_max = max(ct.node_tab.shape[0] for ct in self.compiled)
        cap = max(ct.edge_tab.shape[0] for ct in self.compiled)
        e_max = max(ct.child_list.shape[0] for ct in self.compiled)
        node_tab = np.full((s, n_max, NODE_COLS), -1, dtype=np.int32)
        edge_tab = np.full((s, cap, self.probe_len, 4), -1, dtype=np.int32)
        child_list = np.full((s, e_max), -1, dtype=np.int32)
        route_tab = np.zeros((s, n_max, RT_COLS), dtype=np.int32)
        for i, ct in enumerate(self.compiled):
            n = ct.node_tab.shape[0]
            node_tab[i, :n] = ct.node_tab
            edge_tab[i] = ct.edge_tab
            child_list[i, :ct.child_list.shape[0]] = ct.child_list
            route_tab[i, :n] = route_cols_from_node_tab(ct.node_tab)
            if isinstance(ct, PatchableTrie):
                ct.drain_dirty()
        self.node_tab = node_tab
        self.edge_tab = edge_tab
        self.child_list = child_list
        self.route_tab = route_tab

    @classmethod
    def from_patchable(cls, pts: List[PatchableTrie], *, probe_len: int,
                       max_levels: int, pins: Optional[Dict[str, int]] = None,
                       replicated=None, migrating=None,
                       map_version: int = 0) -> "ShardedTables":
        """Reassemble a mesh base from SHIPPED per-shard arenas (ISSUE 15
        mesh replication: a standby installs the leader's exact shard
        arenas — no DFS, no compile — then tracks the op stream).
        ``migrating``/``map_version`` carry a leader's in-flight
        migrations (ISSUE 17) so a standby joining mid-copy replays the
        remaining migration ops against identical state."""
        s = len(pts)
        self = cls(node_tab=np.zeros((s, 1, NODE_COLS), np.int32),
                   edge_tab=np.zeros((s, 1, probe_len, 4), np.int32),
                   child_list=np.zeros((s, 1), np.int32),
                   compiled=list(pts), n_shards=s, probe_len=probe_len,
                   max_levels=max_levels,
                   pins=dict(pins) if pins else None,
                   route_tab=None,
                   replicated=(frozenset(replicated)
                               if replicated else None),
                   migrating=dict(migrating) if migrating else None,
                   map_version=int(map_version))
        self.restack()
        return self


def build_sharded(tries: Dict[str, SubscriptionTrie], n_shards: int, *,
                  max_levels: int = 16, probe_len: int = 16,
                  pins: Optional[Dict[str, int]] = None,
                  replicate: Optional[Set[str]] = None) -> ShardedTables:
    """Compile each tenant shard with a common edge-table capacity.

    All shards share one edge-table size (power of two) so the device-side
    mixing mask is identical; node/child arrays are -1-padded to the max.
    Tenants in ``replicate`` (hot tenants) compile into EVERY shard.
    """
    by_shard: List[Dict[str, SubscriptionTrie]] = [dict() for _ in range(n_shards)]
    for tenant_id, trie in tries.items():
        if replicate and tenant_id in replicate:
            for d in by_shard:
                d[tenant_id] = trie
            continue
        sh = (pins or {}).get(tenant_id)
        if sh is None or not (0 <= sh < n_shards):
            sh = tenant_shard(tenant_id, n_shards)
        by_shard[sh][tenant_id] = trie

    compiled = [compile_tries(s, max_levels=max_levels, probe_len=probe_len)
                for s in by_shard]
    # common bucket count: the mixing mask must be identical across shards
    cap = max(ct.edge_tab.shape[0] for ct in compiled)
    # re-sync: rebuilding one shard at `cap` can itself overflow a bucket
    # and grow past it; iterate until all bucket counts agree.
    while True:
        compiled = [
            ct if ct.edge_tab.shape[0] == cap else compile_tries(
                by_shard[i], max_levels=max_levels, probe_len=probe_len,
                min_edge_cap=cap)
            for i, ct in enumerate(compiled)
        ]
        new_cap = max(ct.edge_tab.shape[0] for ct in compiled)
        if new_cap == cap:
            break
        cap = new_cap

    n_max = max(ct.node_tab.shape[0] for ct in compiled)
    e_max = max(ct.child_list.shape[0] for ct in compiled)
    node_tab = np.full((n_shards, n_max, NODE_COLS), -1, dtype=np.int32)
    edge_tab = np.full((n_shards, cap, probe_len, 4), -1, dtype=np.int32)
    child_list = np.full((n_shards, e_max), -1, dtype=np.int32)
    route_tab = np.zeros((n_shards, n_max, RT_COLS), dtype=np.int32)
    for s, ct in enumerate(compiled):
        n = ct.node_tab.shape[0]
        node_tab[s, :n] = ct.node_tab
        edge_tab[s] = ct.edge_tab
        child_list[s, :ct.child_list.shape[0]] = ct.child_list
        route_tab[s, :n] = route_cols_from_node_tab(ct.node_tab)
    return ShardedTables(node_tab=node_tab, edge_tab=edge_tab,
                         child_list=child_list, compiled=compiled,
                         n_shards=n_shards, probe_len=probe_len,
                         max_levels=max_levels,
                         pins=dict(pins) if pins else None,
                         route_tab=route_tab,
                         replicated=(frozenset(replicate)
                                     if replicate else None))


def make_mesh(n_replicas: int, n_shards: int,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    assert len(devices) >= n_replicas * n_shards, (
        f"need {n_replicas * n_shards} devices, have {len(devices)}")
    grid = np.array(devices[:n_replicas * n_shards]).reshape(
        n_replicas, n_shards)
    return Mesh(grid, (REPLICA_AXIS, SHARD_AXIS))


_STEP_CACHE: Dict[Tuple, object] = {}


def make_match_step(mesh: Mesh, *, probe_len: int, k_states: int = 32,
                    max_intervals: int = 32, merge_total: bool = True):
    """Build (or reuse) the jitted multi-device match step — memoized per
    (mesh, probe_len, k_states, max_intervals): clone_empty()/reset and
    per-range matchers must share one compiled program, not re-trace
    identical closures at ~seconds each.

    Inputs:  tables sharded [S, ...] over SHARD_AXIS (replicated over
             REPLICA_AXIS); probes [R, S, B, ...] split over both axes.
    Outputs: per-topic matched-slot INTERVALS [R, S, B, A] × (start,
             count) — the same compressed MatchedRoutes the single-chip
             walk_routes emits — plus per-topic totals, overflow, and
             (with ``merge_total``) a globally psum'd matched-route count
             (the cross-shard fan-out MERGE happens on device; the host
             reads one scalar). Cross-device traffic is exactly that one
             psum: probes are shard-routed host-side, so the match itself
             needs no collective. ISSUE 19: the device-expand serving
             path drops the psum (``merge_total=False``) — its merge is
             the expand step's per-peer right_permute ring instead.
    """
    key = (mesh, probe_len, k_states, max_intervals, merge_total)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached

    def local_step(edge_tab, child_list, route_tab,
                   tok_h1, tok_h2, lengths, roots, sys_mask):
        # the interval walk reads ONLY route_tab + edge_tab (+ child_list
        # for shape plumbing) — the 48B/row full node table never ships
        # to the mesh (route_tab stands in for the unused node_tab slot)
        trie = DeviceTrie(route_tab[0], edge_tab[0], child_list[0],
                          None, route_tab[0])
        probes = Probes(tok_h1[0, 0], tok_h2[0, 0], lengths[0, 0],
                        roots[0, 0], sys_mask[0, 0])
        ivl_s, ivl_c, n_routes, overflow = _route_walk(
            trie, probes, probe_len, k_states, "sort", max_intervals)
        expand = lambda x: x[None, None]
        outs = (expand(ivl_s), expand(ivl_c), expand(n_routes),
                expand(overflow))
        if not merge_total:
            return outs
        total = jax.lax.psum(n_routes.sum(), (REPLICA_AXIS, SHARD_AXIS))
        return outs + (total,)

    table_spec = P(SHARD_AXIS)
    probe_spec = P(REPLICA_AXIS, SHARD_AXIS)
    out_specs = (probe_spec, probe_spec, probe_spec, probe_spec)
    sharded = _shard_map(
        local_step, mesh=mesh,
        in_specs=(table_spec, table_spec, table_spec,
                  probe_spec, probe_spec, probe_spec, probe_spec, probe_spec),
        out_specs=out_specs + (P(),) if merge_total else out_specs,
        # the walk's loop carries start as replicated constants and become
        # device-varying after the first level; skip the vma consistency check
        check_vma=False,
    )
    step = jax.jit(sharded)
    _STEP_CACHE[key] = step
    return step


def _ring_allreduce(x, axis_name: str, size: int, axis_names):
    """Right-rotate ring allreduce over one mesh axis: ``size - 1``
    single-neighbor hops, each adding the predecessor's running block.
    This is the ISSUE 19 merge — per-peer delivery counts cross the
    interconnect as neighbor permutes, never as an all-to-host psum. On
    a real TPU each hop is the Pallas RDMA right_permute kernel
    (models/kernels.pallas_right_permute); everywhere else it is
    ``jax.lax.ppermute``, which doubles as the kernel's parity oracle."""
    if size <= 1:
        return x
    from ..models.kernels import pallas_right_permute, rdma_permute_enabled
    rdma = rdma_permute_enabled()
    perm = [(i, (i + 1) % size) for i in range(size)]
    acc = x
    buf = x
    for _ in range(size - 1):
        buf = (pallas_right_permute(buf, axis_name, axis_names) if rdma
               else jax.lax.ppermute(buf, axis_name, perm))
        acc = acc + buf
    return acc


def make_expand_step(mesh: Mesh, *, cap: int, n_peers: int,
                     use_kernel: bool = False):
    """The mesh's second device stage (ISSUE 19): per-shard ragged
    expansion of the walk's interval grids into dense (slot, row) pairs +
    stable per-peer bucketing, with the global per-peer totals merged by
    a right_permute ring (shard axis, then replica axis) instead of the
    psum the walk step used to carry.

    Inputs:  ivl_s/ivl_c [R, S, B, A] + overflow [R, S, B] (the walk's
             outputs, still device-resident) and slot_peer [S, n_cap]
             sharded over SHARD_AXIS (each shard buckets against its own
             arena's table; ids come from the PINNED shared peer list so
             bucket b means the same broker on every device).
    Outputs: per-shard compact buffers — slots/rows [R, S, cap],
             row_offsets [R, S, B+1], n_pairs [R, S], trunc [R, S, B],
             peer_slots/peer_rows [R, S, cap], peer_offsets
             [R, S, n_peers+3] — plus the ring-merged per-peer totals
             [n_peers+2] (pad bucket excluded from meaning, kept for
             shape). The host reads buffers that are already grouped by
             delivery target; nothing here ever round-trips the full
             interval grids.
    """
    key = (mesh, "expand", cap, n_peers, use_kernel)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached
    r = mesh.shape[REPLICA_AXIS]
    s = mesh.shape[SHARD_AXIS]
    axis_names = (REPLICA_AXIS, SHARD_AXIS)

    def local_expand(ivl_s, ivl_c, overflow, slot_peer):
        ivl_s, ivl_c, ovf = ivl_s[0, 0], ivl_c[0, 0], overflow[0, 0]
        # walk-overflow rows spend no buffer: their grids are junk and
        # the host oracle re-matches them regardless (same zeroing as
        # the single-chip expand_routes)
        serve_c = jnp.where(ovf[:, None], 0, ivl_c)
        if use_kernel:
            from ..models.kernels import pallas_expand
            slots, rows, row_offsets, n_pairs, trunc = pallas_expand(
                ivl_s, serve_c, cap=cap)
        else:
            slots, rows, row_offsets, n_pairs, trunc = _expand_pairs(
                ivl_s, serve_c, cap)
        if n_peers == 0:
            # no named peers: live pairs are a contiguous prefix (all
            # UNKNOWN) with pad trailing, so the counting sort is the
            # identity — same scatter-free shortcut as the single-chip
            # _expand_routes_fn
            peer_slots, peer_rows = slots, rows
            peer_offsets = jnp.stack(
                [jnp.zeros((), jnp.int32), n_pairs,
                 jnp.full((), cap, jnp.int32)])
        else:
            peer_slots, peer_rows, peer_offsets = _bucket_pairs(
                slots, rows, slot_peer[0], n_peers)
        counts = peer_offsets[1:] - peer_offsets[:-1]
        totals = _ring_allreduce(counts, SHARD_AXIS, s, axis_names)
        totals = _ring_allreduce(totals, REPLICA_AXIS, r, axis_names)
        expand = lambda x: x[None, None]
        return (expand(slots), expand(rows), expand(row_offsets),
                expand(n_pairs), expand(trunc), expand(peer_slots),
                expand(peer_rows), expand(peer_offsets), totals)

    table_spec = P(SHARD_AXIS)
    probe_spec = P(REPLICA_AXIS, SHARD_AXIS)
    sharded = _shard_map(
        local_expand, mesh=mesh,
        in_specs=(probe_spec, probe_spec, probe_spec, table_spec),
        out_specs=(probe_spec,) * 8 + (P(),),
        check_vma=False,
    )
    step = jax.jit(sharded)
    _STEP_CACHE[key] = step
    return step


# --------------- narrow per-shard device scatters (ISSUE 15) ---------------
#
# The single-chip patch flush ships idx+rows into flat tables
# (ops.match.patch_device_trie); the mesh flush ships the SAME narrow
# updates into one shard's slice of the stacked tables. ``shard`` is
# static (one trace per shard id per shape class — S is small) so the
# update lowers as a local dynamic-update on the owning mesh column.
# Donated variants update in place when the dispatch ring proves no
# in-flight reader of the old tables exists (the matcher's
# single-serving-thread contract, models/matcher._flush_patches).

@functools.partial(jax.jit, static_argnames=("shard",))
def _shard_scatter(tab, idx, vals, *, shard: int):
    return tab.at[shard, idx].set(vals)


@functools.partial(jax.jit, static_argnames=("shard",), donate_argnums=(0,))
def _shard_scatter_donated(tab, idx, vals, *, shard: int):
    return tab.at[shard, idx].set(vals)


@functools.partial(jax.jit, static_argnames=("shard",))
def _shard_slice_set(tab, vals, *, shard: int):
    return tab.at[shard].set(vals)


@functools.partial(jax.jit, static_argnames=("shard",), donate_argnums=(0,))
def _shard_slice_set_donated(tab, vals, *, shard: int):
    return tab.at[shard].set(vals)


# ---------------------- mesh serving plumbing (ISSUE 15) -------------------


@dataclass
class _MeshResult:
    """The mesh step's in-flight result leaves, shaped like the
    single-chip :class:`~bifromq_tpu.ops.match.RouteIntervals` surface the
    ring/watchdog/quarantine machinery reads (``start``/``count``/
    ``overflow`` — ``is_ready``/``copy_to_host_async`` probe these)."""
    start: object     # [R, S, B, A] int32
    count: object     # [R, S, B, A] int32
    overflow: object  # [R, S, B] bool


class _MeshExpanded:
    """The mesh twin of :class:`~bifromq_tpu.ops.match.ExpandedRoutes`
    (ISSUE 19): the walk's interval grids stay device-resident for the
    escalation slow path, while the serving fetch reads only the compact
    per-shard pair buffers + peer buckets. ``peer_totals`` is the
    ring-merged global per-peer delivery ledger — the replacement for
    the walk step's all-reduce psum scalar."""

    __slots__ = ("start", "count", "overflow", "slots", "rows",
                 "row_offsets", "n_pairs", "trunc", "peer_slots",
                 "peer_rows", "peer_offsets", "peer_totals")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)

    def ready_leaves(self):
        """What the dispatch ring kicks/polls (see ExpandedRoutes): the
        compact buffers, never the [R, S, B, A] grids."""
        return (self.slots, self.rows, self.row_offsets, self.n_pairs,
                self.trunc, self.peer_slots, self.peer_rows,
                self.peer_offsets, self.peer_totals, self.overflow)


class _MeshPeerTable:
    """The pinned shared delivery-peer id space of one base snapshot:
    every shard buckets against its OWN arena's slot→peer row, but ids
    index this one ``peers`` list, so bucket b is the same broker on
    every device and per-peer totals are summable across the mesh."""

    __slots__ = ("peers", "tables")

    def __init__(self, peers, tables) -> None:
        self.peers = list(peers)
        self.tables = tables     # per-shard dist.deliverer.PeerTable

    @property
    def n_peers(self) -> int:
        return len(self.peers)


class _MultiLeaf:
    """One logical result leaf spanning every group of a split dispatch,
    quacking like a jax array for exactly the two probes the shared
    machinery makes: ``is_ready`` (ring watchdog + quarantine sweep) and
    ``copy_to_host_async`` (fetch-on-ready kick)."""

    __slots__ = ("_leaves",)

    def __init__(self, leaves) -> None:
        self._leaves = list(leaves)

    def is_ready(self) -> bool:
        for leaf in self._leaves:
            ready = getattr(leaf, "is_ready", None)
            if ready is not None and not ready():
                return False
        return True

    def copy_to_host_async(self) -> None:
        for leaf in self._leaves:
            kick = getattr(leaf, "copy_to_host_async", None)
            if kick is not None:
                try:
                    kick()
                except Exception:  # noqa: BLE001 — backend-optional
                    pass


class _SplitGroup:
    """One fault-domain group of a split mesh dispatch: the healthy-shard
    collective, or a single half-open canary shard probing alone. A group
    that times out flips ``failed`` — its rows re-route to the host
    oracle while sibling groups' results still serve."""

    __slots__ = ("shards", "res", "fault", "tag", "failed")

    def __init__(self, shards, res, fault, tag) -> None:
        self.shards = list(shards)
        self.res = res
        self.fault = fault
        self.tag = tag
        self.failed = False


class _SplitMeshResult:
    """Composite in-flight result of a SPLIT mesh step (ISSUE 16).

    Presents the ``start``/``count``/``overflow`` leaf surface the
    ring/watchdog/quarantine machinery expects (as :class:`_MultiLeaf`
    aggregates), while ``MeshMatcher._await_ready`` waits each group
    under its OWN per-shard deadline and ``_fetch_walk`` reassembles the
    full [R, S, B, …] grid from the surviving groups."""

    __slots__ = ("groups", "shape")

    def __init__(self, groups: List[_SplitGroup],
                 shape: Tuple[int, int, int, int]) -> None:
        self.groups = groups
        self.shape = shape    # full-grid (r, s, b, max_intervals)

    @property
    def start(self) -> _MultiLeaf:
        return _MultiLeaf(g.res.start for g in self.groups)

    @property
    def count(self) -> _MultiLeaf:
        return _MultiLeaf(g.res.count for g in self.groups)

    @property
    def overflow(self) -> _MultiLeaf:
        return _MultiLeaf(g.res.overflow for g in self.groups)


class _CanaryTokens:
    """Outstanding half-open canary probes for one in-flight mesh batch.

    A canary admission reserves the breaker's single probe slot; the
    verdict lands in ``_expand_walk`` (row parity) or the timeout path.
    A batch abandoned BEFORE a verdict (device error, cancellation, a
    re-prep discarding the prepared batch) must hand the slot back or
    the breaker wedges half-open refusing forever — the finalizer
    releases whatever was never settled."""

    __slots__ = ("pending",)

    def __init__(self) -> None:
        self.pending: Dict[int, object] = {}    # shard -> breaker

    def settle(self, shard: int) -> None:
        self.pending.pop(shard, None)

    def __del__(self):
        for br in self.pending.values():
            try:
                br.release_probe()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


class _MeshPrepared:
    """Stage-1 output of the mesh leg: shard-routed, tokenized and
    uploaded probe grids, built BEFORE ring admission (ISSUE 11 overlap
    contract) with per-shard breaker admission already applied.

    ISSUE 16: when any shard breaker is not closed, ``split`` is set and
    ``grids`` stays ``None`` — the full-mesh upload is skipped because
    the step will dispatch as per-fault-domain GROUPS over sub-mesh
    slices (``grids_np`` keeps the host grids for per-group slicing)."""

    __slots__ = ("queries", "ct", "batch", "b", "slots", "grids",
                 "grids_np", "split", "lengths_np", "oracle_qis",
                 "canaries", "dispatch_shards", "tokenize_s")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


class _MeshInFlight:
    """Captured dispatch state for one mesh batch — the mesh twin of
    models.matcher._InFlight: expansion must run against THIS snapshot
    (tables object + overlay dict objects), never re-read the live
    matcher, or a mid-flight compaction swap drops overlay routes."""

    __slots__ = ("queries", "ct", "dev", "res", "tomb", "delta", "batch",
                 "b", "slots", "lengths_np", "oracle_qis", "canaries",
                 "dispatch_shards", "kernel", "fault", "fault_shards",
                 "dispatch_s", "tokenize_s", "quarantine_tag",
                 "dev_expand_s", "peer_tab")

    def __init__(self, **kw) -> None:
        self.fault = None
        self.fault_shards = {}
        self.dispatch_s = 0.0
        self.tokenize_s = 0.0
        self.quarantine_tag = "mesh"
        self.dev_expand_s = 0.0  # device-expand enqueue (ISSUE 19)
        self.peer_tab = None     # _MeshPeerTable the buckets index
        for k, v in kw.items():
            setattr(self, k, v)


@dataclass(frozen=True)
class ShardMoveCommand:
    """One balancer decision: re-pin a tenant's automaton shard (the
    TPU-shard analog of the reference's balancer→command pattern,
    KVStoreBalanceController.java:85)."""
    tenant_id: str
    from_shard: int
    to_shard: int
    reason: str


class ShardPlacementBalancer:
    """Heat-driven tenant→shard re-placement (closes SURVEY §2.8's
    placement row for the TPU plane).

    Observes per-tenant query heat (MeshMatcher.query_heat — the same
    role kv/load.py's KVLoadRecorder plays for KV ranges) and, when the
    hottest shard carries more than ``imbalance_factor`` × the coldest
    shard's heat, emits ONE command moving that shard's hottest tenant to
    the coldest shard. One move per round, like the KV balancers: each
    recompile is a placement epoch, and convergence beats thrash.
    """

    def __init__(self, *, imbalance_factor: float = 2.0,
                 min_heat: int = 64) -> None:
        self.imbalance_factor = imbalance_factor
        self.min_heat = min_heat

    def balance(self, heat: Dict[str, int], tables: ShardedTables
                ) -> Optional[ShardMoveCommand]:
        s = tables.n_shards
        shard_heat = [0] * s
        by_shard: List[List[Tuple[int, str]]] = [[] for _ in range(s)]
        for tenant_id, h in heat.items():
            if tables.replicated and tenant_id in tables.replicated:
                continue    # replicated tenants spread by construction
            sh = tables.shard_of(tenant_id)
            shard_heat[sh] += h
            by_shard[sh].append((h, tenant_id))
        hot = max(range(s), key=lambda i: shard_heat[i])
        cold = min(range(s), key=lambda i: shard_heat[i])
        if shard_heat[hot] < self.min_heat:
            return None
        if shard_heat[hot] <= self.imbalance_factor * max(1,
                                                          shard_heat[cold]):
            return None
        # move the hottest tenant whose relocation actually improves the
        # max: new cold-shard heat must stay below the current hot-shard
        # heat (moving a shard's ONLY tenant to a busier target is a loss)
        by_shard[hot].sort(reverse=True)
        for h, tenant_id in by_shard[hot]:
            if shard_heat[cold] + h < shard_heat[hot]:
                return ShardMoveCommand(
                    tenant_id=tenant_id, from_shard=hot, to_shard=cold,
                    reason=f"shard {hot} heat {shard_heat[hot]} > "
                           f"{self.imbalance_factor}x shard {cold} "
                           f"heat {shard_heat[cold]}")
        return None


class MeshMatcher(TpuMatcher):
    """The multi-device match plane with TpuMatcher's full mutation
    machinery — per-shard in-place patching first, delta overlay as the
    fallback, background shadow-compile compaction — and the SAME staged
    serving path (prepare → dispatch → ready → fetch → expand) as the
    single-chip matcher, so the async dispatch ring, watchdog, quarantine
    and profiler drive the mesh leg unchanged. A MeshMatcher drops into
    every TpuMatcher seat (DistWorkerCoProc, DistWorker) and serves live
    add_route/remove_route traffic."""

    # ISSUE 15: the mesh leg now implements the staged serving contract
    # (_prepare_probes/_dispatch_prepared/_expand_walk), so the shared
    # async ring + watchdog drive it like the single-chip path
    supports_async = True
    # ISSUE 15: per-shard PatchableTrie arenas — mutations fold into the
    # owning shard(s) in place; BIFROMQ_MESH_PATCH=0 kills back to the
    # overlay+compaction path
    supports_patching = True

    def __init__(self, tries: Optional[Dict[str, SubscriptionTrie]] = None,
                 mesh: Optional[Mesh] = None, *,
                 max_levels: int = 16, probe_len: int = 16,
                 k_states: int = 32, auto_compact: bool = True,
                 compact_threshold: int = 2048,
                 match_cache: Optional[bool] = None,
                 replicate: Optional[Set[str]] = None) -> None:
        assert mesh is not None, "MeshMatcher requires a mesh"
        super().__init__(max_levels=max_levels, k_states=k_states,
                         probe_len=probe_len, auto_compact=auto_compact,
                         compact_threshold=compact_threshold,
                         match_cache=match_cache)
        self.mesh = mesh
        self.n_replicas = mesh.shape[REPLICA_AXIS]
        self.n_shards = mesh.shape[SHARD_AXIS]
        self._step = make_match_step(mesh, probe_len=probe_len,
                                     k_states=k_states)
        # ISSUE 19: the device-expand serving path walks WITHOUT the
        # scalar psum — its cross-mesh merge is the expand step's
        # per-peer right_permute ring (jit is lazy; only the path that
        # actually serves ever compiles)
        self._step_walk_only = make_match_step(
            mesh, probe_len=probe_len, k_states=k_states,
            merge_total=False)
        self._table_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._probe_sharding = NamedSharding(mesh, P(REPLICA_AXIS,
                                                     SHARD_AXIS))
        self._repl_sharding = NamedSharding(mesh, P())
        # ISSUE 15 fault domains: ONE breaker per shard on the shared
        # board replaces the single matcher-level device breaker — an
        # open shard's rows degrade to the host oracle while healthy
        # shards keep serving on device; the board joins them to
        # /metrics fabric.breakers + the gossip digest per label
        from ..resilience.device import (DEVICE_BREAKERS,
                                         device_breaker_enabled)
        self.device_breaker = None
        self.shard_breakers = [
            DEVICE_BREAKERS.create(label=f"shard{sh}")
            if device_breaker_enabled() else None
            for sh in range(self.n_shards)]
        # load-driven shard re-placement (SURVEY §2.8 placement): desired
        # tenant→shard pins; the serving snapshot routes by ITS OWN pin
        # copy until a recompile swaps the new assignment in
        self._pins: Dict[str, int] = {}
        # ISSUE 20: per-shard dispatch→ready completion rows — a hung
        # device is NAMED in /mesh and the e2e degraded attribution, and
        # recent ready history feeds half-open canary deadline hints
        self.completion = ShardCompletionBoard()
        # ISSUE 16 split dispatch: sub-mesh + group-table caches keyed on
        # the shard column set (one trace / one upload per healthy-mask
        # class, invalidated by compile epoch + flush count)
        self._sub_meshes: Dict[Tuple[int, ...], Mesh] = {}
        self._split_tables: Dict[Tuple[int, ...], tuple] = {}
        # hot tenants compiled into EVERY shard (ISSUE 15): queries fan
        # to the least-loaded grid slot; mutations fan to all shards
        self._replicas: Set[str] = set(replicate or ())
        self.query_heat: Dict[str, int] = {}
        self.shard_balancer = ShardPlacementBalancer()
        if tries:
            # seed path: write straight into authoritative + shadow state
            # and compile one base — building a full overlay that the
            # first refresh immediately discards would be wasted work
            for tenant_id, trie in tries.items():
                for route in trie.routes():
                    self.tries.setdefault(
                        tenant_id, SubscriptionTrie()).add(route)
                    self._shadow.setdefault(
                        tenant_id, SubscriptionTrie()).add(route)
            self._install_base(*self._compile_shadow())

    def clone_empty(self) -> "MeshMatcher":
        return MeshMatcher(mesh=self.mesh, max_levels=self.max_levels,
                           probe_len=self.probe_len, k_states=self.k_states,
                           auto_compact=self.auto_compact,
                           compact_threshold=self.compact_threshold,
                           match_cache=self.match_cache is not None,
                           replicate=set(self._replicas))

    # ---------------- compile target: sharded tables on the mesh -----------

    def _compile_shadow(self) -> Tuple[ShardedTables, tuple]:
        import time as _time
        t0 = _time.perf_counter()
        self.compile_count += 1
        tables = build_sharded(self._shadow, self.n_shards,
                               max_levels=self.max_levels,
                               probe_len=self.probe_len,
                               pins=dict(self._pins),
                               replicate=set(self._replicas))
        if self._patching_enabled():
            # ISSUE 15: per-shard patchable arenas at common capacities —
            # the padded stacked shape is what the mesh step jits against
            tables.make_patchable()
        # node_tab intentionally NOT uploaded: the interval step never
        # gathers from it (route_tab carries every column the walk reads)
        dev = (jax.device_put(tables.edge_tab, self._table_sharding),
               jax.device_put(tables.child_list, self._table_sharding),
               jax.device_put(tables.route_tab, self._table_sharding))
        # warm the step at the small-grid shape so the first serve after
        # an install (this runs on the compile thread) pays no trace
        self._warm_step(dev)
        # ISSUE 8: the mesh plane feeds the same compile accounting
        # (time + ledger attribution via _install_base) as single-chip
        self._last_compile_s = _time.perf_counter() - t0
        self.compile_time_s += self._last_compile_s
        return tables, dev

    def _warm_step(self, dev, b: int = 16) -> None:
        try:
            r, s = self.n_replicas, self.n_shards
            width = self.max_levels + 1
            z = np.zeros((r, s, b, width), dtype=np.int32)
            lengths = np.full((r, s, b), -1, dtype=np.int32)
            roots = np.full((r, s, b), -1, dtype=np.int32)
            sysm = np.zeros((r, s, b), dtype=bool)
            out = self._step(dev[0], dev[1], dev[2], z, z, lengths, roots,
                             sysm)
            out[4].block_until_ready()
        except Exception:  # noqa: BLE001 — warm-up is best-effort
            pass

    # ---------------- per-shard patch plane (ISSUE 15 tentpole) ------------

    def _patching_enabled(self) -> bool:
        return super()._patching_enabled() and mesh_patch_enabled()

    def _base_patchable(self) -> bool:
        base = self._base_ct
        return isinstance(base, ShardedTables) and base.patchable

    def _patch_targets(self, tenant_id: str) -> list:
        base = self._base_ct
        if not isinstance(base, ShardedTables) \
                or not self._patching_enabled():
            return []
        pts = [base.compiled[sh] for sh in base.shards_of(tenant_id)]
        if not all(isinstance(pt, PatchableTrie) for pt in pts):
            return []
        return pts

    def _patch_frag_pending(self) -> bool:
        base = self._base_ct
        return isinstance(base, ShardedTables) and any(
            isinstance(pt, PatchableTrie) and pt.frag_pending()
            for pt in base.compiled)

    def _try_patch(self, op) -> bool:
        ok = super()._try_patch(op)
        if ok:
            # edge-cap sync ON THE MUTATION PATH (not the flush): an
            # organic bucket regrow on one shard regrows the rest to the
            # new common mask at the SAME op position — a replica
            # applying the same op stream regrows at the same point with
            # the same live sets, keeping arenas byte-identical
            base = self._base_ct
            if isinstance(base, ShardedTables):
                base.sync_edge_caps()
                # ISSUE 17 dual-fold ledger: a mutation folded into a
                # migrating tenant's TARGET arena joins (add) or leaves
                # (rm) the copied ledger, so an abort kills exactly the
                # rows this migration created — leader and standby run
                # this same hook at the same op position
                st = (base.migrating or {}).get(op[1])
                if st is not None:
                    if op[0] == "add":
                        route = op[2]
                        st.copied[(route.matcher.mqtt_topic_filter,
                                   route.receiver_url)] = route
                    elif op[0] == "rm":
                        st.copied.pop((op[2].mqtt_topic_filter, op[3]),
                                      None)
        return ok

    def _flush_patches(self, own_slots: int = 0) -> None:
        """Ship every dirty shard's host patches as NARROW per-shard
        scatters into the stacked device tables (one coalesced flush per
        dispatch, donated in place when nothing else is in flight — the
        same exclusivity proof as the single-chip flush). An arena
        reshape (node growth / edge regrow on any shard) RESTACKS at the
        new common capacities and re-uploads — pow2-amortized, never a
        recompile."""
        base = self._base_ct
        if not isinstance(base, ShardedTables) or self._device_trie is None:
            return
        dirty = [(sh, pt) for sh, pt in enumerate(base.compiled)
                 if isinstance(pt, PatchableTrie) and pt.dirty]
        if not dirty:
            return
        ring = self._ring
        donate = ring is None or (ring.in_flight <= own_slots
                                  and not len(ring.quarantine))
        t0 = time.perf_counter()
        dev_edge, dev_child, dev_route = self._device_trie
        node_dim = int(dev_route.shape[1])
        edge_shape = tuple(dev_edge.shape[1:])
        restack = any(pt.node_tab.shape[0] > node_dim
                      or tuple(pt.edge_tab.shape) != edge_shape
                      for _, pt in dirty)
        ops_total = rows_total = bytes_total = 0
        full_tags = set()
        drained: List[Tuple[PatchableTrie, int]] = []
        put = functools.partial(jax.device_put, device=self._repl_sharding)
        scatter = _shard_scatter_donated if donate else _shard_scatter
        slice_set = _shard_slice_set_donated if donate else _shard_slice_set
        try:
            if restack:
                for _, pt in dirty:
                    ops = pt.drain_dirty()[3]
                    drained.append((pt, ops))
                    ops_total += ops
                base.restack()
                dev_edge = jax.device_put(base.edge_tab,
                                          self._table_sharding)
                dev_route = jax.device_put(base.route_tab,
                                           self._table_sharding)
                rows_total = int(base.route_tab.shape[0]
                                 * base.route_tab.shape[1])
                bytes_total = int(base.edge_tab.nbytes
                                  + base.route_tab.nbytes)
                full_tags.add("restack")
            else:
                for sh, pt in dirty:
                    full, nodes, edges, ops = pt.drain_dirty()
                    drained.append((pt, ops))
                    ops_total += ops
                    if "node" in full:
                        from ..models.automaton import pad_rows
                        rows = pad_rows(
                            route_cols_from_node_tab(pt.node_tab),
                            node_dim)
                        dev_route = slice_set(dev_route, put(rows),
                                              shard=sh)
                        rows_total += int(rows.shape[0])
                        bytes_total += int(rows.nbytes)
                        full_tags.add(f"s{sh}:node")
                    elif nodes.size:
                        idx_np = _pad_patch_idx(nodes.astype(np.int32))
                        rows_np = route_cols_from_node_tab(
                            pt.node_tab[idx_np])
                        dev_route = scatter(dev_route, put(idx_np),
                                            put(rows_np), shard=sh)
                        rows_total += int(nodes.size)
                        bytes_total += int(idx_np.nbytes + rows_np.nbytes)
                    if "edge" in full:
                        dev_edge = slice_set(dev_edge, put(pt.edge_tab),
                                             shard=sh)
                        rows_total += int(pt.edge_tab.shape[0])
                        bytes_total += int(pt.edge_tab.nbytes)
                        full_tags.add(f"s{sh}:edge")
                    elif edges.size:
                        idx_np = _pad_patch_idx(edges.astype(np.int32))
                        rows_np = pt.edge_tab[idx_np]
                        dev_edge = scatter(dev_edge, put(idx_np),
                                           put(rows_np), shard=sh)
                        rows_total += int(edges.size)
                        bytes_total += int(idx_np.nbytes + rows_np.nbytes)
        except BaseException:
            # a flush that dies mid-update must not lose the drained row
            # ids (donation may even have consumed a table): mark every
            # drained shard for full re-upload from its host arenas
            for pt, ops in drained:
                pt.restore_dirty(ops)
            raise
        self._device_trie = (dev_edge, dev_child, dev_route)
        dt = time.perf_counter() - t0
        self.patch_flushes += 1
        self.patch_device_s += dt
        STAGES.record("mesh.flush", dt)
        from ..obs import OBS
        OBS.profiler.ledger.record_patch(
            reason="+".join(sorted(full_tags)) if full_tags else "rows",
            mutations=ops_total, rows=rows_total,
            bytes_shipped=bytes_total, duration_s=dt)

    # ---------------- load-driven shard re-placement ------------------------

    def pin_tenant(self, tenant_id: str, shard: int) -> None:
        """Pin a tenant's automaton to a shard; takes effect when the next
        recompiled snapshot swaps in (serving stays exact throughout —
        the installed snapshot keeps routing by its own assignment)."""
        assert 0 <= shard < self.n_shards
        self._pins[tenant_id] = shard

    def replicate_tenant(self, tenant_id: str) -> None:
        """Mark a hot tenant for replication across EVERY shard (ISSUE 15:
        query fan-out spreads over the whole grid; mutations fan to all
        copies). Takes effect when the next recompiled snapshot swaps in."""
        base = self._base_ct
        if isinstance(base, ShardedTables) and base.migrating:
            # replication lands via a forced recompile, and recompiles
            # defer while a migration owns the shard map — raising is
            # honest where silent no-op would lose the request
            raise RuntimeError(
                f"migration of {sorted(base.migrating)} in flight — "
                "finish or abort before replicating")
        if tenant_id not in self._replicas:
            self._replicas.add(tenant_id)
            self._maybe_compact(force=True)

    def rebalance_step(self) -> Optional[ShardMoveCommand]:
        """One balancer round (≈ KVStoreBalanceController.java:85's
        observe→command→apply loop for TPU shards): consult the heat
        profile, apply at most one move, kick a background recompile,
        and decay the heat window.

        This is the RECOMPILE re-placement path (pre-ISSUE 17, kept for
        the quiesce/bench baseline); :meth:`migrate_tenant` /
        :class:`~bifromq_tpu.parallel.reshard.MeshRebalancer` move live
        tenants with zero rebuilds."""
        # defer while a compaction is in flight: the compile thread reads
        # the frozen shadow, and replaying the log (or re-pinning) under
        # it would race; the heat profile persists, so the next round
        # re-evaluates after the swap
        if self._base_ct is None or self._compact_thread is not None:
            self._apply_pending_swap()
            return None
        if isinstance(self._base_ct, ShardedTables) \
                and self._base_ct.migrating:
            return None   # live migrations own the shard map right now
        cmd = self.shard_balancer.balance(self.query_heat, self._base_ct)
        if cmd is not None:
            self.pin_tenant(cmd.tenant_id, cmd.to_shard)
            # fold pending mutations + new pins into a fresh shadow build
            # on the compaction thread (_maybe_compact replays the log
            # itself, safely, before spawning); serving swaps atomically
            self._maybe_compact(force=True)
        # exponential decay: old heat fades, the window tracks current load
        self.query_heat = {t: h // 2 for t, h in self.query_heat.items()
                           if h // 2 > 0}
        return cmd

    # ---------------- elastic mesh (ISSUE 17 tentpole) ----------------------

    def _maybe_compact(self, force: bool = False) -> None:
        # a rebuild mid-migration would compile from the shadow (which
        # places the tenant by pins — still the SOURCE shard) and
        # destroy the migration's dual-fold state: defer until every
        # migration cut over or aborted; the trigger condition persists
        base = self._base_ct
        if isinstance(base, ShardedTables) and base.migrating:
            self._apply_pending_swap()
            return
        super()._maybe_compact(force)

    def migrate_tenant(self, tenant_id: str, src: Optional[int] = None,
                       dst: Optional[int] = None, *, run: bool = True):
        """Live-migrate a tenant between shards with zero rebuilds
        (ISSUE 17): streams the tenant's arena rows to ``dst`` as delta
        records through the target's patch path, dual-serves during the
        copy, then atomically cuts the shard map over. ``run=False``
        returns the started :class:`~bifromq_tpu.parallel.reshard.
        TenantMigration` for step-wise driving (services interleave
        ``step()`` with serving); ``run=True`` drives the whole ladder
        synchronously."""
        from .reshard import TenantMigration
        if dst is None:
            src, dst = None, src
        if dst is None:
            raise ValueError("migrate_tenant needs a target shard")
        mig = TenantMigration(self, tenant_id, int(dst), src=src)
        return mig.run() if run else mig.start()

    def resize_mesh(self, n_shards: int) -> None:
        """Grow or shrink the mesh's shard axis live (ISSUE 17): pin
        tenants where they are, add empty arenas / drain evacuees via
        live migration, re-place the jax mesh plumbing. Zero rebuilds."""
        from .reshard import resize_mesh
        resize_mesh(self, n_shards)

    def _rebuild_mesh_plumbing(self, n_shards: int) -> None:
        """Re-place everything derived from the shard count after a
        resize: jax Mesh + shardings + step trace + per-shard breakers +
        split caches, then a full restack/re-upload of the stacked
        tables (the pjit/NamedSharding re-placement leg — the arenas
        themselves never recompile)."""
        from ..resilience.device import (DEVICE_BREAKERS,
                                         device_breaker_enabled)
        self.mesh = make_mesh(self.n_replicas, n_shards)
        self.n_shards = n_shards
        self._step = make_match_step(self.mesh, probe_len=self.probe_len,
                                     k_states=self.k_states)
        self._step_walk_only = make_match_step(
            self.mesh, probe_len=self.probe_len, k_states=self.k_states,
            merge_total=False)
        # the peer table's stacked [S, n_cap] layout is shard-count
        # derived: a resize must rebuild it (snapshot identity alone
        # would serve a stale-shaped device table to the new mesh)
        self._peer_cache = None
        self._table_sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self._probe_sharding = NamedSharding(self.mesh, P(REPLICA_AXIS,
                                                          SHARD_AXIS))
        self._repl_sharding = NamedSharding(self.mesh, P())
        self.shard_breakers = [
            DEVICE_BREAKERS.create(label=f"shard{sh}")
            if device_breaker_enabled() else None
            for sh in range(n_shards)]
        self._sub_meshes.clear()
        self._split_tables.clear()
        base = self._base_ct
        if isinstance(base, ShardedTables) and self._device_trie is not None:
            base.sync_edge_caps()
            base.restack()
            dev = (jax.device_put(base.edge_tab, self._table_sharding),
                   jax.device_put(base.child_list, self._table_sharding),
                   jax.device_put(base.route_tab, self._table_sharding))
            self._device_trie = dev
            self._warm_step(dev)

    def mesh_status(self) -> dict:
        """The ``GET /mesh`` / ``mesh.shard_load`` surface: shard map
        version, per-shard load rows (the same numbers the rebalancer
        scores), in-flight migrations, pins and replicas."""
        from .reshard import ShardLoadModel, migration_digest
        base = self._base_ct
        if not isinstance(base, ShardedTables):
            return {"n_replicas": self.n_replicas, "n_shards": self.n_shards,
                    "map_version": 0, "shard_load": [], "skew": 1.0,
                    "migrating": {}, "migrations": migration_digest(self),
                    "pins": {}, "replicated": [],
                    "completion": self.completion.snapshot()}
        model = ShardLoadModel()
        rows = model.rows(self)
        return {"n_replicas": self.n_replicas,
                "n_shards": base.n_shards,
                "map_version": base.map_version,
                "shard_load": rows,
                "skew": model.skew(rows),
                "migrating": {t: st.digest()
                              for t, st in (base.migrating or {}).items()},
                # ISSUE 18 leg 3: ladder progress + completed/aborted
                # tallies (the mesh.migrations digest subfield)
                "migrations": migration_digest(self),
                "pins": dict(base.pins or {}),
                "replicated": sorted(base.replicated or ()),
                # ISSUE 20: per-shard dispatch→ready rows + hung naming
                "completion": self.completion.snapshot()}

    # ---------------- staged serving path (ISSUE 15 tentpole) --------------
    #
    # The mesh leg implements the SAME prepare/dispatch/expand stage
    # contract as the single-chip matcher, so TpuMatcher's sync entry
    # (_match_batch_device) and async entry (_device_leg_async — ring
    # admission, watchdogged readiness, fetch-on-ready, quarantine,
    # profiler stamping) drive it without a mesh-specific serve loop.

    def _route_slots(self, queries, tables: ShardedTables
                     ) -> List[List[int]]:
        """Route each query to its (replica, shard) slot: home-shard
        queries round-robin across replicas; replicated hot tenants take
        the least-loaded slot of the WHOLE grid."""
        r, s = self.n_replicas, self.n_shards
        slots: List[List[int]] = [[] for _ in range(r * s)]
        replicated = tables.replicated or frozenset()
        migrating = tables.migrating or {}
        for qi, (tenant_id, _) in enumerate(queries):
            self.query_heat[tenant_id] = \
                self.query_heat.get(tenant_id, 0) + 1
            if tenant_id in replicated:
                slot = min(range(r * s), key=lambda j: len(slots[j]))
            else:
                st = migrating.get(tenant_id)
                if st is not None and st.ready:
                    # dual-SERVE window (ISSUE 17): the copy caught up,
                    # so either shard answers exactly — take the
                    # least-loaded of the tenant's two homes, like a
                    # two-shard slice of hot-tenant replication
                    slot = min((j * s + sh for j in range(r)
                                for sh in (st.src, st.dst)),
                               key=lambda j: len(slots[j]))
                else:
                    sh = tables.shard_of(tenant_id)
                    slot = min((j * s + sh for j in range(r)),
                               key=lambda j: len(slots[j]))
            slots[slot].append(qi)
        return slots

    def _prepare_probes(self, queries, batch: Optional[int] = None
                        ) -> _MeshPrepared:
        """Stage 0: shard-route + per-shard breaker admission + tokenize
        + probe-grid upload, BEFORE ring admission (the async leg preps
        batch N+1 while batch N walks). ``batch`` from the generic entry
        is a whole-batch hint; the mesh pads PER DEVICE from the busiest
        slot's occupancy (honoring the ring's adaptive floor)."""
        self._apply_pending_swap()
        if self._base_ct is None:
            self.refresh()
        tables: ShardedTables = self._base_ct
        r, s = self.n_replicas, self.n_shards
        t0 = time.perf_counter()
        slots = self._route_slots(queries, tables)
        # per-shard fault domain: an OPEN shard's rows never dispatch —
        # they serve from the exact host oracle while healthy shards
        # stay on device; HALF-OPEN admits this batch's rows as the
        # canary, re-closed only on row parity in _expand_walk
        oracle_qis: List[int] = []
        canaries = _CanaryTokens()
        for sh in range(s):
            br = self.shard_breakers[sh]
            if br is None or not any(slots[j * s + sh] for j in range(r)):
                continue
            verdict = br.admit()
            if verdict == "rejected":
                for j in range(r):
                    oracle_qis.extend(slots[j * s + sh])
                    slots[j * s + sh] = []
            elif verdict == "canary":
                canaries.pending[sh] = br
        # ISSUE 16 split trigger: a not-closed breaker ANYWHERE on the
        # board means the full-mesh collective would still synchronize
        # with the sick device (the psum spans every mesh slot, even
        # row-less ones) — so the step dispatches as per-fault-domain
        # groups over sub-mesh slices instead. Half-open canary shards
        # probe in their OWN group: they never rejoin the collective
        # until row parity re-closes them.
        split = bool(canaries.pending) or any(
            br is not None and br.state != "closed"
            for br in self.shard_breakers)
        floor = self._ring.planned_floor() if self._ring is not None else 16
        need = max([len(x) for x in slots] + [1])
        b = _pow2_batch(need, floor=floor)
        width = tables.max_levels + 1
        tok_h1 = np.zeros((r, s, b, width), dtype=np.int32)
        tok_h2 = np.zeros((r, s, b, width), dtype=np.int32)
        lengths = np.full((r, s, b), -1, dtype=np.int32)
        roots = np.full((r, s, b), -1, dtype=np.int32)
        sys_mask = np.zeros((r, s, b), dtype=bool)
        salts = {ct.salt for ct in tables.compiled}
        cache = self._tok_cache if len(salts) == 1 else None
        with trace.span("device.tokenize", batch=r * s * b,
                        queries=len(queries)):
            for rep in range(r):
                for sh in range(s):
                    idxs = slots[rep * s + sh]
                    if not idxs:
                        continue
                    ct = tables.compiled[sh]
                    topics = [queries[qi][1] for qi in idxs]
                    qroots = [ct.root_of(queries[qi][0]) for qi in idxs]
                    tk = tokenize(topics, qroots, max_levels=ct.max_levels,
                                  salt=ct.salt, batch=b, cache=cache)
                    tok_h1[rep, sh] = tk.tok_h1
                    tok_h2[rep, sh] = tk.tok_h2
                    lengths[rep, sh] = tk.lengths
                    roots[rep, sh] = tk.roots
                    sys_mask[rep, sh] = tk.sys_mask
            # prep-before-admission upload: the grids land on the mesh
            # NOW, so ring-parked callers hold uploaded probes bounded by
            # the prep tickets exactly like the single-chip leg. Split
            # mode defers the upload: each group device_puts only ITS
            # sub-mesh slice at dispatch, so no probe bytes ever target a
            # quarantined device.
            grids = None if split else tuple(
                jax.device_put(a, self._probe_sharding)
                for a in (tok_h1, tok_h2, lengths, roots, sys_mask))
        tokenize_s = time.perf_counter() - t0
        STAGES.record("tokenize", tokenize_s)
        dispatch_shards = sorted({
            sh for sh in range(s)
            if any(slots[j * s + sh] for j in range(r))})
        return _MeshPrepared(queries=list(queries), ct=tables, batch=r * s * b,
                             b=b, slots=slots, grids=grids,
                             grids_np=(tok_h1, tok_h2, lengths, roots,
                                       sys_mask),
                             split=split, lengths_np=lengths,
                             oracle_qis=oracle_qis, canaries=canaries,
                             dispatch_shards=dispatch_shards,
                             tokenize_s=tokenize_s)

    def _dispatch_prepared(self, prep: _MeshPrepared, *,
                           donate: bool = False,
                           watchdogged: bool = False) -> _MeshInFlight:
        """Stage 1: flush per-shard patches, enqueue the mesh step.
        Returns on ENQUEUE — readiness is awaited by the caller (the
        watchdogged async ring or the sync short-poll)."""
        from ..resilience.faults import get_injector
        inj = get_injector()
        fault = None
        fault_shards: Dict[int, object] = {}
        if watchdogged:
            fault = inj.device_rule("dispatch")
        else:
            inj.check_raise("device", "tpu-device", "dispatch")
        # per-shard chaos (ISSUE 15): rules target method "mesh:shard<k>"
        # so a test can hang ONE shard's device; the fired rule both
        # shapes readiness (threaded into wait_ready) and attributes the
        # resulting timeout to that shard's breaker alone
        for sh in prep.dispatch_shards:
            try:
                rule = inj.device_rule(f"mesh:shard{sh}")
            except BaseException:
                br = self.shard_breakers[sh]
                if br is not None:
                    br.record_failure(f"injected error shard{sh}")
                    prep.canaries.settle(sh)
                raise
            if rule is not None:
                fault_shards[sh] = rule
                if fault is None:
                    fault = rule
        if self._base_ct is not prep.ct:
            # a compaction swap landed between prep and dispatch (the
            # async leg awaits ring admission in the gap): roots/salts
            # are per-snapshot, so re-prep against the installed base
            prep = self._prepare_probes(prep.queries)
        # ship any host patches accumulated since the last dispatch (one
        # coalesced narrow update per shard, so this batch walks the
        # post-mutation tables). watchdogged == the async leg, which
        # already holds its own (not-yet-dispatched) ring slot.
        self._flush_patches(own_slots=1 if watchdogged else 0)
        if prep.split:
            # ISSUE 16: a not-closed shard breaker splits the step into
            # per-fault-domain groups so the collective never
            # synchronizes with the quarantined device
            return self._dispatch_split(prep, fault, fault_shards)
        dev_edge, dev_child, dev_route = self._device_trie
        use_expand = device_expand_enabled()
        t0 = time.perf_counter()
        with trace.span("device.dispatch", batch=prep.batch,
                        queries=len(prep.queries)) as sp:
            if use_expand:
                ivl_s, ivl_c, _n_routes, overflow = self._step_walk_only(
                    dev_edge, dev_child, dev_route, *prep.grids)
            else:
                ivl_s, ivl_c, _n_routes, overflow, _total = self._step(
                    dev_edge, dev_child, dev_route, *prep.grids)
            if sp is not trace.NOOP:
                sp.set_tag("kernel", "mesh")
        dispatch_s = time.perf_counter() - t0
        STAGES.record("device.dispatch", dispatch_s)
        res = _MeshResult(start=ivl_s, count=ivl_c, overflow=overflow)
        dev_expand_s = 0.0
        peer_tab = None
        if use_expand:
            # ISSUE 19: the second device stage — per-shard fan-out
            # expansion + peer bucketing, cross-mesh totals merged by the
            # right_permute ring; the fetch then reads compact buffers
            # that are already grouped by delivery broker
            from ..models.kernels import expand_kernel_enabled
            t1 = time.perf_counter()
            with trace.span("device.expand", batch=prep.batch):
                peer_tab, slot_peer = self._mesh_peer_table(prep.ct)
                step = make_expand_step(
                    self.mesh, cap=prep.b * expand_cap_lanes(),
                    n_peers=peer_tab.n_peers,
                    use_kernel=expand_kernel_enabled())
                (slots, rows, row_offsets, n_pairs, trunc, peer_slots,
                 peer_rows, peer_offsets, peer_totals) = step(
                    ivl_s, ivl_c, overflow, slot_peer)
                res = _MeshExpanded(
                    start=ivl_s, count=ivl_c, overflow=overflow,
                    slots=slots, rows=rows, row_offsets=row_offsets,
                    n_pairs=n_pairs, trunc=trunc, peer_slots=peer_slots,
                    peer_rows=peer_rows, peer_offsets=peer_offsets,
                    peer_totals=peer_totals)
            dev_expand_s = time.perf_counter() - t1
            STAGES.record("device.expand", dev_expand_s)
        tag = "mesh"
        if fault_shards:
            tag = "mesh:" + ",".join(f"shard{sh}"
                                     for sh in sorted(fault_shards))
        return _MeshInFlight(
            queries=prep.queries, ct=prep.ct, dev=self._device_trie,
            res=res, dev_expand_s=dev_expand_s, peer_tab=peer_tab,
            tomb=self._tomb, delta=self._delta, batch=prep.batch,
            b=prep.b, slots=prep.slots, lengths_np=prep.lengths_np,
            oracle_qis=prep.oracle_qis, canaries=prep.canaries,
            dispatch_shards=prep.dispatch_shards, kernel="mesh",
            fault=fault, fault_shards=fault_shards,
            dispatch_s=dispatch_s, tokenize_s=prep.tokenize_s,
            quarantine_tag=tag)

    def _mesh_peer_table(self, tables: ShardedTables):
        """The per-shard slot→delivery-peer tables of one base snapshot,
        stacked + device_put over SHARD_AXIS, with the peer-id space
        PINNED to the union of every shard's deliverer servers (sorted)
        so bucket ids line up across devices. Cached on base-snapshot
        identity only — patch flushes must NOT invalidate it (a stale
        slot lands in UNKNOWN, a fast-path miss, not a correctness
        risk; see models/matcher.TpuMatcher.__init__)."""
        cached = self._peer_cache
        if cached is not None and cached[0] is tables:
            return cached[1], cached[2]
        from ..dist.deliverer import build_peer_table, server_of
        arenas = [ct.matchings_arr for ct in tables.compiled]
        keys: Set[str] = set()
        for arr in arenas:
            for m in arr:
                dkey = getattr(m, "deliverer_key", None)
                if isinstance(dkey, str):
                    sid = server_of(dkey)
                    if sid:
                        keys.add(sid)
        peers = sorted(keys)
        tabs = [build_peer_table(arr, peers=peers) for arr in arenas]
        n_cap = max([t.slot_peer.shape[0] for t in tabs] + [1])
        # pad rows read UNKNOWN (= n_peers): a slot id past a shard's
        # arena can only come from post-table patches — host fallback
        stacked = np.full((self.n_shards, n_cap), len(peers), np.int32)
        for sh, t in enumerate(tabs):
            stacked[sh, :t.slot_peer.shape[0]] = t.slot_peer
        tab = _MeshPeerTable(peers, tabs)
        dev = jax.device_put(stacked, self._table_sharding)
        self._peer_cache = (tables, tab, dev)
        return tab, dev

    # ------------- split mesh dispatch (ISSUE 16 tentpole leg 1) -----------

    def _sub_mesh(self, cols: Tuple[int, ...]) -> Mesh:
        """The surviving mesh slice for one fault-domain group: the same
        replica rows over only the group's shard columns. Cached per
        column set so ``make_match_step``'s (mesh, …) memo key is stable
        — one trace per healthy-mask class, not per batch."""
        cached = self._sub_meshes.get(cols)
        if cached is None:
            cached = Mesh(self.mesh.devices[:, list(cols)],
                          (REPLICA_AXIS, SHARD_AXIS))
            self._sub_meshes[cols] = cached
        return cached

    def _group_tables(self, tables: ShardedTables, cols: Tuple[int, ...]):
        """Stack the group's per-shard HOST arenas onto its sub-mesh.

        Built from ``tables.compiled[sh]`` (the authoritative arenas),
        NOT the full-mesh host stacks — those go stale after narrow
        per-shard device flushes. Cached per (column set, base identity,
        compile epoch, flush count): a mutation bumps ``patch_flushes``
        via the pre-dispatch flush, so the cache never serves pre-
        mutation rows. Edge caps are common across shards by the
        ``sync_edge_caps`` invariant, so no edge padding happens here
        (padding would change the device-side mixing mask)."""
        ver = (id(tables), self.compile_count, self.patch_flushes)
        cached = self._split_tables.get(cols)
        if cached is not None and cached[0] == ver:
            return cached[1]
        sub = [tables.compiled[sh] for sh in cols]
        g = len(sub)
        cap = sub[0].edge_tab.shape[0]
        n_max = max(ct.node_tab.shape[0] for ct in sub)
        e_max = max(ct.child_list.shape[0] for ct in sub)
        edge_tab = np.full((g, cap, tables.probe_len, 4), -1,
                           dtype=np.int32)
        child_list = np.full((g, e_max), -1, dtype=np.int32)
        route_tab = np.zeros((g, n_max, RT_COLS), dtype=np.int32)
        for i, ct in enumerate(sub):
            edge_tab[i] = ct.edge_tab
            child_list[i, :ct.child_list.shape[0]] = ct.child_list
            route_tab[i, :ct.node_tab.shape[0]] = \
                route_cols_from_node_tab(ct.node_tab)
        sharding = NamedSharding(self._sub_mesh(cols), P(SHARD_AXIS))
        dev = (jax.device_put(edge_tab, sharding),
               jax.device_put(child_list, sharding),
               jax.device_put(route_tab, sharding))
        self._split_tables[cols] = (ver, dev)
        return dev

    def _dispatch_split(self, prep: _MeshPrepared, fault,
                        fault_shards: Dict[int, object]) -> _MeshInFlight:
        """Dispatch the step as per-fault-domain GROUPS: one collective
        over every closed shard (psum spans only the surviving slice) +
        one single-shard group per half-open canary — a canary probes
        alone and rejoins the collective only after row parity re-closes
        its breaker. Each group gets its own result leaves, chaos rule
        and quarantine tag, so ``_await_ready`` can time out ONE group
        (attributing the hang to its shards) while siblings' results
        still serve from device."""
        tables: ShardedTables = prep.ct
        r, s, b = self.n_replicas, self.n_shards, prep.b
        closed = tuple(sh for sh in prep.dispatch_shards
                       if sh not in prep.canaries.pending)
        group_cols: List[Tuple[int, ...]] = \
            ([closed] if closed else []) + \
            [(sh,) for sh in sorted(prep.canaries.pending)
             if sh in prep.dispatch_shards]
        groups: List[_SplitGroup] = []
        t0 = time.perf_counter()
        with trace.span("device.dispatch", batch=prep.batch,
                        queries=len(prep.queries)) as sp:
            for cols in group_cols:
                sub_mesh = self._sub_mesh(cols)
                step = make_match_step(sub_mesh, probe_len=self.probe_len,
                                       k_states=self.k_states)
                dev = self._group_tables(tables, cols)
                psharding = NamedSharding(sub_mesh, P(REPLICA_AXIS,
                                                      SHARD_AXIS))
                idx = list(cols)
                grids = tuple(
                    jax.device_put(np.ascontiguousarray(a[:, idx]),
                                   psharding)
                    for a in prep.grids_np)
                ivl_s, ivl_c, _n_routes, overflow, _total = \
                    step(*dev, *grids)
                gf = next((fault_shards[sh] for sh in cols
                           if sh in fault_shards), fault)
                tag = "mesh:" + ",".join(f"shard{sh}" for sh in cols)
                groups.append(_SplitGroup(
                    cols, _MeshResult(start=ivl_s, count=ivl_c,
                                      overflow=overflow), gf, tag))
            if sp is not trace.NOOP:
                sp.set_tag("kernel", "mesh_split")
        dispatch_s = time.perf_counter() - t0
        STAGES.record("device.dispatch", dispatch_s)
        tag = "mesh"
        if fault_shards:
            tag = "mesh:" + ",".join(f"shard{sh}"
                                     for sh in sorted(fault_shards))
        return _MeshInFlight(
            queries=prep.queries, ct=prep.ct, dev=self._device_trie,
            res=_SplitMeshResult(groups, (r, s, b)),
            tomb=self._tomb, delta=self._delta, batch=prep.batch,
            b=prep.b, slots=prep.slots, lengths_np=prep.lengths_np,
            oracle_qis=prep.oracle_qis, canaries=prep.canaries,
            dispatch_shards=prep.dispatch_shards, kernel="mesh_split",
            fault=fault, fault_shards=fault_shards,
            dispatch_s=dispatch_s, tokenize_s=prep.tokenize_s,
            quarantine_tag=tag)

    def _note_shard_ready(self, sh: int, dt: float,
                          start_hlc: int = 0) -> None:
        """One completion row (ISSUE 20): per-shard dispatch→ready timing
        into the stage histogram + the board (deferred span like the
        batcher's queue-wait — duration is only known at readiness); a
        previously-hung shard that serves again clears its degraded
        attribution."""
        STAGES.record("device.shard_ready", dt)
        trace.record_finished("device.shard_ready", trace.current_ctx(),
                              start_hlc=start_hlc, duration_s=dt,
                              tags={"shard": sh})
        self.completion.note_ready(sh, dt)
        OBS.e2e.clear_degraded(f"mesh:shard{sh}")

    async def _await_ready_shards(self, ring, fl) -> None:
        """Non-split readiness with PER-SHARD completion attribution
        (ISSUE 20 tentpole part 3): every dispatched shard polls the
        same collective leaves under its OWN chaos-rule view, so the
        board gets one dispatch→ready row per shard and a timeout NAMES
        the hung shard(s) instead of raising an anonymous step-wide
        error. The collective still completes (or times out) as one
        step — attribution costs concurrent polls, never extra syncs."""
        from ..resilience.device import (DeviceTimeoutError,
                                         device_deadline_s)
        shards = list(fl.dispatch_shards or ())
        if len(shards) <= 1:
            t0, shlc = time.monotonic(), HLC.INST.get()
            await ring.wait_ready(fl.res, fault=fl.fault)
            dt = time.monotonic() - t0
            for sh in shards:
                self._note_shard_ready(sh, dt, shlc)
            return
        deadline = device_deadline_s()
        t0, shlc = time.monotonic(), HLC.INST.get()
        hung: List[int] = []

        async def wait_shard(sh: int) -> None:
            try:
                await ring.wait_ready(
                    fl.res, deadline_s=deadline,
                    fault=fl.fault_shards.get(sh, fl.fault))
                self._note_shard_ready(sh, time.monotonic() - t0, shlc)
            except DeviceTimeoutError:
                hung.append(sh)
        await asyncio.gather(*(wait_shard(sh) for sh in shards))
        if hung:
            for sh in sorted(hung):
                self.completion.note_hung(sh, "deadline")
                OBS.e2e.set_degraded(f"mesh:shard{sh}", "device_timeout")
            raise DeviceTimeoutError(
                deadline or 0.0,
                " (shard%s)" % ",".join(str(sh) for sh in sorted(hung)))

    async def _await_ready(self, ring, fl) -> None:
        """Per-group readiness waits under PER-SHARD deadlines (ISSUE 16):
        a hung group is indicted alone — its leaves go to quarantine
        shard-tagged, its breakers open, its rows re-route to the host
        oracle — while every surviving group's device results serve.
        Only an all-groups hang escalates to the whole-step
        DeviceTimeoutError the base leg already handles."""
        res = fl.res
        if not isinstance(res, _SplitMeshResult):
            await self._await_ready_shards(ring, fl)
            return
        if not res.groups:
            return
        from ..resilience.device import (DeviceTimeoutError,
                                         shard_deadline_s)
        deadline = shard_deadline_s()
        t0, shlc = time.monotonic(), HLC.INST.get()

        async def wait_group(g: _SplitGroup) -> None:
            # ISSUE 20: a half-open canary probes alone under a deadline
            # scaled to ITS OWN recent completion history (never looser
            # than the configured shard deadline)
            gd = deadline
            if len(g.shards) == 1 and g.shards[0] in fl.canaries.pending:
                gd = self.completion.deadline_hint(g.shards[0], deadline)
            try:
                await ring.wait_ready(g.res, deadline_s=gd,
                                      fault=g.fault)
                dt = time.monotonic() - t0
                for sh in g.shards:
                    self._note_shard_ready(sh, dt, shlc)
            except DeviceTimeoutError:
                g.failed = True
        await asyncio.gather(*(wait_group(g) for g in res.groups))
        failed = [g for g in res.groups if g.failed]
        if not failed:
            return
        if len(failed) == len(res.groups):
            # no surviving device evidence: whole-step timeout semantics
            # (the caller reclaims the composite, _note_device_timeout
            # attributes every dispatched shard)
            raise DeviceTimeoutError(deadline or 0.0,
                                     " (all shard groups)")
        from ..utils.metrics import FABRIC, FabricMetric
        s = self.n_shards
        for g in failed:
            ring.reclaim(g.res, tag=g.tag)
            FABRIC.inc(FabricMetric.DEVICE_TIMEOUT)
            # blame the shard(s) whose chaos rule shaped the hang when
            # one fired; a collective-group stall with no finer evidence
            # indicts every member
            blame = [sh for sh in g.shards
                     if sh in fl.fault_shards] or list(g.shards)
            for sh in blame:
                br = self.shard_breakers[sh]
                if br is not None:
                    br.record_failure("shard group timeout")
                    fl.canaries.settle(sh)
                # ISSUE 20: the hung shard is NAMED on the completion
                # board and in the e2e plane's degraded attribution
                self.completion.note_hung(sh, "group timeout")
                OBS.e2e.set_degraded(f"mesh:shard{sh}", "shard_group_timeout")
            for sh in g.shards:
                for rep in range(self.n_replicas):
                    fl.oracle_qis.extend(fl.slots[rep * s + sh])

    @staticmethod
    def _fetch_walk(res):
        if isinstance(res, _MeshExpanded):
            # ISSUE 19 fast path: compact per-shard pair buffers only —
            # the [R, S, B, A] interval grids stay on device (truncated
            # rows fetch them lazily via _fetch_escalation_grids)
            from ..resilience.faults import get_injector
            get_injector().check_raise("device", "tpu-device", "fetch")
            overflow = np.array(res.overflow)
            pairs = _HostPairs(
                slots=np.asarray(res.slots), rows=np.asarray(res.rows),
                row_offsets=np.asarray(res.row_offsets),
                n_pairs=np.asarray(res.n_pairs),
                trunc=np.asarray(res.trunc),
                peer_slots=np.asarray(res.peer_slots),
                peer_rows=np.asarray(res.peer_rows),
                peer_offsets=np.asarray(res.peer_offsets), res=res)
            return overflow, pairs, None
        if not isinstance(res, _SplitMeshResult):
            return TpuMatcher._fetch_walk(res)
        from ..resilience.faults import get_injector
        get_injector().check_raise("device", "tpu-device", "fetch")
        r, s, b = res.shape
        live = []
        a = 1
        for g in res.groups:
            if g.failed:
                continue    # never synchronize with a hung group's leaves
            gs = np.array(g.res.start)
            live.append((g, gs, np.array(g.res.count),
                         np.array(g.res.overflow)))
            a = max(a, gs.shape[-1])
        starts = np.zeros((r, s, b, a), dtype=np.int32)
        counts = np.zeros((r, s, b, a), dtype=np.int32)
        overflow = np.zeros((r, s, b), dtype=bool)
        for g, gs, gc, go in live:
            for i, sh in enumerate(g.shards):
                starts[:, sh, :, :gs.shape[-1]] = gs[:, i]
                counts[:, sh, :, :gc.shape[-1]] = gc[:, i]
                overflow[:, sh] = go[:, i]
        # failed/absent shards stay all-zero: their rows are already in
        # oracle_qis, so _expand_walk overwrites them with exact rows
        return overflow, starts, counts

    def _note_device_timeout(self, fl) -> None:
        """Watchdog attribution (ISSUE 15): a timed-out mesh step feeds
        the breaker(s) of the shard(s) whose chaos rule shaped the hang
        when one fired — else every dispatched shard (a whole-mesh stall
        has no finer evidence). Subsequent batches then exclude exactly
        the opened shards while the rest keep serving on device."""
        shards = sorted(getattr(fl, "fault_shards", {}) or ()) \
            or list(getattr(fl, "dispatch_shards", ()) or ())
        for sh in shards:
            br = self.shard_breakers[sh]
            if br is not None:
                br.record_failure("mesh step timeout")
                fl.canaries.settle(sh)
            # ISSUE 20: name the implicated shard(s) on the completion
            # board (idempotent when _await_ready already did)
            self.completion.note_hung(sh, "mesh step timeout")
            OBS.e2e.set_degraded(f"mesh:shard{sh}", "device_timeout")
        # canary shards not implicated got no verdict: hand the probe
        # slot back so the breaker can re-probe on the next batch
        for sh, br in list(fl.canaries.pending.items()):
            br.release_probe()
            fl.canaries.settle(sh)

    @staticmethod
    def _canon_routes(m: MatchedRoutes):
        return (sorted((r.matcher.mqtt_topic_filter, r.receiver_url)
                       for r in m.normal),
                {f: sorted(r.receiver_url for r in ms)
                 for f, ms in m.groups.items()})

    def _expand_walk(self, fl: _MeshInFlight, overflow, starts_a, counts_a,
                     max_persistent_fanout: int,
                     max_group_fanout: int) -> List[MatchedRoutes]:
        """Stage 3: one vectorized interval expansion for the whole
        [R,S,B] grid + overlay correction against the _MeshInFlight
        SNAPSHOT, canary parity settlement, and exact host-oracle serving
        for breaker-excluded / unknown-tenant / overflowed rows."""
        tables: ShardedTables = fl.ct
        r, s, b = overflow.shape
        # ISSUE 19: device-expanded batches hand the pairs pre-computed
        # per shard; only buffer-truncated rows re-expand on host from
        # the lazily fetched interval grids (exact, just not bucketed)
        pairs = starts_a if isinstance(starts_a, _HostPairs) else None
        g_s = g_c = None
        if pairs is None:
            a = starts_a.shape[-1]
            flat_slots, flat_offs = expand_intervals(
                starts_a.reshape(-1, a), counts_a.reshape(-1, a))
        out: List[Optional[MatchedRoutes]] = [None] * len(fl.queries)
        oracle_qis: Set[int] = set(fl.oracle_qis)
        canary_rows: Dict[int, List[int]] = {}
        for rep in range(r):
            for sh in range(s):
                ct = tables.compiled[sh]
                for bi, qi in enumerate(fl.slots[rep * s + sh]):
                    tenant_id, levels = fl.queries[qi]
                    if ct.root_of(tenant_id) < 0 \
                            or overflow[rep, sh, bi] \
                            or fl.lengths_np[rep, sh, bi] < 0:
                        # tenant newer than the base / active-set or
                        # interval overflow / topic too deep: exact
                        # host fallback (not a fault-domain degradation)
                        oracle_qis.add(qi)
                        continue
                    if pairs is None:
                        row_i = (rep * s + sh) * b + bi
                        row = flat_slots[
                            flat_offs[row_i]:flat_offs[row_i + 1]]
                    elif pairs.trunc[rep, sh, bi]:
                        if g_s is None:
                            g_s, g_c = TpuMatcher._fetch_escalation_grids(
                                pairs.res)
                        row, _ = expand_intervals(
                            g_s[rep, sh, bi:bi + 1],
                            g_c[rep, sh, bi:bi + 1])
                    else:
                        offs = pairs.row_offsets[rep, sh]
                        row = pairs.slots[rep, sh][offs[bi]:offs[bi + 1]]
                    tomb = fl.tomb.get(tenant_id)
                    delta = fl.delta.get(tenant_id)
                    if not tomb and delta is None:
                        out[qi] = self._routes_from_slots(
                            ct, row, max_persistent_fanout,
                            max_group_fanout)
                    else:
                        out[qi] = self._expand_with_overlay(
                            ct, row, tomb or (), delta,
                            _parse_levels(levels),
                            max_persistent_fanout, max_group_fanout)
                    if sh in fl.canaries.pending:
                        canary_rows.setdefault(sh, []).append(qi)
        if pairs is not None:
            # the delivery-plane surface (deliverer.bucket_views reads
            # the per-shard buckets through this; bench reads totals)
            self.last_expanded = (pairs, fl.peer_tab)
        # half-open settlement: a canary shard re-closes ONLY when its
        # device rows are row-identical to the host oracle; wrong rows
        # reopen the breaker and the oracle rows serve instead
        for sh, br in list(fl.canaries.pending.items()):
            qis = canary_rows.get(sh)
            if not qis:
                # every row of the canary shard fell to the oracle —
                # no device evidence either way: release the probe
                br.release_probe()
                fl.canaries.settle(sh)
                continue
            oracle = self.match_from_tries(
                [fl.queries[qi] for qi in qis],
                max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout)
            if all(self._canon_routes(out[qi]) == self._canon_routes(om)
                   for qi, om in zip(qis, oracle)):
                br.record_success()
            else:
                br.record_failure("canary row parity")
                for qi, om in zip(qis, oracle):
                    out[qi] = om
            fl.canaries.settle(sh)
        if oracle_qis:
            qlist = sorted(oracle_qis)
            rows = self.match_from_tries(
                [fl.queries[qi] for qi in qlist],
                max_persistent_fanout=max_persistent_fanout,
                max_group_fanout=max_group_fanout)
            for qi, m in zip(qlist, rows):
                out[qi] = m
            degraded = len(fl.oracle_qis)
            if degraded:
                # ONLY breaker-excluded rows are a degradation; the
                # overflow/unknown-tenant fallback is normal serving
                from ..utils.metrics import FABRIC, FabricMetric
                FABRIC.inc(FabricMetric.MATCH_DEGRADED, degraded)
                with trace.span("match.degraded", reason="shard_breaker",
                                n_queries=degraded):
                    pass
        return out
