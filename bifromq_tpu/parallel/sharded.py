"""Tenant-sharded, replica-parallel match plane over a jax.sharding.Mesh.

This is the TPU-native analog of the reference's two scale-out axes for the
route table (SURVEY.md §2.8):

- KV **range partitioning** across dist-worker stores → here: tenants are
  hashed onto ``n_shards`` automaton shards; each mesh column holds one
  shard's tables in its HBM (sharded over the ``shard`` mesh axis).
- **Raft replication** for read scaling (replica-spread queries,
  BatchDistServerCall.replicaSelect:245) → here: every shard's tables are
  replicated over the ``replica`` mesh axis and probe batches are split
  across replicas.

The per-device program is the same fixed-shape walk as single-chip
(ops.match.walk); cross-device communication is a single ``psum`` for global
fan-out stats — probes are routed host-side to their tenant's shard, so the
match itself needs no collective, exactly like the reference where a topic's
query goes to the one range replica that owns the tenant's key span.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.automaton import (
    NODE_COLS, CompiledTrie, compile_tries, tokenize,
)
from ..models.matcher import TpuMatcher, _parse_levels
from ..models.oracle import UNCAPPED_FANOUT, MatchedRoutes, SubscriptionTrie
from ..ops.match import (
    RT_COLS, DeviceTrie, Probes, _route_walk, expand_intervals,
    route_cols_from_node_tab,
)

REPLICA_AXIS = "replica"
SHARD_AXIS = "shard"


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-compat shard_map across three jax API generations: the
    image's 0.4.x has only ``jax.experimental.shard_map`` with
    ``check_rep``; mid versions expose top-level ``jax.shard_map`` still
    with ``check_rep``; current ones renamed it ``check_vma``. Probe the
    signature rather than the module path."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    import inspect
    try:
        has_vma = "check_vma" in inspect.signature(sm).parameters
    except (TypeError, ValueError):  # C-accelerated / wrapped callables
        has_vma = True
    kw = {"check_vma": check_vma} if has_vma else {"check_rep": check_vma}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def tenant_shard(tenant_id: str, n_shards: int) -> int:
    """Stable tenant → shard assignment (≈ range ownership by tenant prefix)."""
    d = hashlib.blake2b(tenant_id.encode("utf-8"), digest_size=4).digest()
    return int.from_bytes(d, "little") % n_shards


@dataclass
class ShardedTables:
    """Per-shard compiled automata padded/stacked for mesh placement.

    ``pins`` is the tenant→shard OVERRIDE map this build was compiled
    with (load-driven re-placement, SURVEY §2.8 placement row): routing
    MUST consult the snapshot's own pins — a pin applied after this build
    only takes effect when the recompiled tables swap in, so queries
    always route to the shard that actually holds the tenant.
    """
    node_tab: np.ndarray    # [S, N, NODE_COLS]
    edge_tab: np.ndarray    # [S, T, 4]
    child_list: np.ndarray  # [S, E]
    compiled: List[CompiledTrie]   # per-shard (for salt, matchings, roots)
    n_shards: int
    probe_len: int
    max_levels: int
    pins: Optional[Dict[str, int]] = None
    route_tab: Optional[np.ndarray] = None   # [S, N, RT_COLS]

    def shard_of(self, tenant_id: str) -> int:
        if self.pins:
            pin = self.pins.get(tenant_id)
            # same range guard as build_sharded: an out-of-range pin fell
            # back to hash placement at build time, so routing must too
            if pin is not None and 0 <= pin < self.n_shards:
                return pin
        return tenant_shard(tenant_id, self.n_shards)

    def root_of(self, tenant_id: str) -> int:
        return self.compiled[self.shard_of(tenant_id)].root_of(tenant_id)

    def device_bytes(self) -> Dict[str, object]:
        """Per-shard HBM accounting (ISSUE 8): exact bytes of the stacks
        ``MeshMatcher._compile_shadow`` actually uploads (node_tab never
        ships), each shard's padded slice next to its real rows — the
        capacity plane the multi-chip ROADMAP item lands against."""
        from ..obs.capacity import sharded_tables_device_bytes
        return sharded_tables_device_bytes(self)


def build_sharded(tries: Dict[str, SubscriptionTrie], n_shards: int, *,
                  max_levels: int = 16, probe_len: int = 16,
                  pins: Optional[Dict[str, int]] = None) -> ShardedTables:
    """Compile each tenant shard with a common edge-table capacity.

    All shards share one edge-table size (power of two) so the device-side
    mixing mask is identical; node/child arrays are -1-padded to the max.
    """
    by_shard: List[Dict[str, SubscriptionTrie]] = [dict() for _ in range(n_shards)]
    for tenant_id, trie in tries.items():
        sh = (pins or {}).get(tenant_id)
        if sh is None or not (0 <= sh < n_shards):
            sh = tenant_shard(tenant_id, n_shards)
        by_shard[sh][tenant_id] = trie

    compiled = [compile_tries(s, max_levels=max_levels, probe_len=probe_len)
                for s in by_shard]
    # common bucket count: the mixing mask must be identical across shards
    cap = max(ct.edge_tab.shape[0] for ct in compiled)
    # re-sync: rebuilding one shard at `cap` can itself overflow a bucket
    # and grow past it; iterate until all bucket counts agree.
    while True:
        compiled = [
            ct if ct.edge_tab.shape[0] == cap else compile_tries(
                by_shard[i], max_levels=max_levels, probe_len=probe_len,
                min_edge_cap=cap)
            for i, ct in enumerate(compiled)
        ]
        new_cap = max(ct.edge_tab.shape[0] for ct in compiled)
        if new_cap == cap:
            break
        cap = new_cap

    n_max = max(ct.node_tab.shape[0] for ct in compiled)
    e_max = max(ct.child_list.shape[0] for ct in compiled)
    node_tab = np.full((n_shards, n_max, NODE_COLS), -1, dtype=np.int32)
    edge_tab = np.full((n_shards, cap, probe_len, 4), -1, dtype=np.int32)
    child_list = np.full((n_shards, e_max), -1, dtype=np.int32)
    route_tab = np.zeros((n_shards, n_max, RT_COLS), dtype=np.int32)
    for s, ct in enumerate(compiled):
        n = ct.node_tab.shape[0]
        node_tab[s, :n] = ct.node_tab
        edge_tab[s] = ct.edge_tab
        child_list[s, :ct.child_list.shape[0]] = ct.child_list
        route_tab[s, :n] = route_cols_from_node_tab(ct.node_tab)
    return ShardedTables(node_tab=node_tab, edge_tab=edge_tab,
                         child_list=child_list, compiled=compiled,
                         n_shards=n_shards, probe_len=probe_len,
                         max_levels=max_levels,
                         pins=dict(pins) if pins else None,
                         route_tab=route_tab)


def make_mesh(n_replicas: int, n_shards: int,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    assert len(devices) >= n_replicas * n_shards, (
        f"need {n_replicas * n_shards} devices, have {len(devices)}")
    grid = np.array(devices[:n_replicas * n_shards]).reshape(
        n_replicas, n_shards)
    return Mesh(grid, (REPLICA_AXIS, SHARD_AXIS))


_STEP_CACHE: Dict[Tuple, object] = {}


def make_match_step(mesh: Mesh, *, probe_len: int, k_states: int = 32,
                    max_intervals: int = 32):
    """Build (or reuse) the jitted multi-device match step — memoized per
    (mesh, probe_len, k_states, max_intervals): clone_empty()/reset and
    per-range matchers must share one compiled program, not re-trace
    identical closures at ~seconds each.

    Inputs:  tables sharded [S, ...] over SHARD_AXIS (replicated over
             REPLICA_AXIS); probes [R, S, B, ...] split over both axes.
    Outputs: per-topic matched-slot INTERVALS [R, S, B, A] × (start,
             count) — the same compressed MatchedRoutes the single-chip
             walk_routes emits — plus per-topic totals, overflow, and a
             globally psum'd matched-route count. Cross-device traffic is
             exactly one psum: probes are shard-routed host-side, so the
             match itself needs no collective.
    """
    key = (mesh, probe_len, k_states, max_intervals)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached

    def local_step(edge_tab, child_list, route_tab,
                   tok_h1, tok_h2, lengths, roots, sys_mask):
        # the interval walk reads ONLY route_tab + edge_tab (+ child_list
        # for shape plumbing) — the 48B/row full node table never ships
        # to the mesh (route_tab stands in for the unused node_tab slot)
        trie = DeviceTrie(route_tab[0], edge_tab[0], child_list[0],
                          None, route_tab[0])
        probes = Probes(tok_h1[0, 0], tok_h2[0, 0], lengths[0, 0],
                        roots[0, 0], sys_mask[0, 0])
        ivl_s, ivl_c, n_routes, overflow = _route_walk(
            trie, probes, probe_len, k_states, "sort", max_intervals)
        total = jax.lax.psum(n_routes.sum(), (REPLICA_AXIS, SHARD_AXIS))
        expand = lambda x: x[None, None]
        return (expand(ivl_s), expand(ivl_c), expand(n_routes),
                expand(overflow), total)

    table_spec = P(SHARD_AXIS)
    probe_spec = P(REPLICA_AXIS, SHARD_AXIS)
    sharded = _shard_map(
        local_step, mesh=mesh,
        in_specs=(table_spec, table_spec, table_spec,
                  probe_spec, probe_spec, probe_spec, probe_spec, probe_spec),
        out_specs=(probe_spec, probe_spec, probe_spec, probe_spec, P()),
        # the walk's loop carries start as replicated constants and become
        # device-varying after the first level; skip the vma consistency check
        check_vma=False,
    )
    step = jax.jit(sharded)
    _STEP_CACHE[key] = step
    return step


@dataclass(frozen=True)
class ShardMoveCommand:
    """One balancer decision: re-pin a tenant's automaton shard (the
    TPU-shard analog of the reference's balancer→command pattern,
    KVStoreBalanceController.java:85)."""
    tenant_id: str
    from_shard: int
    to_shard: int
    reason: str


class ShardPlacementBalancer:
    """Heat-driven tenant→shard re-placement (closes SURVEY §2.8's
    placement row for the TPU plane).

    Observes per-tenant query heat (MeshMatcher.query_heat — the same
    role kv/load.py's KVLoadRecorder plays for KV ranges) and, when the
    hottest shard carries more than ``imbalance_factor`` × the coldest
    shard's heat, emits ONE command moving that shard's hottest tenant to
    the coldest shard. One move per round, like the KV balancers: each
    recompile is a placement epoch, and convergence beats thrash.
    """

    def __init__(self, *, imbalance_factor: float = 2.0,
                 min_heat: int = 64) -> None:
        self.imbalance_factor = imbalance_factor
        self.min_heat = min_heat

    def balance(self, heat: Dict[str, int], tables: ShardedTables
                ) -> Optional[ShardMoveCommand]:
        s = tables.n_shards
        shard_heat = [0] * s
        by_shard: List[List[Tuple[int, str]]] = [[] for _ in range(s)]
        for tenant_id, h in heat.items():
            sh = tables.shard_of(tenant_id)
            shard_heat[sh] += h
            by_shard[sh].append((h, tenant_id))
        hot = max(range(s), key=lambda i: shard_heat[i])
        cold = min(range(s), key=lambda i: shard_heat[i])
        if shard_heat[hot] < self.min_heat:
            return None
        if shard_heat[hot] <= self.imbalance_factor * max(1,
                                                          shard_heat[cold]):
            return None
        # move the hottest tenant whose relocation actually improves the
        # max: new cold-shard heat must stay below the current hot-shard
        # heat (moving a shard's ONLY tenant to a busier target is a loss)
        by_shard[hot].sort(reverse=True)
        for h, tenant_id in by_shard[hot]:
            if shard_heat[cold] + h < shard_heat[hot]:
                return ShardMoveCommand(
                    tenant_id=tenant_id, from_shard=hot, to_shard=cold,
                    reason=f"shard {hot} heat {shard_heat[hot]} > "
                           f"{self.imbalance_factor}x shard {cold} "
                           f"heat {shard_heat[cold]}")
        return None


class MeshMatcher(TpuMatcher):
    """The multi-device match plane with TpuMatcher's full mutation
    machinery — delta overlay, tombstones, background shadow-compile
    compaction — inherited unchanged; only the compile target (sharded
    tables placed over the mesh) and the walk (shard-routed [R,S,B]
    batches through the shard_map step) differ. A MeshMatcher therefore
    drops into every TpuMatcher seat (DistWorkerCoProc, DistWorker) and
    serves live add_route/remove_route traffic, answering VERDICT-r2's
    'MeshMatcher is a demo' finding."""

    # the shard-routed [R,S,B] device plane replaces _match_batch_device
    # wholesale, so the ISSUE 6 async dispatch ring (which drives
    # TpuMatcher._dispatch_device) degrades to this sync path; pipelining
    # the mesh step is the ROADMAP multi-chip item's business
    supports_async = False
    # ISSUE 9: the compile target is ShardedTables (per-shard stacks on a
    # mesh), not the single-chip PatchableTrie — mutations keep the
    # overlay+compaction path; per-shard independent patching is the
    # sharded-matcher ROADMAP follow-up this PR's arena layout unlocks
    supports_patching = False

    def __init__(self, tries: Optional[Dict[str, SubscriptionTrie]] = None,
                 mesh: Optional[Mesh] = None, *,
                 max_levels: int = 16, probe_len: int = 16,
                 k_states: int = 32, auto_compact: bool = True,
                 compact_threshold: int = 2048,
                 match_cache: Optional[bool] = None) -> None:
        assert mesh is not None, "MeshMatcher requires a mesh"
        super().__init__(max_levels=max_levels, k_states=k_states,
                         probe_len=probe_len, auto_compact=auto_compact,
                         compact_threshold=compact_threshold,
                         match_cache=match_cache)
        self.mesh = mesh
        self.n_replicas = mesh.shape[REPLICA_AXIS]
        self.n_shards = mesh.shape[SHARD_AXIS]
        self._step = make_match_step(mesh, probe_len=probe_len,
                                     k_states=k_states)
        self._table_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        # load-driven shard re-placement (SURVEY §2.8 placement): desired
        # tenant→shard pins; the serving snapshot routes by ITS OWN pin
        # copy until a recompile swaps the new assignment in
        self._pins: Dict[str, int] = {}
        self.query_heat: Dict[str, int] = {}
        self.shard_balancer = ShardPlacementBalancer()
        if tries:
            # seed path: write straight into authoritative + shadow state
            # and compile one base — building a full overlay that the
            # first refresh immediately discards would be wasted work
            for tenant_id, trie in tries.items():
                for route in trie.routes():
                    self.tries.setdefault(
                        tenant_id, SubscriptionTrie()).add(route)
                    self._shadow.setdefault(
                        tenant_id, SubscriptionTrie()).add(route)
            self._install_base(*self._compile_shadow())

    def clone_empty(self) -> "MeshMatcher":
        return MeshMatcher(mesh=self.mesh, max_levels=self.max_levels,
                           probe_len=self.probe_len, k_states=self.k_states,
                           auto_compact=self.auto_compact,
                           compact_threshold=self.compact_threshold,
                           match_cache=self.match_cache is not None)

    # ---------------- compile target: sharded tables on the mesh -----------

    def _compile_shadow(self) -> Tuple[ShardedTables, tuple]:
        import time as _time
        t0 = _time.perf_counter()
        self.compile_count += 1
        tables = build_sharded(self._shadow, self.n_shards,
                               max_levels=self.max_levels,
                               probe_len=self.probe_len,
                               pins=dict(self._pins))
        # node_tab intentionally NOT uploaded: the interval step never
        # gathers from it (route_tab carries every column the walk reads)
        dev = (jax.device_put(tables.edge_tab, self._table_sharding),
               jax.device_put(tables.child_list, self._table_sharding),
               jax.device_put(tables.route_tab, self._table_sharding))
        # ISSUE 8: the mesh plane now feeds the same compile accounting
        # (time + ledger attribution via _install_base) as single-chip —
        # it previously counted compiles but never their wall time
        self._last_compile_s = _time.perf_counter() - t0
        self.compile_time_s += self._last_compile_s
        return tables, dev

    # ---------------- load-driven shard re-placement ------------------------

    def pin_tenant(self, tenant_id: str, shard: int) -> None:
        """Pin a tenant's automaton to a shard; takes effect when the next
        recompiled snapshot swaps in (serving stays exact throughout —
        the installed snapshot keeps routing by its own assignment)."""
        assert 0 <= shard < self.n_shards
        self._pins[tenant_id] = shard

    def rebalance_step(self) -> Optional[ShardMoveCommand]:
        """One balancer round (≈ KVStoreBalanceController.java:85's
        observe→command→apply loop for TPU shards): consult the heat
        profile, apply at most one move, kick a background recompile,
        and decay the heat window."""
        # defer while a compaction is in flight: the compile thread reads
        # the frozen shadow, and replaying the log (or re-pinning) under
        # it would race; the heat profile persists, so the next round
        # re-evaluates after the swap
        if self._base_ct is None or self._compact_thread is not None:
            self._apply_pending_swap()
            return None
        cmd = self.shard_balancer.balance(self.query_heat, self._base_ct)
        if cmd is not None:
            self.pin_tenant(cmd.tenant_id, cmd.to_shard)
            # fold pending mutations + new pins into a fresh shadow build
            # on the compaction thread (_maybe_compact replays the log
            # itself, safely, before spawning); serving swaps atomically
            self._maybe_compact(force=True)
        # exponential decay: old heat fades, the window tracks current load
        self.query_heat = {t: h // 2 for t, h in self.query_heat.items()
                           if h // 2 > 0}
        return cmd

    # ---------------- query side -------------------------------------------

    def _match_batch_device(self, queries: Sequence[Tuple[str,
                                                          Sequence[str]]],
                            *, max_persistent_fanout: int = UNCAPPED_FANOUT,
                            max_group_fanout: int = UNCAPPED_FANOUT,
                            batch: Optional[int] = None,
                            per_device_batch: Optional[int] = None,
                            stats: Optional[dict] = None
                            ) -> List[MatchedRoutes]:
        """Match (tenant, topic_levels) pairs across the mesh; exact at
        every instant (base walk ⊕ overlay ⊖ tombstones) like TpuMatcher.
        The cache/dedup front-end (TpuMatcher.match_batch, ISSUE 4) is
        inherited — only the device plane differs. ``stats`` is accepted
        for signature parity with the front-end; the mesh plane has no
        device breaker yet (ROADMAP follow-up) so it never sets
        ``degraded``."""
        if not queries:
            return []
        self._apply_pending_swap()
        if self._base_ct is None:
            self.refresh()
        tables: ShardedTables = self._base_ct
        dev_edge, dev_child, dev_route = self._device_trie
        r, s = self.n_replicas, self.n_shards
        # route each query to its shard, then round-robin across replicas
        slots: List[List[int]] = [[] for _ in range(r * s)]
        for qi, (tenant_id, _) in enumerate(queries):
            # route via the INSTALLED snapshot's assignment (incl. pins)
            sh = tables.shard_of(tenant_id)
            rep = min(range(r), key=lambda j: len(slots[j * s + sh]))
            slots[rep * s + sh].append(qi)
            self.query_heat[tenant_id] = \
                self.query_heat.get(tenant_id, 0) + 1
        if per_device_batch is None:
            per_device_batch = batch
        if per_device_batch is None:
            # power-of-two bucket: keep the set of compiled shapes small
            need = max(1, max(len(x) for x in slots))
            b = 16
            while b < need:
                b *= 2
        else:
            b = per_device_batch
        assert all(len(x) <= b for x in slots)

        width = tables.max_levels + 1
        tok_h1 = np.zeros((r, s, b, width), dtype=np.int32)
        tok_h2 = np.zeros((r, s, b, width), dtype=np.int32)
        lengths = np.full((r, s, b), -1, dtype=np.int32)
        roots = np.full((r, s, b), -1, dtype=np.int32)
        sys_mask = np.zeros((r, s, b), dtype=bool)
        for rep in range(r):
            for sh in range(s):
                idxs = slots[rep * s + sh]
                if not idxs:
                    continue
                ct = tables.compiled[sh]
                topics = [queries[qi][1] for qi in idxs]
                qroots = [ct.root_of(queries[qi][0]) for qi in idxs]
                tk = tokenize(topics, qroots, max_levels=ct.max_levels,
                              salt=ct.salt, batch=b)
                tok_h1[rep, sh] = tk.tok_h1
                tok_h2[rep, sh] = tk.tok_h2
                lengths[rep, sh] = tk.lengths
                roots[rep, sh] = tk.roots
                sys_mask[rep, sh] = tk.sys_mask

        import time as _time
        t_disp = _time.perf_counter()
        ivl_s, ivl_c, _n_routes, overflow, _total = self._step(
            dev_edge, dev_child, dev_route,
            tok_h1, tok_h2, lengths, roots, sys_mask)
        t_fetch = _time.perf_counter()
        ivl_s = np.asarray(ivl_s)       # [R, S, B, A]
        ivl_c = np.asarray(ivl_c)
        overflow = np.asarray(overflow)
        t_done = _time.perf_counter()
        # ISSUE 8: the mesh walk feeds the same per-batch profile stream
        # as the single-chip paths (kernel tag distinguishes it); padded
        # rows = the full [R,S,B] grid minus the real queries
        from ..obs import OBS
        OBS.profiler.record_batch(
            n_queries=len(queries), batch=r * s * b, kernel="mesh",
            dispatch_s=t_fetch - t_disp, fetch_s=t_done - t_fetch,
            path="sync")
        # one vectorized expansion for the whole [R*S*B] grid
        a = ivl_s.shape[-1]
        flat_slots, flat_offs = expand_intervals(
            ivl_s.reshape(-1, a), ivl_c.reshape(-1, a))

        out: List[MatchedRoutes] = [MatchedRoutes() for _ in queries]
        for rep in range(r):
            for sh in range(s):
                ct = tables.compiled[sh]
                for bi, qi in enumerate(slots[rep * s + sh]):
                    tenant_id, levels = queries[qi]
                    tomb = self._tomb.get(tenant_id)
                    delta = self._delta.get(tenant_id)
                    if ct.root_of(tenant_id) < 0:
                        # tenant newer than the base: authoritative serve
                        trie = self.tries.get(tenant_id)
                        if trie is not None:
                            out[qi] = trie.match(
                                _parse_levels(levels),
                                max_persistent_fanout=max_persistent_fanout,
                                max_group_fanout=max_group_fanout)
                        continue
                    if overflow[rep, sh, bi] or lengths[rep, sh, bi] < 0:
                        trie = self.tries.get(tenant_id)
                        out[qi] = (trie.match(
                            _parse_levels(levels),
                            max_persistent_fanout=max_persistent_fanout,
                            max_group_fanout=max_group_fanout)
                            if trie is not None else MatchedRoutes())
                        continue
                    row = (rep * s + sh) * b + bi
                    srow = flat_slots[flat_offs[row]:flat_offs[row + 1]]
                    if not tomb and delta is None:
                        out[qi] = self._routes_from_slots(
                            ct, srow, max_persistent_fanout,
                            max_group_fanout)
                    else:
                        out[qi] = self._expand_with_overlay(
                            ct, srow, tomb or (), delta,
                            _parse_levels(levels),
                            max_persistent_fanout, max_group_fanout)
        return out
