"""Retained-message service (≈ bifromq-retain store + server + client).

Reference: RetainStoreCoProc (retain/store/RetainStoreCoProc.java:76) —
RW batchRetain (empty payload deletes, per [MQTT-3.3.1-6/7/10/11]), RO
batchMatch against the in-memory RetainTopicIndex; expiry GC driven by a
tenant GC runner (store/gc/RetainStoreGCProcessor). Here:

- authoritative state: per-tenant ``topic → RetainedMsg`` maps
- wildcard lookup: models.retained.RetainedIndex (device probes + fallback)
- expiry: lazy on match + an explicit ``gc()`` sweep (the delay-runner
  scheduling lands with the inbox milestone's DelayTaskRunner)
- per-tenant topic quota via IResourceThrottler (TOTAL_RETAIN_TOPICS)
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..models.retained import RetainedIndex
from ..plugin.events import Event, EventType, IEventCollector
from ..plugin.throttler import (AllowAllResourceThrottler, IResourceThrottler,
                                TenantResourceType)
from ..types import ClientInfo, Message
from ..utils import topic as topic_util

_NEVER = 0xFFFFFFFF


@dataclass
class RetainedMsg:
    topic: str
    message: Message
    publisher: ClientInfo
    expire_at: Optional[float]  # epoch seconds; None = never


class RetainService:
    def __init__(self, events: IEventCollector, *,
                 throttler: Optional[IResourceThrottler] = None,
                 index: Optional[RetainedIndex] = None,
                 clock=time.time) -> None:
        self.events = events
        self.throttler = throttler or AllowAllResourceThrottler()
        self.index = index or RetainedIndex()
        self.clock = clock
        self.tenants: Dict[str, Dict[str, RetainedMsg]] = {}

    # ---------------- mutations (≈ batchRetain) ----------------------------

    async def retain(self, publisher: ClientInfo, topic: str,
                     message: Message) -> bool:
        tenant_id = publisher.tenant_id
        levels = topic_util.parse(topic)
        store = self.tenants.setdefault(tenant_id, {})
        if not message.payload:
            # empty payload clears the retained message [MQTT-3.3.1-10/11]
            if store.pop(topic, None) is not None:
                self.index.remove_topic(tenant_id, levels, topic)
                if not store:
                    del self.tenants[tenant_id]
                self.events.report(Event(EventType.RETAIN_MSG_CLEARED,
                                         tenant_id, {"topic": topic}))
            return True
        if topic not in store and not self.throttler.has_resource(
                tenant_id, TenantResourceType.TOTAL_RETAIN_TOPICS):
            self.events.report(Event(EventType.RETAIN_ERROR, tenant_id,
                                     {"topic": topic, "reason": "quota"}))
            return False
        expire_at = None
        if message.expiry_seconds != _NEVER:
            expire_at = self.clock() + message.expiry_seconds
        store[topic] = RetainedMsg(topic=topic, message=message,
                                   publisher=publisher, expire_at=expire_at)
        self.index.add_topic(tenant_id, levels, topic)
        self.events.report(Event(EventType.MSG_RETAINED, tenant_id,
                                 {"topic": topic}))
        return True

    # ---------------- queries (≈ batchMatch) -------------------------------

    async def match(self, tenant_id: str, filter_levels: Sequence[str],
                    limit: int) -> List[Tuple[str, Message]]:
        res = await self.match_batch([(tenant_id, filter_levels)], limit)
        return res[0]

    async def match_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                          limit: int) -> List[List[Tuple[str, Message]]]:
        raw = self.index.match_batch(queries, limit=limit)
        now = self.clock()
        out: List[List[Tuple[str, Message]]] = []
        for (tenant_id, _), topics in zip(queries, raw):
            store = self.tenants.get(tenant_id, {})
            hits: List[Tuple[str, Message]] = []
            for topic in topics:
                rm = store.get(topic)
                if rm is None:
                    continue
                if rm.expire_at is not None and rm.expire_at <= now:
                    self._expire(tenant_id, rm)
                    continue
                if len(hits) < limit:
                    hits.append((topic, rm.message))
            out.append(hits)
        return out

    # ---------------- expiry GC (≈ RetainStoreGCProcessor) -----------------

    def gc(self, tenant_id: Optional[str] = None) -> int:
        now = self.clock()
        removed = 0
        tenants = ([tenant_id] if tenant_id is not None
                   else list(self.tenants))
        for t in tenants:
            store = self.tenants.get(t)
            if store is None:
                continue
            for rm in [x for x in store.values()
                       if x.expire_at is not None and x.expire_at <= now]:
                self._expire(t, rm)
                removed += 1
        return removed

    def _expire(self, tenant_id: str, rm: RetainedMsg) -> None:
        store = self.tenants.get(tenant_id)
        if store is None:
            return
        if store.pop(rm.topic, None) is not None:
            self.index.remove_topic(tenant_id, topic_util.parse(rm.topic),
                                    rm.topic)
            if not store:
                del self.tenants[tenant_id]

    def topic_count(self, tenant_id: str) -> int:
        return len(self.tenants.get(tenant_id, {}))
