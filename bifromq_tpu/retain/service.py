"""Retained-message service (≈ bifromq-retain store + server + client).

Reference: RetainStoreCoProc (retain/store/RetainStoreCoProc.java:76) —
RW batchRetain (empty payload deletes, per [MQTT-3.3.1-6/7/10/11]), RO
batchMatch against the in-memory RetainTopicIndex; expiry GC driven by a
tenant GC runner (store/gc/RetainStoreGCProcessor). Here:

- authoritative state: per-tenant ``topic → RetainedMsg`` maps
- wildcard lookup: models.retained.RetainedIndex (device probes + fallback)
- expiry: lazy on match + an explicit ``gc()`` sweep (the delay-runner
  scheduling lands with the inbox milestone's DelayTaskRunner)
- per-tenant topic quota via IResourceThrottler (TOTAL_RETAIN_TOPICS)
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..models.retained import RetainedIndex
from ..plugin.events import Event, EventType, IEventCollector
from ..plugin.throttler import (AllowAllResourceThrottler, IResourceThrottler,
                                TenantResourceType)
from ..types import ClientInfo, Message
from ..utils import topic as topic_util

_NEVER = 0xFFFFFFFF


@dataclass
class RetainedMsg:
    topic: str
    message: Message
    publisher: ClientInfo
    expire_at: Optional[float]  # epoch seconds; None = never


class RetainService:
    """Retained messages over a REPLICATED retain range: SET/DEL ride
    consensus (retain/coproc.py), wildcard matches serve from this
    replica's derived index; durable when an engine is provided."""

    def __init__(self, events: IEventCollector, *,
                 throttler: Optional[IResourceThrottler] = None,
                 index: Optional[RetainedIndex] = None,
                 engine=None, node_id: str = "local", voters=None,
                 transport=None, raft_store_factory=None,
                 tick_interval: float = 0.01, clock=time.time,
                 split_threshold: Optional[int] = None) -> None:
        from ..kv.engine import InMemKVEngine
        from ..kv.store import KVRangeStore
        from ..raft.transport import InMemTransport
        from .coproc import RetainCoProc

        self.events = events
        self.throttler = throttler or AllowAllResourceThrottler()
        self.clock = clock
        self.tick_interval = tick_interval
        self._node_id = node_id
        engine = engine or InMemKVEngine()
        self._transport = (transport if transport is not None
                           else InMemTransport())
        # the retain keyspace on a MULTI-RANGE store ("retain_" prefix
        # namespaces its spaces on a shared durable engine); per-range
        # derived RetainedIndex instances rebuild via reset-from-KV
        self._index_template = index
        self.kvstore = KVRangeStore(
            node_id, self._transport, engine,
            coproc_factory=self._mk_coproc,
            member_nodes=voters or [node_id],
            raft_store_factory=raft_store_factory,
            space_prefix="retain_", legacy_space="retain_data")
        self.kvstore.open()
        self.balance_controller = None
        if split_threshold is not None:
            from ..kv.balance import (KVStoreBalanceController,
                                      RangeSplitBalancer)
            self.balance_controller = KVStoreBalanceController(
                self.kvstore,
                [RangeSplitBalancer(max_keys=split_threshold)])
        self._tick_task = None

    def _mk_coproc(self, rid: str):
        from ..retained_plane import RetainedDeltaLog, RetainedScanPlane
        from .coproc import RetainCoProc
        tmpl = self._index_template
        idx = (RetainedIndex(max_levels=tmpl.max_levels,
                             k_states=tmpl.k_states)
               if tmpl is not None else None)
        coproc = RetainCoProc(idx)
        # ISSUE 13: the SUBSCRIBE-side scan plane (dispatch ring +
        # breaker + watchdog + filter-keyed cache) per range replica; the
        # index indirection survives reset-from-KV swaps
        plane = RetainedScanPlane(lambda: coproc.index)
        coproc.scan_plane = plane
        # per-range retained delta stream (GET /replication visibility +
        # the exact-invalidation feed; fires for raft-replayed ops too)
        log = RetainedDeltaLog(self._node_id, rid)
        if plane.cache is not None:
            coproc.delta_consumers.append(plane.cache.on_delta)
        coproc.delta_consumers.append(
            lambda tenant, levels, op:
                log.append(tenant or "", levels or (), op))
        # ISSUE 16: the standby feed — a warm retained replica attaches
        # here (arenas via capture_retained_base, deltas via the log)
        coproc.delta_log = log
        return coproc

    # ---------------- per-range access -------------------------------------

    def _coprocs(self):
        return self.kvstore.coprocs.values()

    def _coproc_for(self, tenant_id: str, topic: str):
        from ..kv import schema as _schema
        key = _schema.retain_key(tenant_id, topic)
        rid = self.kvstore.router.find_by_key(key)
        if rid is None:
            raise KeyError(f"no range covers retain key {key!r}")
        return self.kvstore.coprocs[rid], self.kvstore.ranges[rid]

    @property
    def index(self) -> RetainedIndex:
        """Single-range introspection convenience (tests)."""
        if len(self.kvstore.ranges) != 1:
            raise RuntimeError("multiple ranges; use kvstore.coprocs")
        return next(iter(self.kvstore.coprocs.values())).index

    def standby_feed(self, rid: Optional[str] = None):
        """(index-accessor, delta log) of one retain range — the
        in-process feed a :class:`RetainedStandby` attaches to
        (ISSUE 16). The accessor is a CALLABLE because reset-from-KV
        swaps the coproc's index object; the indirection keeps a
        long-lived standby capturing the live one."""
        if rid is None:
            if len(self.kvstore.coprocs) != 1:
                raise RuntimeError("multiple ranges; pass rid")
            rid = next(iter(self.kvstore.coprocs))
        coproc = self.kvstore.coprocs[rid]
        return (lambda: coproc.index), coproc.delta_log

    def retained_standby(self, rid: Optional[str] = None, *,
                         device=None):
        """Spawn a warm retained standby of one range: resyncs from
        this service's arenas (never KV), then rides the range's delta
        log; ``promote()`` hands back an index that serves wildcard
        retained scans immediately at arena-byte parity."""
        from ..replication.standby import RetainedStandby
        index_fn, log = self.standby_feed(rid)
        return RetainedStandby(leader_index=index_fn, leader_log=log,
                               device=device)

    async def start(self) -> None:
        import asyncio

        from ..raft.node import Role
        if self.kvstore.member_nodes == [self.kvstore.node_id]:
            for _ in range(10_000):
                if all(r.raft.role == Role.LEADER
                       for r in self.kvstore.ranges.values()):
                    break
                self.kvstore.tick()
                pump = getattr(self._transport, "pump", None)
                if pump is not None:
                    pump()

        async def loop():
            while True:
                self.kvstore.tick()
                pump = getattr(self._transport, "pump", None)
                if pump is not None:
                    pump()
                await asyncio.sleep(self.tick_interval)
        self._tick_task = asyncio.create_task(loop())
        if self.balance_controller is not None:
            await self.balance_controller.start()

    async def stop(self) -> None:
        if self.balance_controller is not None:
            await self.balance_controller.stop()
        if self._tick_task is not None:
            self._tick_task.cancel()
            self._tick_task = None
        self.kvstore.stop()

    def _decode(self, tenant_id: str, topic: str) -> Optional[RetainedMsg]:
        from .coproc import dec_retained
        coproc, _rng = self._coproc_for(tenant_id, topic)
        raw = coproc.values.get(tenant_id, {}).get(topic)
        if raw is None:
            return None
        expire_at, publisher, msg = dec_retained(raw)
        return RetainedMsg(topic=topic, message=msg, publisher=publisher,
                           expire_at=expire_at)

    # ---------------- mutations (≈ batchRetain) ----------------------------

    async def _mutate(self, tenant_id: str, topic: str, payload: bytes,
                      timeout: float = 5.0) -> bytes:
        import asyncio
        import time as _time

        from ..kv.range import propose_with_leader_wait
        deadline = _time.monotonic() + timeout
        while True:
            _coproc, rng = self._coproc_for(tenant_id, topic)
            out = await propose_with_leader_wait(
                rng, lambda rng=rng: rng.mutate_coproc(payload),
                timeout=max(0.01, deadline - _time.monotonic()),
                tick_single_voter=True)  # standalone use without start()
            if out != b"retry":
                return out
            if _time.monotonic() >= deadline:
                raise TimeoutError("retain op kept racing splits")
            await asyncio.sleep(0)    # split raced: re-resolve the range

    async def retain(self, publisher: ClientInfo, topic: str,
                     message: Message) -> bool:
        from ..kv import schema as _schema
        from .coproc import OP_DEL, OP_SET, enc_op, enc_retained

        tenant_id = publisher.tenant_id
        coproc, _rng = self._coproc_for(tenant_id, topic)
        existing = coproc.values.get(tenant_id, {})
        if not message.payload:
            # empty payload clears the retained message [MQTT-3.3.1-10/11]
            out = await self._mutate(tenant_id, topic,
                                     enc_op(OP_DEL, tenant_id, topic))
            if out == b"\x01":
                self.events.report(Event(EventType.RETAIN_MSG_CLEARED,
                                         tenant_id, {"topic": topic}))
            return True
        # quota is advisory under concurrency (check-then-propose): like
        # the reference's IResourceThrottler, has_resource is an
        # eventually-consistent gate, not a transactional reservation
        if topic not in existing and not self.throttler.has_resource(
                tenant_id, TenantResourceType.TOTAL_RETAIN_TOPICS):
            self.events.report(Event(EventType.MSG_RETAINED_ERROR, tenant_id,
                                     {"topic": topic, "reason": "quota"}))
            return False
        expire_at = None
        if message.expiry_seconds != _NEVER:
            expire_at = self.clock() + message.expiry_seconds
        value = enc_retained(_schema.encode_message(message), publisher,
                             expire_at)
        await self._mutate(tenant_id, topic,
                           enc_op(OP_SET, tenant_id, topic, value))
        self.events.report(Event(EventType.MSG_RETAINED, tenant_id,
                                 {"topic": topic}))
        return True

    # ---------------- queries (≈ batchMatch) -------------------------------

    async def match(self, tenant_id: str, filter_levels: Sequence[str],
                    limit: int) -> List[Tuple[str, Message]]:
        res = await self.match_batch([(tenant_id, filter_levels)], limit)
        return res[0]

    async def match_batch(self, queries: Sequence[Tuple[str, Sequence[str]]],
                          limit: int) -> List[List[Tuple[str, Message]]]:
        from ..kv import schema as _schema

        # per-tenant boundary intersect over the multi-range store, then
        # union per-range index hits (≈ dist worker's range routing)
        tenant_rids: Dict[str, List[str]] = {}
        for tenant_id, _lv in queries:
            if tenant_id not in tenant_rids:
                pfx = _schema.retain_prefix(tenant_id)
                tenant_rids[tenant_id] = self.kvstore.router.intersecting(
                    pfx, _schema.prefix_end(pfx))
        range_queries: Dict[str, List[int]] = {}
        for qi, (tenant_id, _lv) in enumerate(queries):
            for rid in tenant_rids[tenant_id]:
                range_queries.setdefault(rid, []).append(qi)
        raw: List[List[str]] = [[] for _ in queries]
        for rid, idxs in range_queries.items():
            sub = [queries[qi] for qi in idxs]
            coproc = self.kvstore.coprocs[rid]
            plane = getattr(coproc, "scan_plane", None)
            if plane is not None:
                # ISSUE 13: device scans serve through the shared
                # ring/breaker/watchdog plane — `retain.scan` span +
                # stage, filter-keyed cache, per-tenant SLO feeds,
                # oracle degradation on timeout/breaker-open
                res = await plane.scan_batch(sub, limit=limit)
            else:
                res = coproc.index.match_batch(sub, limit=limit)
            for qi, topics in zip(idxs, res):
                raw[qi].extend(topics)
        now = self.clock()
        out: List[List[Tuple[str, Message]]] = []
        for (tenant_id, _), topics in zip(queries, raw):
            hits: List[Tuple[str, Message]] = []
            for topic in topics:
                rm = self._decode(tenant_id, topic)
                if rm is None:
                    continue
                if rm.expire_at is not None and rm.expire_at <= now:
                    # best-effort consensus cleanup: a follower replica
                    # cannot propose — it still FILTERS the expired hit
                    # (the leader's gc sweep removes it for real)
                    try:
                        await self._expire(tenant_id, rm)
                    except Exception:  # noqa: BLE001
                        pass
                    continue
                if len(hits) < limit:
                    hits.append((topic, rm.message))
            out.append(hits)
        return out

    # ---------------- expiry GC (≈ RetainStoreGCProcessor) -----------------

    async def gc(self, tenant_id: Optional[str] = None) -> int:
        now = self.clock()
        removed = 0
        for coproc in list(self._coprocs()):
            tenants = ([tenant_id] if tenant_id is not None
                       else list(coproc.values))
            for t in tenants:
                for topic in list(coproc.values.get(t, {})):
                    rm = self._decode(t, topic)
                    if rm is not None and rm.expire_at is not None \
                            and rm.expire_at <= now:
                        await self._expire(t, rm)
                        removed += 1
        return removed

    async def _expire(self, tenant_id: str, rm: RetainedMsg) -> None:
        from .coproc import OP_DEL, enc_op
        await self._mutate(tenant_id, rm.topic,
                           enc_op(OP_DEL, tenant_id, rm.topic))

    def topic_count(self, tenant_id: str) -> int:
        return sum(len(c.values.get(tenant_id, {}))
                   for c in self._coprocs())

    def topics(self, tenant_id: str) -> List[str]:
        """Retained topic listing (introspection/API)."""
        out: List[str] = []
        for c in self._coprocs():
            out.extend(c.values.get(tenant_id, {}))
        return sorted(out)
