"""bifromq_tpu.retain — retained-message service (analog of bifromq-retain)."""
