"""Retain store as a replicated KV coprocessor (≈ retain-store
RetainStoreCoProc.java:76 on base-kv): batchRetain-style SET/DEL ops ride
consensus into the retain keyspace; the wildcard RetainedIndex + message
map are derived state rebuilt from KV on reset (≈ RetainTopicIndex rebuilt
on reset, store/index/RetainTopicIndex.java:35)."""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

from ..kv import schema
from ..kv.engine import IKVSpace, KVWriteBatch
from ..kv.range import IKVRangeCoProc
from ..models.retained import RetainedIndex
from ..types import ClientInfo
from ..utils import topic as topic_util

OP_SET = 0
OP_DEL = 1

_len16 = schema._len16
_read16 = schema._read_len16


def enc_retained(msg_bytes: bytes, publisher: ClientInfo,
                 expire_at: Optional[float]) -> bytes:
    out = bytearray(struct.pack(">d", -1.0 if expire_at is None
                                else expire_at))
    out += _len16(publisher.tenant_id.encode())
    out += _len16(publisher.type.encode())
    out += struct.pack(">H", len(publisher.metadata))
    for k, v in publisher.metadata:
        out += _len16(k.encode()) + _len16(v.encode())
    out += msg_bytes
    return bytes(out)


def dec_retained(buf: bytes):
    (exp,) = struct.unpack_from(">d", buf, 0)
    pos = 8
    tenant_b, pos = _read16(buf, pos)
    type_b, pos = _read16(buf, pos)
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    meta = []
    for _ in range(n):
        k, pos = _read16(buf, pos)
        v, pos = _read16(buf, pos)
        meta.append((k.decode(), v.decode()))
    msg = schema.decode_message(buf[pos:])
    publisher = ClientInfo(tenant_id=tenant_b.decode(),
                           type=type_b.decode(), metadata=tuple(meta))
    return (None if exp < 0 else exp), publisher, msg


class RetainCoProc(IKVRangeCoProc):
    """Applies retain SET/DEL deterministically; derived index per replica."""

    def __init__(self, index: Optional[RetainedIndex] = None) -> None:
        from ..kv.load import KVLoadRecorder

        self.index = index or RetainedIndex()
        # tenant -> topic -> value bytes (decoded lazily by the service)
        self.values: Dict[str, Dict[str, bytes]] = {}
        # multi-range hosting (boundary bounce + load profile)
        self.boundary = None
        self.load_recorder = KVLoadRecorder()

    def reset(self, reader: IKVSpace) -> None:
        self.index = RetainedIndex(max_levels=self.index.max_levels,
                                   k_states=self.index.k_states)
        self.values = {}
        for key, value in reader.iterate(
                schema.TAG_RETAIN, schema.prefix_end(schema.TAG_RETAIN)):
            tenant, topic = schema.split_retain_key(key)
            self.values.setdefault(tenant, {})[topic] = value
            self.index.add_topic(tenant, topic_util.parse(topic), topic)

    def query(self, input_data: bytes, reader: IKVSpace) -> bytes:
        return b""  # queries go through the local index/service

    def mutate(self, input_data: bytes, reader: IKVSpace,
               writer: KVWriteBatch) -> bytes:
        op = input_data[0]
        tenant_b, pos = _read16(input_data, 1)
        topic_b, pos = _read16(input_data, pos)
        tenant, topic = tenant_b.decode(), topic_b.decode()
        key = schema.retain_key(tenant, topic)
        if self.boundary is not None:
            start, end = self.boundary
            if key < start or (end is not None and key >= end):
                return b"retry"     # split moved the key: re-resolve
        self.load_recorder.record(key)
        store = self.values.setdefault(tenant, {})
        if op == OP_DEL:
            existed = store.pop(topic, None) is not None
            if existed:
                writer.delete(key)
                self.index.remove_topic(tenant, topic_util.parse(topic),
                                        topic)
            if not store:
                self.values.pop(tenant, None)
            return b"\x01" if existed else b"\x00"
        value = input_data[pos:]
        created = topic not in store
        store[topic] = value
        writer.put(key, value)
        if created:
            self.index.add_topic(tenant, topic_util.parse(topic), topic)
        return b"\x01" if created else b"\x00"


def enc_op(op: int, tenant: str, topic: str, value: bytes = b"") -> bytes:
    return (bytes([op]) + _len16(tenant.encode()) + _len16(topic.encode())
            + value)
