"""Retain store as a replicated KV coprocessor (≈ retain-store
RetainStoreCoProc.java:76 on base-kv): batchRetain-style SET/DEL ops ride
consensus into the retain keyspace; the wildcard RetainedIndex + message
map are derived state rebuilt from KV on reset (≈ RetainTopicIndex rebuilt
on reset, store/index/RetainTopicIndex.java:35)."""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

from ..kv import schema
from ..kv.engine import IKVSpace, KVWriteBatch
from ..kv.range import IKVRangeCoProc
from ..models.retained import RetainedIndex
from ..types import ClientInfo
from ..utils import topic as topic_util

OP_SET = 0
OP_DEL = 1

_len16 = schema._len16
_read16 = schema._read_len16


def enc_retained(msg_bytes: bytes, publisher: ClientInfo,
                 expire_at: Optional[float]) -> bytes:
    out = bytearray(struct.pack(">d", -1.0 if expire_at is None
                                else expire_at))
    out += _len16(publisher.tenant_id.encode())
    out += _len16(publisher.type.encode())
    out += struct.pack(">H", len(publisher.metadata))
    for k, v in publisher.metadata:
        out += _len16(k.encode()) + _len16(v.encode())
    out += msg_bytes
    return bytes(out)


def dec_retained(buf: bytes):
    (exp,) = struct.unpack_from(">d", buf, 0)
    pos = 8
    tenant_b, pos = _read16(buf, pos)
    type_b, pos = _read16(buf, pos)
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    meta = []
    for _ in range(n):
        k, pos = _read16(buf, pos)
        v, pos = _read16(buf, pos)
        meta.append((k.decode(), v.decode()))
    msg = schema.decode_message(buf[pos:])
    publisher = ClientInfo(tenant_id=tenant_b.decode(),
                           type=type_b.decode(), metadata=tuple(meta))
    return (None if exp < 0 else exp), publisher, msg


class RetainCoProc(IKVRangeCoProc):
    """Applies retain SET/DEL deterministically; derived index per replica.

    ISSUE 13: the derived index is PATCHED in place per applied op (the
    apply stream is exactly the retained delta stream), and the coproc
    fans each applied mutation out to ``delta_consumers`` — the scan
    cache's exact invalidation and the per-range retained delta log —
    for raft-replayed mutations too. ``reset`` (rebuild-from-KV) emits
    the wholesale ``(None, None, "reset")`` record, the retained twin of
    a stream anchor.
    """

    def __init__(self, index: Optional[RetainedIndex] = None) -> None:
        from ..kv.load import KVLoadRecorder

        # (tenant, topic_levels, op) consumers; op in set|del|reset
        self.delta_consumers: list = []
        # the SUBSCRIBE-side serving plane (armed by RetainService; a
        # bare coproc — tests, RO query — serves without one)
        self.scan_plane = None
        self.index = index or RetainedIndex()
        self._arm_index(self.index)
        # tenant -> topic -> value bytes (decoded lazily by the service)
        self.values: Dict[str, Dict[str, bytes]] = {}
        # multi-range hosting (boundary bounce + load profile)
        self.boundary = None
        self.load_recorder = KVLoadRecorder()

    def _arm_index(self, index: RetainedIndex) -> None:
        index.delta_hooks.append(self._emit_delta)

    def _emit_delta(self, tenant, topic_levels, op) -> None:
        for cb in list(self.delta_consumers):
            try:
                cb(tenant, topic_levels, op)
            except Exception:  # noqa: BLE001 — observers must not break
                import logging
                logging.getLogger(__name__).exception("retain delta hook")

    def reset(self, reader: IKVSpace) -> None:
        self.index = RetainedIndex(max_levels=self.index.max_levels,
                                   k_states=self.index.k_states)
        self.values = {}
        for key, value in reader.iterate(
                schema.TAG_RETAIN, schema.prefix_end(schema.TAG_RETAIN)):
            tenant, topic = schema.split_retain_key(key)
            self.values.setdefault(tenant, {})[topic] = value
            self.index.add_topic(tenant, topic_util.parse(topic), topic)
        # the rebuilt world renumbers everything: consumers degrade to
        # their wholesale form (scan cache bump), THEN new deltas flow
        self._arm_index(self.index)
        self._emit_delta(None, None, "reset")

    # RO wildcard match over the wire (retain-store-as-a-service read
    # side, ≈ RetainStoreCoProc's RO batchMatch): a replica-less frontend
    # matches retained messages via the store RPC. Wire:
    #   req  := 0x01 ‖ len16 tenant ‖ u32 limit ‖ len16 filter
    #   resp := u32 n ‖ n × (len16 topic ‖ len32 stored-value)
    Q_MATCH = 1

    def query(self, input_data: bytes, reader: IKVSpace) -> bytes:
        from ..kv.range import BoundaryBounce

        if not input_data or input_data[0] != self.Q_MATCH:
            return b""  # local reads go through the index/service
        tenant_b, pos = _read16(input_data, 1)
        (limit,) = struct.unpack_from(">I", input_data, pos)
        pos += 4
        filter_b, pos = _read16(input_data, pos)
        tenant = tenant_b.decode()
        if self.boundary is not None:
            start, end = self.boundary
            pfx = schema.retain_prefix(tenant)
            if pfx < start or (end is not None and pfx >= end):
                # split/seal raced the routing: bounce, never answer
                # "no retained messages" from an emptied span
                raise BoundaryBounce(tenant)
        topics = self.index.match_batch(
            [(tenant, topic_util.parse(filter_b.decode()))],
            limit=limit)[0]
        vals = self.values.get(tenant, {})
        out = bytearray(struct.pack(">I", 0))
        n = 0
        for topic in topics:
            raw = vals.get(topic)
            if raw is None:
                continue
            out += _len16(topic.encode())
            out += struct.pack(">I", len(raw)) + raw
            n += 1
            if n >= limit:
                break
        struct.pack_into(">I", out, 0, n)
        return bytes(out)

    def mutate(self, input_data: bytes, reader: IKVSpace,
               writer: KVWriteBatch) -> bytes:
        op = input_data[0]
        tenant_b, pos = _read16(input_data, 1)
        topic_b, pos = _read16(input_data, pos)
        tenant, topic = tenant_b.decode(), topic_b.decode()
        key = schema.retain_key(tenant, topic)
        if self.boundary is not None:
            start, end = self.boundary
            if key < start or (end is not None and key >= end):
                return b"retry"     # split moved the key: re-resolve
        self.load_recorder.record(key)
        store = self.values.setdefault(tenant, {})
        if op == OP_DEL:
            existed = store.pop(topic, None) is not None
            if existed:
                writer.delete(key)
                self.index.remove_topic(tenant, topic_util.parse(topic),
                                        topic)
            if not store:
                self.values.pop(tenant, None)
            return b"\x01" if existed else b"\x00"
        value = input_data[pos:]
        created = topic not in store
        store[topic] = value
        writer.put(key, value)
        if created:
            self.index.add_topic(tenant, topic_util.parse(topic), topic)
        return b"\x01" if created else b"\x00"


def enc_op(op: int, tenant: str, topic: str, value: bytes = b"") -> bytes:
    return (bytes([op]) + _len16(tenant.encode()) + _len16(topic.encode())
            + value)


def enc_match_query(tenant_id: str, topic_filter: str,
                    limit: int) -> bytes:
    return (bytes([RetainCoProc.Q_MATCH]) + _len16(tenant_id.encode())
            + struct.pack(">I", limit) + _len16(topic_filter.encode()))


def dec_match_reply(buf: bytes):
    """[(topic, expire_at, publisher, Message)] from a Q_MATCH reply."""
    (n,) = struct.unpack_from(">I", buf, 0)
    pos = 4
    out = []
    for _ in range(n):
        topic_b, pos = _read16(buf, pos)
        (rlen,) = struct.unpack_from(">I", buf, pos)
        pos += 4
        expire_at, publisher, msg = dec_retained(buf[pos:pos + rlen])
        pos += rlen
        out.append((topic_b.decode(), expire_at, publisher, msg))
    return out


class RemoteRetainReader:
    """Match retained messages on a SHARED retain store over the wire
    (routes by the tenant's retain prefix through ClusterKVClient) —
    expired hits are filtered client-side like the local service does.

    Routing targets the range covering the tenant's prefix START; a
    tenant whose retain keyspace was split across ranges needs the
    client to union over ``ClusterKVClient.ranges()`` (the local
    RetainService does exactly that with its in-proc router)."""

    def __init__(self, client, *, clock=None) -> None:
        import time as _time
        self.client = client        # kv.meta.ClusterKVClient
        self.clock = clock or _time.time

    async def match(self, tenant_id: str, topic_filter: str,
                    limit: int = 100):
        out = await self.client.query(
            schema.retain_prefix(tenant_id),
            enc_match_query(tenant_id, topic_filter, limit))
        now = self.clock()
        return [(topic, msg) for topic, expire_at, _pub, msg
                in dec_match_reply(out)
                if expire_at is None or expire_at > now]
