"""``python -m bifromq_tpu --config conf.yml`` — standalone broker CLI."""

import os

if os.environ.get("JAX_PLATFORMS"):
    # config-level override beats a sitecustomize-registered platform plugin
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from .starter import main

main()
