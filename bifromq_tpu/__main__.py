"""``python -m bifromq_tpu --config conf.yml`` — standalone broker CLI."""

from .utils.jaxenv import pin_jax_platform

pin_jax_platform()

from .starter import main  # noqa: E402

main()
