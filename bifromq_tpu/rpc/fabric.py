"""RPC fabric: multiplexed length-prefixed RPC over asyncio TCP.

Re-expression of base-rpc (SURVEY.md §2.4) without gRPC (not in the image):

- ``RPCServer`` binds one port and hosts many named services
  (≈ RPCServer.java: one server, many BluePrints). A service is a map of
  method name → async handler(payload: bytes, headers) -> bytes.
- ``RPCClient`` multiplexes concurrent calls over one connection with
  correlation ids; calls carrying an ``order_key`` execute in FIFO order
  per key on the server (≈ orderKey-pinned ManagedRequestPipeline /
  ResponsePipeline semantics: one ordered stream per key).
- ``ServiceRegistry`` is the traffic-governor analog: servers announce
  ``(service, address)`` into a gossip agent's metadata
  (≈ RPCServiceAnnouncer publishing ServerEndpoint into the traffic
  governor ORMap CRDT, RPCServiceTrafficService.java:30); clients pick a
  server by rendezvous hash over a tenant key (≈ HRWRouter tenant-aware
  load balancing).

Wire format (all big-endian):
  frame   := u32 length ‖ body
  request := 0x01 ‖ u64 id ‖ len16 service ‖ len16 method ‖ len16 order_key
             ‖ payload
  reply   := 0x02 ‖ u64 id ‖ u8 status ‖ payload      (status 0 = OK)
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import struct
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

_REQ = 0x01
_REP = 0x02

Handler = Callable[[bytes, str], Awaitable[bytes]]


class RPCError(Exception):
    pass


def _len16(b: bytes) -> bytes:
    return struct.pack(">H", len(b)) + b


def _read16(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n = struct.unpack_from(">H", buf, pos)[0]
    pos += 2
    return buf[pos:pos + n], pos + n


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)
    (n,) = struct.unpack(">I", hdr)
    return await reader.readexactly(n)


def _write_frame(writer: asyncio.StreamWriter, body: bytes) -> None:
    writer.write(struct.pack(">I", len(body)) + body)


class _OrderedRunner:
    """Per-order-key FIFO execution (≈ base-util AsyncRunner: a serialized
    async task queue; the reference pins one response pipeline per key)."""

    def __init__(self) -> None:
        self._queues: Dict[str, asyncio.Queue] = {}
        self._tasks: Dict[str, asyncio.Task] = {}

    def submit(self, key: str, coro_fn) -> None:
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = asyncio.Queue()
            self._tasks[key] = asyncio.create_task(self._drain(key, q))
        q.put_nowait(coro_fn)

    async def _drain(self, key: str, q: asyncio.Queue) -> None:
        while True:
            try:
                coro_fn = await asyncio.wait_for(q.get(), timeout=30)
            except asyncio.TimeoutError:
                # idle: retire the queue (bounded state per key)
                if q.empty():
                    self._queues.pop(key, None)
                    self._tasks.pop(key, None)
                    return
                continue
            try:
                await coro_fn()
            except Exception:  # noqa: BLE001
                log.exception("ordered task failed (key=%s)", key)

    def close(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._queues.clear()
        self._tasks.clear()


# process-local server table: calls addressed to a server in THIS process
# bypass TCP entirely (≈ the reference's in-proc RPC bypass, where client
# and server stubs short-circuit inside one JVM)
_LOCAL_SERVERS: Dict[str, "RPCServer"] = {}


class RPCServer:
    """One listener hosting many services.

    ``ssl_context`` (server-side) enables TLS on the listener — the
    counterpart of the reference's SSL-capable RPC servers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 ssl_context=None) -> None:
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._services: Dict[str, Dict[str, Handler]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()
        self._local_runner: Optional[_OrderedRunner] = None

    def register(self, service: str, methods: Dict[str, Handler]) -> None:
        self._services.setdefault(service, {}).update(methods)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host,
                                                  self.port,
                                                  ssl=self.ssl_context)
        self.port = self._server.sockets[0].getsockname()[1]
        _LOCAL_SERVERS[self.address] = self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        _LOCAL_SERVERS.pop(self.address, None)
        if self._server is not None:
            self._server.close()
        if self._local_runner is not None:
            self._local_runner.close()
            self._local_runner = None
        for t in list(self._conn_tasks):
            t.cancel()

    async def dispatch_local(self, service: str, method: str,
                             payload: bytes, order_key: str) -> bytes:
        """In-proc bypass entry: same semantics as the wire path —
        handler errors surface as RPCError, and calls sharing an
        order_key execute FIFO through the same runner machinery."""
        handler = self._services.get(service, {}).get(method)
        if handler is None:
            raise RPCError("no such method")

        async def run() -> bytes:
            try:
                return await handler(payload, order_key)
            except Exception as e:  # noqa: BLE001 — wire-path parity
                raise RPCError(repr(e)) from e

        if not order_key:
            return await run()
        if self._local_runner is None:
            self._local_runner = _OrderedRunner()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()

        async def ordered() -> None:
            try:
                res = await run()
                if not fut.done():      # caller may have been cancelled
                    fut.set_result(res)
            except BaseException as e:  # noqa: BLE001
                if not fut.done():
                    fut.set_exception(e)
        self._local_runner.submit(order_key, ordered)
        return await fut

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        runner = _OrderedRunner()
        send_lock = asyncio.Lock()
        try:
            while True:
                body = await _read_frame(reader)
                # hostile/truncated frames (port scanners, bad peers) drop
                # the connection without an unhandled-traceback path
                if not body or body[0] != _REQ:
                    if not body:
                        break
                    continue
                try:
                    (rid,) = struct.unpack_from(">Q", body, 1)
                    service_b, pos = _read16(body, 9)
                    method_b, pos = _read16(body, pos)
                    okey_b, pos = _read16(body, pos)
                except (struct.error, IndexError):
                    break
                payload = body[pos:]
                handler = self._services.get(service_b.decode(), {}).get(
                    method_b.decode())

                async def run(rid=rid, handler=handler, payload=payload,
                              okey=okey_b.decode()):
                    if handler is None:
                        status, out = 1, b"no such method"
                    else:
                        try:
                            out = await handler(payload, okey)
                            status = 0
                        except Exception as e:  # noqa: BLE001
                            status, out = 1, repr(e).encode()
                    async with send_lock:
                        _write_frame(writer, bytes([_REP])
                                     + struct.pack(">Q", rid)
                                     + bytes([status]) + out)
                        await writer.drain()

                if okey_b:
                    runner.submit(okey_b.decode(), run)
                else:
                    asyncio.ensure_future(run())
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            runner.close()
            writer.close()
            self._conn_tasks.discard(task)


class RPCClient:
    """Multiplexed client for one server address; reconnects lazily.
    Calls addressed to a server living in THIS process short-circuit
    through ``dispatch_local`` (no sockets). ``ssl_context`` dials TLS."""

    def __init__(self, host: str, port: int, *, ssl_context=None,
                 local_bypass: bool = True) -> None:
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self.local_bypass = local_bypass
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._conn_lock = asyncio.Lock()

    @classmethod
    def from_address(cls, address: str) -> "RPCClient":
        host, port = address.rsplit(":", 1)
        return cls(host, int(port))

    async def _ensure_conn(self) -> asyncio.StreamWriter:
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return self._writer
            reader, writer = await asyncio.open_connection(
                self.host, self.port, ssl=self.ssl_context)
            # per-connection pending map: a dead connection's cleanup must
            # only fail ITS calls, never a successor connection's
            self._writer = writer
            self._pending = {}
            self._reader_task = asyncio.create_task(
                self._read_loop(reader, writer, self._pending))
            return writer

    async def _read_loop(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter,
                         pending: Dict[int, asyncio.Future]) -> None:
        try:
            while True:
                body = await _read_frame(reader)
                if not body or body[0] != _REP:
                    if not body:
                        break
                    continue
                (rid,) = struct.unpack_from(">Q", body, 1)
                status = body[9]
                payload = body[10:]
                fut = pending.pop(rid, None)
                if fut is not None and not fut.done():
                    if status == 0:
                        fut.set_result(payload)
                    else:
                        fut.set_exception(RPCError(payload.decode()))
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(RPCError("connection lost"))
            pending.clear()
            writer.close()
            if self._writer is writer:
                self._writer = None

    async def call(self, service: str, method: str, payload: bytes, *,
                   order_key: str = "", timeout: float = 30.0) -> bytes:
        if self.local_bypass:
            local = _LOCAL_SERVERS.get(f"{self.host}:{self.port}")
            if (local is not None and local._server is not None
                    and local._server.is_serving()):
                # in-proc bypass: no sockets, no codec. The handler runs
                # as a DETACHED task shielded from the client timeout —
                # on the wire path a timed-out call still completes
                # server-side, and the bypass must not diverge (a
                # cancelled mutate could be half-applied)
                task = asyncio.ensure_future(local.dispatch_local(
                    service, method, payload, order_key))
                return await asyncio.wait_for(asyncio.shield(task),
                                              timeout)
        writer = await self._ensure_conn()
        pending = self._pending
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        pending[rid] = fut
        body = (bytes([_REQ]) + struct.pack(">Q", rid)
                + _len16(service.encode()) + _len16(method.encode())
                + _len16(order_key.encode()) + payload)
        _write_frame(writer, body)
        await writer.drain()
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            # a timed-out call must not leak its correlation entry
            pending.pop(rid, None)

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class ServiceRegistry:
    """Service discovery (traffic governor analog, three backends):

    - **CRDT** (the reference way, RPCServiceTrafficService.java:30): each
      server announces ``(service → address)`` into a replicated ORMap
      ("traffic" uri) on a CRDTStore; anti-entropy spreads it.
    - **gossip agents**: announce into agent ``rpc:<service>`` metadata.
    - **static**: explicit addresses (tests / config files).

    Clients rendezvous-hash a tenant key over the union of live endpoints
    (HRWRouter semantics)."""

    TRAFFIC_URI = "traffic"
    DIRECTIVE_URI = "traffic-directive"

    def __init__(self, agent_host=None, crdt_store=None, *,
                 local_bypass: bool = True,
                 client_ssl_context=None) -> None:
        self.agent_host = agent_host
        self.crdt_store = crdt_store
        self.local_bypass = local_bypass        # in-proc short-circuit
        self.client_ssl_context = client_ssl_context  # TLS dialing
        self._static: Dict[str, List[str]] = {}
        self._clients: Dict[str, RPCClient] = {}
        # traffic governor state (≈ IRPCServiceTrafficGovernor.java:29):
        # address -> server-group tag, and per-service tenant-prefix
        # directives mapping group -> weight
        self._groups: Dict[str, str] = {}
        self._directives: Dict[str, Dict[str, Dict[str, int]]] = {}

    # -- server side --------------------------------------------------------

    def announce(self, service: str, address: str,
                 group: str = "") -> None:
        """Announce an endpoint, optionally tagged with a server GROUP
        (the traffic governor's unit of weighted tenant assignment)."""
        element = f"{address}|{group}" if group else address
        if self.crdt_store is not None:
            self.crdt_store.set_add(self.TRAFFIC_URI, service, element)
        if self.agent_host is not None:
            self.agent_host.host_agent(f"rpc:{service}",
                                       {"address": address,
                                        "group": group})
        self._static.setdefault(service, []).append(address)
        if group:
            self._groups[address] = group

    # -- traffic directives (≈ setTrafficDirective) -------------------------

    def set_traffic_directive(self, service: str, tenant_prefix: str,
                              group_weights: Dict[str, int]) -> None:
        """Route tenants matching ``tenant_prefix`` across server groups
        by weight (weight 0 = drain). The LONGEST matching prefix wins;
        tenants matching no directive spread over all endpoints."""
        self._directives.setdefault(service, {})[tenant_prefix] = \
            dict(group_weights)
        getattr(self, "_directive_cache", {}).pop(service, None)
        if self.crdt_store is not None:
            import json as _json
            key = f"{service}/{tenant_prefix}"
            for el in self.crdt_store.elements(self.DIRECTIVE_URI, key):
                self.crdt_store.set_remove(self.DIRECTIVE_URI, key, el)
            self.crdt_store.set_add(self.DIRECTIVE_URI, key,
                                    _json.dumps(group_weights,
                                                sort_keys=True))

    def traffic_directives(self) -> Dict[str, Dict[str, Dict[str, int]]]:
        """Snapshot of service → tenant_prefix → {group: weight} (the
        apiserver's GET /traffic introspection)."""
        return {svc: {pfx: dict(gw) for pfx, gw in rules.items()}
                for svc, rules in self._directives.items()}

    def unset_traffic_directive(self, service: str,
                                tenant_prefix: str) -> None:
        self._directives.get(service, {}).pop(tenant_prefix, None)
        getattr(self, "_directive_cache", {}).pop(service, None)
        if self.crdt_store is not None:
            self.crdt_store.remove_key(self.DIRECTIVE_URI,
                                       f"{service}/{tenant_prefix}")

    _DIRECTIVE_CACHE_TTL = 1.0

    def _directive_for(self, service: str,
                       key: str) -> Optional[Dict[str, int]]:
        import time as _time
        cached = getattr(self, "_directive_cache", None)
        if cached is None:
            cached = self._directive_cache = {}
        hit = cached.get(service)
        if hit is not None and hit[0] > _time.monotonic():
            directives = hit[1]
        else:
            directives = dict(self._directives.get(service, {}))
            if self.crdt_store is not None:
                import json as _json
                prefix = f"{service}/"
                for k in self.crdt_store.keys(self.DIRECTIVE_URI):
                    if k.startswith(prefix):
                        for el in self.crdt_store.elements(
                                self.DIRECTIVE_URI, k):
                            try:
                                directives.setdefault(k[len(prefix):],
                                                      _json.loads(el))
                            except ValueError:
                                continue
            # bounded staleness beats O(directives) JSON parsing on every
            # routed message (pick() is the per-request hot path)
            cached[service] = (_time.monotonic()
                               + self._DIRECTIVE_CACHE_TTL, directives)
        best = None
        for pfx in directives:
            if key.startswith(pfx) and (best is None
                                        or len(pfx) > len(best)):
                best = pfx
        return directives[best] if best is not None else None

    def withdraw(self, service: str, address: str) -> None:
        if self.crdt_store is not None:
            # grouped endpoints are stored as "address|group": remove every
            # element whose address part matches
            for el in list(self.crdt_store.elements(self.TRAFFIC_URI,
                                                    service)):
                if el == address or el.startswith(address + "|"):
                    self.crdt_store.set_remove(self.TRAFFIC_URI, service,
                                               el)
        if self.agent_host is not None:
            self.agent_host.stop_agent(f"rpc:{service}")
        if address in self._static.get(service, []):
            self._static[service].remove(address)

    # -- client side --------------------------------------------------------

    def endpoints(self, service: str) -> List[str]:
        out = []
        if self.crdt_store is not None:
            for el in self.crdt_store.elements(self.TRAFFIC_URI, service):
                addr, _, group = el.partition("|")
                if group:
                    self._groups[addr] = group
                if addr not in out:
                    out.append(addr)
        if self.agent_host is not None:
            for _node, meta in self.agent_host.agent_members(
                    f"rpc:{service}").items():
                addr = (meta or {}).get("address")
                if addr and addr not in out:
                    out.append(addr)
                    if (meta or {}).get("group"):
                        self._groups[addr] = meta["group"]
        for addr in self._static.get(service, []):
            if addr not in out:
                out.append(addr)
        return sorted(out)

    def pick(self, service: str, key: str) -> Optional[str]:
        """Weighted rendezvous hash (≈ HRWRouter with traffic-governor
        directives): the longest tenant-prefix directive scales each
        endpoint's score by its group weight; weight-0 groups drain."""
        eps = self.endpoints(service)
        if not eps:
            return None
        directive = self._directive_for(service, key)
        if directive is not None:
            weighted = [ep for ep in eps
                        if directive.get(self._groups.get(ep, ""), 0) > 0]
            if weighted:
                def wscore(ep: str) -> float:
                    w = directive.get(self._groups.get(ep, ""), 0)
                    h = hashlib.blake2b(f"{ep}|{key}".encode(),
                                        digest_size=8).digest()
                    # weighted rendezvous: u^(1/w) ordering via -w/ln(u).
                    # Map the top 52 hash bits into (0,1) EXCLUSIVE with
                    # representable float endpoints — a u that rounds to
                    # exactly 0.0 or 1.0 would crash log for that
                    # (endpoint, tenant) pair deterministically forever
                    import math
                    u = ((int.from_bytes(h, "big") >> 12) + 1) \
                        / float((1 << 52) + 2)
                    return -w / math.log(u)
                return max(weighted, key=wscore)

        def score(ep: str) -> int:
            h = hashlib.blake2b(f"{ep}|{key}".encode(),
                                digest_size=8).digest()
            return int.from_bytes(h, "big")
        return max(eps, key=score)

    def client(self, service: str, key: str) -> Optional[RPCClient]:
        addr = self.pick(service, key)
        if addr is None:
            return None
        return self.client_for(addr)

    def client_for(self, addr: str) -> RPCClient:
        c = self._clients.get(addr)
        if c is None:
            host, port = addr.rsplit(":", 1)
            c = self._clients[addr] = RPCClient(
                host, int(port), ssl_context=self.client_ssl_context,
                local_bypass=self.local_bypass)
        return c

    async def close(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
