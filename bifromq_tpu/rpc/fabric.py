"""RPC fabric: multiplexed length-prefixed RPC over asyncio TCP.

Re-expression of base-rpc (SURVEY.md §2.4) without gRPC (not in the image):

- ``RPCServer`` binds one port and hosts many named services
  (≈ RPCServer.java: one server, many BluePrints). A service is a map of
  method name → async handler(payload: bytes, headers) -> bytes.
- ``RPCClient`` multiplexes concurrent calls over one connection with
  correlation ids; calls carrying an ``order_key`` execute in FIFO order
  per key on the server (≈ orderKey-pinned ManagedRequestPipeline /
  ResponsePipeline semantics: one ordered stream per key).
- ``ServiceRegistry`` is the traffic-governor analog: servers announce
  ``(service, address)`` into a gossip agent's metadata
  (≈ RPCServiceAnnouncer publishing ServerEndpoint into the traffic
  governor ORMap CRDT, RPCServiceTrafficService.java:30); clients pick a
  server by rendezvous hash over a tenant key (≈ HRWRouter tenant-aware
  load balancing).

Wire format (all big-endian):
  frame   := u32 length ‖ body
  request := 0x01 ‖ u64 id ‖ len16 service ‖ len16 method ‖ len16 order_key
             ‖ payload
  request2:= 0x03 ‖ u64 id ‖ len16 service ‖ len16 method ‖ len16 order_key
             ‖ u32 deadline_ms ‖ payload       (deadline header, ISSUE 1 —
             the remaining call budget, ≈ gRPC's grpc-timeout; 0 = none)
  request3:= 0x04 ‖ u64 id ‖ len16 service ‖ len16 method ‖ len16 order_key
             ‖ u32 deadline_ms ‖ u8 trace_len ‖ trace_ctx ‖ payload
             (request2 header family extended with a trace context,
             ISSUE 2: trace id ‖ parent span id ‖ sampled flag ‖ sender
             HLC stamp — the receiver merges the stamp so cross-process
             spans order causally)
  reply   := 0x02 ‖ u64 id ‖ u8 status ‖ payload      (status 0 = OK)

Resilience (ISSUE 1): transport failures surface as ``RPCTransportError``
and timeouts as ``RPCTimeoutError`` (both ``RPCError``), call outcomes
feed per-endpoint circuit breakers so ``ServiceRegistry.pick`` routes
around open circuits, and the process-global ``resilience.faults``
injector hooks both ends of the frame path for chaos tests.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import struct
import time
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from .. import trace as _trace
from ..resilience import faults as _faults
from ..resilience import policy as _policy
from ..utils.metrics import STAGES as _STAGES

log = logging.getLogger(__name__)

_REQ = 0x01
_REP = 0x02
_REQ2 = 0x03
_REQ3 = 0x04

Handler = Callable[[bytes, str], Awaitable[bytes]]


class RPCError(Exception):
    """Base of the fabric's error taxonomy (also: handler-raised errors
    reflected back over the wire as status-1 replies)."""


class RPCTransportError(RPCError):
    """The connection failed (dial, write, or mid-call loss). The request
    may or may not have executed server-side — only idempotent methods
    auto-retry (``resilience.policy.is_idempotent``)."""


class RPCTimeoutError(RPCTransportError):
    """The per-call timeout or the propagated deadline budget expired."""


class RPCCircuitOpenError(RPCTransportError):
    """Refused pre-send by an OPEN circuit (or an exhausted half-open
    probe budget): the request was never transmitted, so there is ZERO
    execution ambiguity — even non-idempotent calls may safely fail over
    to another endpoint."""


def _len16(b: bytes) -> bytes:
    return struct.pack(">H", len(b)) + b


def _read16(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n = struct.unpack_from(">H", buf, pos)[0]
    pos += 2
    return buf[pos:pos + n], pos + n


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)
    (n,) = struct.unpack(">I", hdr)
    return await reader.readexactly(n)


def _write_frame(writer: asyncio.StreamWriter, body: bytes) -> None:
    writer.write(struct.pack(">I", len(body)) + body)


class _OrderedRunner:
    """Per-order-key FIFO execution (≈ base-util AsyncRunner: a serialized
    async task queue; the reference pins one response pipeline per key)."""

    IDLE_RETIRE_S = 30.0

    def __init__(self) -> None:
        self._queues: Dict[str, asyncio.Queue] = {}
        self._tasks: Dict[str, asyncio.Task] = {}

    def submit(self, key: str, coro_fn) -> None:
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = asyncio.Queue()
            self._tasks[key] = asyncio.create_task(self._drain(key, q))
        q.put_nowait(coro_fn)

    async def _drain(self, key: str, q: asyncio.Queue) -> None:
        while True:
            try:
                coro_fn = await asyncio.wait_for(q.get(),
                                                 timeout=self.IDLE_RETIRE_S)
            except asyncio.TimeoutError:
                # idle: retire ATOMICALLY — deregister FIRST, then re-check
                # the queue. A submit() that raced the wait_for timeout
                # (its enqueue landed between the timeout firing and this
                # block — incl. the pre-3.12 wait_for lost-wakeup window)
                # left the queue non-empty: re-register and keep draining
                # instead of abandoning its item. submit() itself is
                # synchronous on the event loop, so it can never observe
                # the deregistered-but-nonempty intermediate state.
                if self._queues.get(key) is q:
                    del self._queues[key]
                    self._tasks.pop(key, None)
                if q.empty():
                    return
                self._queues[key] = q
                self._tasks[key] = asyncio.current_task()
                continue
            try:
                await coro_fn()
            except Exception:  # noqa: BLE001
                log.exception("ordered task failed (key=%s)", key)

    def close(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._queues.clear()
        self._tasks.clear()


# process-local server table: calls addressed to a server in THIS process
# bypass TCP entirely (≈ the reference's in-proc RPC bypass, where client
# and server stubs short-circuit inside one JVM)
_LOCAL_SERVERS: Dict[str, "RPCServer"] = {}


class RPCServer:
    """One listener hosting many services.

    ``ssl_context`` (server-side) enables TLS on the listener — the
    counterpart of the reference's SSL-capable RPC servers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 ssl_context=None) -> None:
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._services: Dict[str, Dict[str, Handler]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()
        # unordered handler tasks, strongly held server-wide: a bare
        # ensure_future is only weakly referenced (GC could collect it
        # mid-flight, silently dropping the reply). They run to
        # COMPLETION even if their connection dies — wire-path parity
        # with the local bypass's shielded dispatch (a cancelled mutate
        # could be half-applied) — and are cancelled only by stop().
        self._handler_tasks: set = set()
        self._local_runner: Optional[_OrderedRunner] = None

    def register(self, service: str, methods: Dict[str, Handler]) -> None:
        self._services.setdefault(service, {}).update(methods)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host,
                                                  self.port,
                                                  ssl=self.ssl_context)
        self.port = self._server.sockets[0].getsockname()[1]
        _LOCAL_SERVERS[self.address] = self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        _LOCAL_SERVERS.pop(self.address, None)
        if self._server is not None:
            self._server.close()
        if self._local_runner is not None:
            self._local_runner.close()
            self._local_runner = None
        for t in list(self._conn_tasks):
            t.cancel()
        # stop == crash semantics for in-flight handlers (raft/kv
        # invariants must tolerate that anyway); cancelling here keeps
        # them from dying as destroyed-pending tasks at loop teardown
        for t in list(self._handler_tasks):
            t.cancel()

    async def dispatch_local(self, service: str, method: str,
                             payload: bytes, order_key: str) -> bytes:
        """In-proc bypass entry: same semantics as the wire path —
        handler errors surface as RPCError, and calls sharing an
        order_key execute FIFO through the same runner machinery."""
        handler = self._services.get(service, {}).get(method)
        if handler is None:
            raise RPCError("no such method")
        # capture the CALLER's deadline + trace context: the ordered path
        # below runs the handler in the _OrderedRunner drain task, whose
        # context would otherwise silently drop the budget (and trace)
        # the wire path re-arms
        deadline = _policy.current_deadline()
        tctx = _trace.current_ctx()

        async def run() -> bytes:
            try:
                with _policy.absolute_deadline(deadline), \
                        _trace.activate(tctx):
                    return await handler(payload, order_key)
            except Exception as e:  # noqa: BLE001 — wire-path parity
                raise RPCError(repr(e)) from e

        if not order_key:
            return await run()
        if self._local_runner is None:
            self._local_runner = _OrderedRunner()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()

        async def ordered() -> None:
            try:
                res = await run()
                if not fut.done():      # caller may have been cancelled
                    fut.set_result(res)
            except BaseException as e:  # noqa: BLE001
                if not fut.done():
                    fut.set_exception(e)
        self._local_runner.submit(order_key, ordered)
        return await fut

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        runner = _OrderedRunner()
        send_lock = asyncio.Lock()
        try:
            while True:
                body = await _read_frame(reader)
                # hostile/truncated frames (port scanners, bad peers) drop
                # the connection without an unhandled-traceback path
                if not body or body[0] not in (_REQ, _REQ2, _REQ3):
                    if not body:
                        break
                    continue
                try:
                    (rid,) = struct.unpack_from(">Q", body, 1)
                    service_b, pos = _read16(body, 9)
                    method_b, pos = _read16(body, pos)
                    okey_b, pos = _read16(body, pos)
                    deadline = None
                    tctx = None
                    if body[0] in (_REQ2, _REQ3):
                        # deadline header: remaining budget in ms (0 = none)
                        (ms,) = struct.unpack_from(">I", body, pos)
                        pos += 4
                        if ms:
                            deadline = time.monotonic() + ms / 1000.0
                    if body[0] == _REQ3:
                        # trace context (ISSUE 2): decode merges the
                        # sender's HLC stamp into the local clock. A
                        # trace_len overrunning the frame is a malformed
                        # frame — drop the connection like any other
                        # garbled header, never run the handler on a
                        # truncated payload
                        tlen = body[pos]
                        pos += 1
                        if pos + tlen > len(body):
                            break
                        tctx = _trace.extract(body[pos:pos + tlen])
                        pos += tlen
                    service = service_b.decode()
                    method = method_b.decode()
                    okey = okey_b.decode()
                except (struct.error, IndexError, UnicodeDecodeError):
                    break
                payload = body[pos:]
                fault = _faults.get_injector().decide("server", service,
                                                      method)
                if fault is not None:
                    if fault.action == "drop":
                        continue        # request vanishes: caller times out
                    if fault.action == "disconnect":
                        break
                handler = self._services.get(service, {}).get(method)

                async def run(rid=rid, handler=handler, payload=payload,
                              okey=okey, deadline=deadline, fault=fault,
                              tctx=tctx, service=service, method=method):
                    if fault is not None and fault.action == "delay":
                        await asyncio.sleep(fault.delay)
                    if fault is not None and fault.action == "error":
                        status, out = 1, b"injected fault"
                    elif handler is None:
                        status, out = 1, b"no such method"
                    else:
                        try:
                            # re-arm the caller's budget so handler-issued
                            # downstream RPCs inherit the shrunken deadline,
                            # and the caller's trace context so handler
                            # spans join the distributed trace (activate
                            # also CLEARS any context leaked from a prior
                            # request on this connection task)
                            with _policy.absolute_deadline(deadline), \
                                    _trace.activate(tctx), \
                                    _trace.span("rpc.server",
                                                service=service,
                                                method=method):
                                out = await handler(payload, okey)
                            status = 0
                        except Exception as e:  # noqa: BLE001
                            status, out = 1, repr(e).encode()
                    if fault is not None and fault.action == "corrupt":
                        out = _faults.get_injector().corrupt(out)
                    try:
                        async with send_lock:
                            _write_frame(writer, bytes([_REP])
                                         + struct.pack(">Q", rid)
                                         + bytes([status]) + out)
                            await writer.drain()
                    except (ConnectionError, OSError, RuntimeError):
                        # the caller is gone (died/disconnected mid-call):
                        # its reply has nowhere to go — never let a
                        # detached handler task die with an unretrieved
                        # exception over it (RuntimeError: write() on a
                        # transport closed by connection teardown)
                        pass

                if okey:
                    runner.submit(okey, run)
                else:
                    t = asyncio.ensure_future(run())
                    self._handler_tasks.add(t)
                    t.add_done_callback(self._handler_tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            runner.close()
            writer.close()
            self._conn_tasks.discard(task)


class RPCClient:
    """Multiplexed client for one server address; reconnects lazily.
    Calls addressed to a server living in THIS process short-circuit
    through ``dispatch_local`` (no sockets). ``ssl_context`` dials TLS."""

    def __init__(self, host: str, port: int, *, ssl_context=None,
                 local_bypass: bool = True, breaker=None) -> None:
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self.local_bypass = local_bypass
        # optional resilience.breaker.CircuitBreaker fed by wire-path call
        # outcomes (a status-1 handler error is a SUCCESSFUL round trip)
        self.breaker = breaker
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._conn_lock = asyncio.Lock()

    @classmethod
    def from_address(cls, address: str) -> "RPCClient":
        host, port = address.rsplit(":", 1)
        return cls(host, int(port))

    async def _ensure_conn(self) -> asyncio.StreamWriter:
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return self._writer
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port, ssl=self.ssl_context)
            except (ConnectionError, OSError) as e:
                raise RPCTransportError(f"dial {self.host}:{self.port} "
                                        f"failed: {e!r}") from e
            # per-connection pending map: a dead connection's cleanup must
            # only fail ITS calls, never a successor connection's
            self._writer = writer
            self._pending = {}
            self._reader_task = asyncio.create_task(
                self._read_loop(reader, writer, self._pending))
            return writer

    async def _read_loop(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter,
                         pending: Dict[int, asyncio.Future]) -> None:
        try:
            while True:
                body = await _read_frame(reader)
                if not body or body[0] != _REP:
                    if not body:
                        break
                    continue
                (rid,) = struct.unpack_from(">Q", body, 1)
                status = body[9]
                payload = body[10:]
                fut = pending.pop(rid, None)
                if fut is not None and not fut.done():
                    if status == 0:
                        fut.set_result(payload)
                    else:
                        # errors="replace": a corrupted error reply (chaos
                        # injection, hostile peer) must not kill the read
                        # loop with a UnicodeDecodeError
                        fut.set_exception(RPCError(
                            payload.decode(errors="replace")))
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(RPCTransportError("connection lost"))
            pending.clear()
            writer.close()
            if self._writer is writer:
                self._writer = None

    def _effective_timeout(self, timeout: float) -> Tuple[float, bool]:
        """Per-call timeout capped by the propagated deadline budget; an
        exhausted budget fails fast (metered) instead of dispatching.
        Returns (timeout, budget_capped) — when the budget is the binding
        constraint, a resulting timeout says nothing about endpoint
        health and must not feed the breaker."""
        rem = _policy.remaining_budget()
        if rem is None:
            return timeout, False
        if rem <= 0.0:
            from ..utils.metrics import FABRIC, FabricMetric
            FABRIC.inc(FabricMetric.RPC_DEADLINE_EXPIRED)
            raise RPCTimeoutError("deadline budget exhausted")
        return min(timeout, rem), rem < timeout

    async def call(self, service: str, method: str, payload: bytes, *,
                   order_key: str = "", timeout: float = 30.0,
                   trace_tags: Optional[dict] = None) -> bytes:
        """Span-wrapped call (ISSUE 2): every attempt gets an "rpc.attempt"
        span tagged with endpoint + breaker state (``trace_tags`` lets
        ``call_resilient`` stamp attempt/failover counts), and feeds the
        "rpc" stage histogram whether or not the trace is sampled."""
        sp = _trace.span("rpc.attempt", service=service, method=method,
                         endpoint=f"{self.host}:{self.port}",
                         **(trace_tags or {}))
        if self.breaker is not None:
            sp.set_tag("breaker", self.breaker.state)
        t0 = time.perf_counter()
        try:
            with sp:
                return await self._call(service, method, payload,
                                        order_key, timeout)
        finally:
            _STAGES.record("rpc", time.perf_counter() - t0)

    async def _call(self, service: str, method: str, payload: bytes,
                    order_key: str, timeout: float) -> bytes:
        timeout, budget_capped = self._effective_timeout(timeout)
        if self.local_bypass:
            local = _LOCAL_SERVERS.get(f"{self.host}:{self.port}")
            if (local is not None and local._server is not None
                    and local._server.is_serving()):
                # in-proc bypass: no sockets, no codec. The handler runs
                # as a DETACHED task shielded from the client timeout —
                # on the wire path a timed-out call still completes
                # server-side, and the bypass must not diverge (a
                # cancelled mutate could be half-applied)
                task = asyncio.ensure_future(local.dispatch_local(
                    service, method, payload, order_key))
                try:
                    return await asyncio.wait_for(asyncio.shield(task),
                                                  timeout)
                except asyncio.TimeoutError as e:
                    raise RPCTimeoutError(
                        f"{service}/{method} timed out after "
                        f"{timeout:.3f}s (local)") from e
        if self.breaker is not None and not self.breaker.allow():
            # OPEN circuit (or half-open probe budget exhausted): fail fast
            # without dialing — and without recording a new failure, a
            # refused admission is not a fresh outcome. The distinct type
            # tells retrying callers the request was NEVER sent (safe to
            # fail over even for non-idempotent methods).
            raise RPCCircuitOpenError(
                f"circuit open for {self.host}:{self.port}")
        fault = _faults.get_injector().decide("client", service, method)
        if fault is not None and fault.action == "error":
            self._record(False, "injected fault")
            raise RPCTransportError("injected fault")
        try:
            out = await self._call_wire(service, method, payload,
                                        order_key, timeout, fault)
        except RPCTimeoutError as e:
            # a timeout whose clock was the CALLER's nearly-spent budget
            # says nothing about endpoint health: release the admission
            # without a verdict instead of tripping a healthy breaker
            if budget_capped:
                if self.breaker is not None:
                    self.breaker.release_probe()
            else:
                self._record(False, repr(e))
            raise
        except RPCTransportError as e:
            # breaker food: transport failures only
            self._record(False, repr(e))
            raise
        except RPCError:
            # a reflected handler error is a SUCCESSFUL round trip — the
            # endpoint is alive. Recording success here also releases a
            # HALF_OPEN probe slot (a handler-error probe must close the
            # circuit, not strand it half-open forever)
            self._record(True)
            raise
        except BaseException:
            # cancellation (or any non-RPC failure) mid-call: no verdict
            # on endpoint health, but a charged HALF_OPEN probe slot must
            # be returned or the breaker wedges half-open forever
            if self.breaker is not None:
                self.breaker.release_probe()
            raise
        self._record(True)
        return out

    def _record(self, ok: bool, error: Optional[str] = None) -> None:
        if self.breaker is not None:
            if ok:
                self.breaker.record_success()
            else:
                self.breaker.record_failure(error)

    async def _call_wire(self, service: str, method: str, payload: bytes,
                         order_key: str, timeout: float, fault) -> bytes:
        writer = await self._ensure_conn()
        if fault is not None:
            if fault.action == "delay":
                # injected latency counts AGAINST the per-call timeout,
                # exactly like real network delay would
                await asyncio.sleep(fault.delay)
                timeout -= fault.delay
                if timeout <= 0:
                    raise RPCTimeoutError(
                        f"{service}/{method} timed out under injected "
                        f"{fault.delay:.3f}s delay")
            elif fault.action == "corrupt":
                payload = _faults.get_injector().corrupt(payload)
            elif fault.action == "disconnect":
                writer.close()
                raise RPCTransportError("injected disconnect")
        pending = self._pending
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        pending[rid] = fut
        rem = _policy.remaining_budget()
        tblob = _trace.inject()
        hdr = (struct.pack(">Q", rid) + _len16(service.encode())
               + _len16(method.encode()) + _len16(order_key.encode()))
        if tblob is not None:
            # request3: deadline budget (0 = none) + trace context, so the
            # server joins the distributed trace in causal HLC order
            body = (bytes([_REQ3]) + hdr
                    + struct.pack(">I", 0 if rem is None
                                  else max(1, int(rem * 1000)))
                    + bytes([len(tblob)]) + tblob + payload)
        elif rem is not None:
            # request2: stamp the remaining budget so the server (and its
            # downstream calls) inherit the shrunken deadline
            body = (bytes([_REQ2]) + hdr
                    + struct.pack(">I", max(1, int(rem * 1000)))
                    + payload)
        else:
            body = bytes([_REQ]) + hdr + payload
        if fault is not None and fault.action == "drop":
            # the request frame vanishes on the wire: the reply future can
            # only time out (exactly what a blackholed network does)
            pass
        else:
            try:
                _write_frame(writer, body)
                await writer.drain()
            except (ConnectionError, OSError) as e:
                pending.pop(rid, None)
                raise RPCTransportError(f"send failed: {e!r}") from e
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError as e:
            raise RPCTimeoutError(f"{service}/{method} timed out after "
                                  f"{timeout:.3f}s") from e
        finally:
            # a timed-out call must not leak its correlation entry
            pending.pop(rid, None)

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class ServiceRegistry:
    """Service discovery (traffic governor analog, three backends):

    - **CRDT** (the reference way, RPCServiceTrafficService.java:30): each
      server announces ``(service → address)`` into a replicated ORMap
      ("traffic" uri) on a CRDTStore; anti-entropy spreads it.
    - **gossip agents**: announce into agent ``rpc:<service>`` metadata.
    - **static**: explicit addresses (tests / config files).

    Clients rendezvous-hash a tenant key over the union of live endpoints
    (HRWRouter semantics)."""

    TRAFFIC_URI = "traffic"
    DIRECTIVE_URI = "traffic-directive"

    def __init__(self, agent_host=None, crdt_store=None, *,
                 local_bypass: bool = True,
                 client_ssl_context=None, breakers=None) -> None:
        from ..resilience.breaker import BreakerRegistry
        self.agent_host = agent_host
        self.crdt_store = crdt_store
        self.local_bypass = local_bypass        # in-proc short-circuit
        self.client_ssl_context = client_ssl_context  # TLS dialing
        # per-endpoint circuit breakers: pick() routes around open
        # circuits; clients created here feed them with call outcomes
        self.breakers = (breakers if breakers is not None
                         else BreakerRegistry())
        # live breaker state shows up in the /metrics "fabric" section
        # (weakly held — a test-scoped registry dies with its owner)
        from ..utils.metrics import FABRIC as _FABRIC
        _FABRIC.register_breakers(self.breakers)
        # gossiped remote health (ISSUE 5): an object with
        # ``suspect(endpoint) -> bool`` (obs.clusterview.ClusterView) —
        # pick() demotes endpoints the CLUSTER says are unhealthy (a
        # peer's open breaker, a self-reported deep dispatch queue)
        # before any local failure is observed
        self.remote_health = None
        self._static: Dict[str, List[str]] = {}
        self._clients: Dict[str, RPCClient] = {}
        # traffic governor state (≈ IRPCServiceTrafficGovernor.java:29):
        # address -> server-group tag, and per-service tenant-prefix
        # directives mapping group -> weight
        self._groups: Dict[str, str] = {}
        self._directives: Dict[str, Dict[str, Dict[str, int]]] = {}

    # -- server side --------------------------------------------------------

    def announce(self, service: str, address: str,
                 group: str = "") -> None:
        """Announce an endpoint, optionally tagged with a server GROUP
        (the traffic governor's unit of weighted tenant assignment)."""
        element = f"{address}|{group}" if group else address
        if self.crdt_store is not None:
            self.crdt_store.set_add(self.TRAFFIC_URI, service, element)
        if self.agent_host is not None:
            self.agent_host.host_agent(f"rpc:{service}",
                                       {"address": address,
                                        "group": group})
        self._static.setdefault(service, []).append(address)
        if group:
            self._groups[address] = group

    # -- traffic directives (≈ setTrafficDirective) -------------------------

    def set_traffic_directive(self, service: str, tenant_prefix: str,
                              group_weights: Dict[str, int]) -> None:
        """Route tenants matching ``tenant_prefix`` across server groups
        by weight (weight 0 = drain). The LONGEST matching prefix wins;
        tenants matching no directive spread over all endpoints."""
        self._directives.setdefault(service, {})[tenant_prefix] = \
            dict(group_weights)
        getattr(self, "_directive_cache", {}).pop(service, None)
        if self.crdt_store is not None:
            import json as _json
            key = f"{service}/{tenant_prefix}"
            for el in self.crdt_store.elements(self.DIRECTIVE_URI, key):
                self.crdt_store.set_remove(self.DIRECTIVE_URI, key, el)
            self.crdt_store.set_add(self.DIRECTIVE_URI, key,
                                    _json.dumps(group_weights,
                                                sort_keys=True))

    def traffic_directives(self) -> Dict[str, Dict[str, Dict[str, int]]]:
        """Snapshot of service → tenant_prefix → {group: weight} (the
        apiserver's GET /traffic introspection)."""
        return {svc: {pfx: dict(gw) for pfx, gw in rules.items()}
                for svc, rules in self._directives.items()}

    def unset_traffic_directive(self, service: str,
                                tenant_prefix: str) -> None:
        self._directives.get(service, {}).pop(tenant_prefix, None)
        getattr(self, "_directive_cache", {}).pop(service, None)
        if self.crdt_store is not None:
            self.crdt_store.remove_key(self.DIRECTIVE_URI,
                                       f"{service}/{tenant_prefix}")

    _DIRECTIVE_CACHE_TTL = 1.0

    def _directive_for(self, service: str,
                       key: str) -> Optional[Dict[str, int]]:
        import time as _time
        cached = getattr(self, "_directive_cache", None)
        if cached is None:
            cached = self._directive_cache = {}
        hit = cached.get(service)
        if hit is not None and hit[0] > _time.monotonic():
            directives = hit[1]
        else:
            directives = dict(self._directives.get(service, {}))
            if self.crdt_store is not None:
                import json as _json
                prefix = f"{service}/"
                for k in self.crdt_store.keys(self.DIRECTIVE_URI):
                    if k.startswith(prefix):
                        for el in self.crdt_store.elements(
                                self.DIRECTIVE_URI, k):
                            try:
                                directives.setdefault(k[len(prefix):],
                                                      _json.loads(el))
                            except ValueError:
                                continue
            # bounded staleness beats O(directives) JSON parsing on every
            # routed message (pick() is the per-request hot path)
            cached[service] = (_time.monotonic()
                               + self._DIRECTIVE_CACHE_TTL, directives)
        best = None
        for pfx in directives:
            if key.startswith(pfx) and (best is None
                                        or len(pfx) > len(best)):
                best = pfx
        return directives[best] if best is not None else None

    def withdraw(self, service: str, address: str) -> None:
        if self.crdt_store is not None:
            # grouped endpoints are stored as "address|group": remove every
            # element whose address part matches
            for el in list(self.crdt_store.elements(self.TRAFFIC_URI,
                                                    service)):
                if el == address or el.startswith(address + "|"):
                    self.crdt_store.set_remove(self.TRAFFIC_URI, service,
                                               el)
        if self.agent_host is not None:
            self.agent_host.stop_agent(f"rpc:{service}")
        if address in self._static.get(service, []):
            self._static[service].remove(address)

    # -- client side --------------------------------------------------------

    def endpoints(self, service: str) -> List[str]:
        out = []
        if self.crdt_store is not None:
            for el in self.crdt_store.elements(self.TRAFFIC_URI, service):
                addr, _, group = el.partition("|")
                if group:
                    self._groups[addr] = group
                if addr not in out:
                    out.append(addr)
        if self.agent_host is not None:
            for _node, meta in self.agent_host.agent_members(
                    f"rpc:{service}").items():
                addr = (meta or {}).get("address")
                if addr and addr not in out:
                    out.append(addr)
                    if (meta or {}).get("group"):
                        self._groups[addr] = meta["group"]
        for addr in self._static.get(service, []):
            if addr not in out:
                out.append(addr)
        return sorted(out)

    def pick(self, service: str, key: str,
             exclude: Optional[set] = None) -> Optional[str]:
        """Weighted rendezvous hash (≈ HRWRouter with traffic-governor
        directives): the longest tenant-prefix directive scales each
        endpoint's score by its group weight; weight-0 groups drain.

        Endpoints whose circuit breaker is OPEN are skipped, so the hash
        falls over to the next-ranked live server (ISSUE 1 failover);
        ``exclude`` additionally masks endpoints a retrying caller already
        failed against THIS call. Candidate tiers degrade gracefully:
        (1) locally available AND clear of gossiped remote health flags
        (ISSUE 5: a peer's open breaker or a node's self-reported deep
        dispatch queue demotes it here, before any local failure),
        (2) breaker-available and not excluded, (3) breaker-available —
        a retry that has failed against EVERY endpoint must prefer a
        live-looking one over a known-open circuit, (4) everything
        (total outage stays no worse than before breakers existed)."""
        eps = self.endpoints(service)
        if not eps:
            return None
        available = [ep for ep in eps if self.breakers.available(ep)]
        healthy = available
        rh = self.remote_health
        if rh is not None:
            try:
                healthy = [ep for ep in available if not rh.suspect(ep)]
            except Exception:  # noqa: BLE001 — advisory only: routing
                healthy = available  # must survive a telemetry bug
        if exclude:
            tier1 = [ep for ep in healthy if ep not in exclude]
            tier2 = [ep for ep in available if ep not in exclude]
        else:
            tier1, tier2 = healthy, available
        eps = tier1 or tier2 or available or eps
        directive = self._directive_for(service, key)
        if directive is not None:
            weighted = [ep for ep in eps
                        if directive.get(self._groups.get(ep, ""), 0) > 0]
            if weighted:
                def wscore(ep: str) -> float:
                    w = directive.get(self._groups.get(ep, ""), 0)
                    h = hashlib.blake2b(f"{ep}|{key}".encode(),
                                        digest_size=8).digest()
                    # weighted rendezvous: u^(1/w) ordering via -w/ln(u).
                    # Map the top 52 hash bits into (0,1) EXCLUSIVE with
                    # representable float endpoints — a u that rounds to
                    # exactly 0.0 or 1.0 would crash log for that
                    # (endpoint, tenant) pair deterministically forever
                    import math
                    u = ((int.from_bytes(h, "big") >> 12) + 1) \
                        / float((1 << 52) + 2)
                    return -w / math.log(u)
                return max(weighted, key=wscore)

        def score(ep: str) -> int:
            h = hashlib.blake2b(f"{ep}|{key}".encode(),
                                digest_size=8).digest()
            return int.from_bytes(h, "big")
        return max(eps, key=score)

    def client_for(self, addr: str) -> RPCClient:
        c = self._clients.get(addr)
        if c is None:
            host, port = addr.rsplit(":", 1)
            c = self._clients[addr] = RPCClient(
                host, int(port), ssl_context=self.client_ssl_context,
                local_bypass=self.local_bypass,
                breaker=self.breakers.for_endpoint(addr))
        return c

    async def call_resilient(self, service: str, key: str, method: str,
                             payload: bytes, *, order_key: str = "",
                             timeout: float = 30.0, policy=None,
                             idempotent: Optional[bool] = None,
                             rng=None) -> bytes:
        """Pick → call with retry + endpoint failover (the fabric's
        bounded-work-then-fallback discipline, ISSUE 1 tentpole).

        Each attempt rendezvous-picks over the live (breaker-closed)
        endpoint set, excluding endpoints that already failed THIS call;
        transport failures on idempotent methods back off (exponential +
        full jitter) and fail over; non-idempotent methods fail fast —
        the request may have executed server-side and the caller owns
        that ambiguity. Handler errors (plain RPCError) never retry: the
        server answered. Retries/failovers are metered."""
        from ..resilience.policy import (DEFAULT_RETRY_POLICY,
                                         is_idempotent)
        from ..utils.metrics import FABRIC, FabricMetric
        if policy is None:
            policy = DEFAULT_RETRY_POLICY
        if idempotent is None:
            idempotent = is_idempotent(service, method)
        tried_and_failed: set = set()
        attempt = 0
        last_failed: Optional[str] = None
        while True:
            attempt += 1
            addr = self.pick(service, key, exclude=tried_and_failed)
            if addr is None:
                raise RPCTransportError(
                    f"no endpoints for service {service}")
            failed_over = last_failed is not None and addr != last_failed
            if failed_over:
                FABRIC.inc(FabricMetric.RPC_FAILOVERS)
            try:
                return await self.client_for(addr).call(
                    service, method, payload, order_key=order_key,
                    timeout=timeout,
                    trace_tags={"attempt": attempt,
                                "failed_over": failed_over})
            except RPCTransportError as e:
                tried_and_failed.add(addr)
                last_failed = addr
                # a circuit-open refusal was NEVER sent: zero execution
                # ambiguity, so even non-idempotent methods fail over
                retryable = (idempotent
                             or isinstance(e, RPCCircuitOpenError))
                if not retryable or not policy.should_retry(attempt):
                    raise
                FABRIC.inc(FabricMetric.RPC_RETRIES)
                log.debug("retrying %s/%s after %r (attempt %d)",
                          service, method, e, attempt)
                await asyncio.sleep(policy.backoff(attempt, rng))

    async def close(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
