from .fabric import (RPCClient, RPCError, RPCServer,  # noqa: F401
                     ServiceRegistry)
