from .fabric import (RPCCircuitOpenError, RPCClient,  # noqa: F401
                     RPCError, RPCServer, RPCTimeoutError,
                     RPCTransportError, ServiceRegistry)
