"""Standalone KV store process.

≈ the reference's standalone store server deployment (base-kv-store-server
hosted inside a bifromq-starter process): one ``KVRangeStore`` + raft
``StoreMessenger`` + RPC facade, addressed by static peer configuration.

    python -m bifromq_tpu.kv.store_main --node s1 --port 7001 \
        --peers s1=127.0.0.1:7001,s2=127.0.0.1:7002,s3=127.0.0.1:7003 \
        [--coproc echo|dist] [--data-dir /path]

Prints ``READY <port>`` on stdout once serving. With ``--data-dir`` the
store and raft state are durable (native C++ engine) and a restarted
process resumes from its WAL; without it a restart rejoins empty and
catches up via the leader's snapshot dump session.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from ..utils.jaxenv import pin_jax_platform

pin_jax_platform()


def _coproc_factory(kind: str):
    if kind == "dist":
        from ..dist.worker import DistWorkerCoProc

        def make(range_id: str):
            return DistWorkerCoProc()
        return make

    if kind == "inbox":
        from ..inbox.coproc import InboxStoreCoProc
        from ..plugin.events import IEventCollector

        class _NoEvents(IEventCollector):
            def report(self, event):
                pass
        return lambda range_id: InboxStoreCoProc(_NoEvents())

    if kind == "retain":
        from ..retain.coproc import RetainCoProc
        return lambda range_id: RetainCoProc()

    from .range import IKVRangeCoProc

    class _EchoCoProc(IKVRangeCoProc):
        boundary = (b"", None)

        def query(self, input_data, reader):
            return reader.get(input_data) or b""

        def mutate(self, input_data, reader, writer):
            k, v = input_data.split(b"=", 1)
            writer.put(k, v)
            return b"ok:" + k

        def reset(self, reader):
            pass

    return lambda range_id: _EchoCoProc()


async def amain(args) -> None:
    from ..rpc.fabric import RPCServer, ServiceRegistry
    from .engine import InMemKVEngine
    from .messenger import StoreMessenger
    from .meta import BaseKVStoreServer, MetaService
    from .store import KVRangeStore

    peers = dict(p.split("=", 1) for p in args.peers.split(",") if p)
    registry = ServiceRegistry()
    meta = MetaService()
    messenger = StoreMessenger(args.node, registry)
    for node, addr in peers.items():
        registry.announce(f"{messenger.service}:{node}", addr)

    if args.data_dir:
        from .native import NativeKVEngine
        from ..raft.store import KVRaftStateStore
        engine = NativeKVEngine(args.data_dir)
        raft_store_factory = (
            lambda rid: KVRaftStateStore(
                engine.create_space(f"raft_{rid}")))
    else:
        engine = InMemKVEngine()
        raft_store_factory = None

    store = KVRangeStore(args.node, messenger, engine,
                         _coproc_factory(args.coproc),
                         member_nodes=sorted(peers),
                         raft_store_factory=raft_store_factory)
    store.open()
    server = BaseKVStoreServer(store, messenger,
                               RPCServer(port=args.port), registry, meta,
                               tick_interval=args.tick_interval)
    await server.start()
    print(f"READY {server.server.port}", flush=True)
    await asyncio.Event().wait()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--peers", required=True,
                    help="node=host:port,... (must include --node)")
    ap.add_argument("--coproc", default="echo",
                    choices=["echo", "dist", "inbox", "retain"])
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--tick-interval", type=float, default=0.02)
    args = ap.parse_args(argv)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())
