"""Per-range metrics manager (≈ base-kv-store-server KVRangeMetricManager
+ LoadRecordableKVReader surfacing): answers "which range is hot and
why" — VERDICT-r2 weak #8 ("observability can't explain hot ranges").

``range_stats(store)`` snapshots every hosted range: boundary, key count,
raft health (role/term/commit/apply lag), and the load profile the split
hinters feed on (windowed rate + the current load-median key). The
balancers read the same recorders; this module is the operator's view of
the same signal, exported through the API server (GET /ranges) and the
store RPC facade ("range_stats").
"""

from __future__ import annotations

from typing import List, Optional

from .store import KVRangeStore


def range_stats(store: KVRangeStore) -> List[dict]:
    out = []
    for rid, r in sorted(store.ranges.items()):
        start, end = store.boundaries[rid]
        raft = r.raft
        coproc = store.coprocs.get(rid)
        rec = getattr(coproc, "load_recorder", None)
        load: Optional[dict] = None
        if rec is not None:
            age, total = rec.window()
            hot = rec.hot_split_key()
            load = {
                "window_seconds": round(age, 3),
                "total_cost": total,
                "rate_per_second": round(rec.load_per_second(), 1),
                "tracked_keys": len(rec._samples),
                "dropped_cost": rec.dropped,
                "hot_split_key": hot.hex() if hot else None,
            }
        out.append({
            "id": rid,
            "start": start.hex(),
            "end": end.hex() if end is not None else None,
            "keys": len(r.space),
            "role": raft.role.value,
            "leader": raft.leader_id,
            "term": raft.term,
            "commit_index": raft.commit_index,
            "last_applied": raft.last_applied,
            "apply_lag": raft.commit_index - raft.last_applied,
            "log_size": len(raft.log),
            "voters": sorted(raft.voters),
            "sealed": r.sealed,
            "load": load,
        })
    return out
