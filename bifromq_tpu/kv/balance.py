"""Balance controller + balancers (≈ base-kv-store-balance-controller).

``KVStoreBalanceController`` periodically evaluates pluggable balancers
against its own store and executes the commands they emit — the
decentralized placement loop of KVStoreBalanceController.java:85
(balance():303). First balancer: ``RangeSplitBalancer``
(≈ balance/impl/RangeSplitBalancer.java fed by split hinters): splits any
leader range whose keyspace outgrew ``max_keys`` at its median key, which
keeps the per-range compiled automatons bounded — the TPU analog of
keeping per-range scan cost flat (FanoutSplitHinter's goal).
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from .store import KVRangeStore

log = logging.getLogger(__name__)


class SplitCommand:
    def __init__(self, range_id: str, split_key: bytes) -> None:
        self.range_id = range_id
        self.split_key = split_key

    def __repr__(self) -> str:
        return f"Split({self.range_id} @ {self.split_key!r})"


class RangeSplitBalancer:
    """Emit a split for any local leader range with more than ``max_keys``
    keys, at the median key (a size hinter; fan-out hinters can feed the
    same command stream)."""

    def __init__(self, max_keys: int = 100_000) -> None:
        self.max_keys = max_keys

    def balance(self, store: KVRangeStore) -> List[SplitCommand]:
        out: List[SplitCommand] = []
        for rid, r in store.ranges.items():
            if not r.is_leader:
                continue
            n = len(r.space)
            if n <= self.max_keys:
                continue
            start, end = store.boundaries[rid]
            mid = self._median_key(r.space, start, end, n)
            # coprocs with multi-key record groups (e.g. one inbox's
            # meta + queues) snap the split onto a group boundary
            align = getattr(store.coprocs.get(rid), "align_split_key", None)
            if mid is not None and align is not None:
                mid = align(mid)
            if mid is not None and mid > start \
                    and (end is None or mid < end):
                out.append(SplitCommand(rid, mid))
        return out

    @staticmethod
    def _median_key(space, start: bytes, end, n: int) -> Optional[bytes]:
        target = n // 2
        for i, (k, _v) in enumerate(space.iterate(start, end)):
            if i >= target:
                return k
        return None


class MergeCommand:
    def __init__(self, left_id: str, right_id: str) -> None:
        self.left_id = left_id
        self.right_id = right_id

    def __repr__(self) -> str:
        return f"Merge({self.left_id} <- {self.right_id})"


class RangeMergeBalancer:
    """Merge adjacent under-filled leader ranges (the shrink half of
    elasticity): when two neighbors together hold fewer than ``min_keys``
    keys, fold the right one into the left (≈ the reference's merge
    balancing driven from range load facts)."""

    def __init__(self, min_keys: int = 1000) -> None:
        self.min_keys = min_keys

    def balance(self, store: KVRangeStore) -> List["MergeCommand"]:
        ordered = store.router.ranges()  # boundary-sorted
        for ((_s1, e1), left), ((s2, _e2), right) in zip(ordered,
                                                         ordered[1:]):
            if e1 != s2:
                continue
            lr, rr = store.ranges[left], store.ranges[right]
            if not (lr.is_leader and rr.is_leader):
                continue
            if len(lr.space) + len(rr.space) < self.min_keys:
                return [MergeCommand(left, right)]  # one merge per round
        return []


class KVStoreBalanceController:
    """Runs the balancer set on an interval against one store."""

    def __init__(self, store: KVRangeStore, balancers=None, *,
                 interval: float = 1.0) -> None:
        self.store = store
        self.balancers = balancers or [RangeSplitBalancer()]
        self.interval = interval
        self._task = None
        # admin toggle + last-commands ring (≈ the reference apiserver's
        # balancer enable/disable/state endpoints over
        # KVStoreBalanceController)
        self.enabled = True
        self.history: list = []

    def state(self) -> dict:
        return {
            "enabled": self.enabled,
            "interval_s": self.interval,
            "balancers": [type(b).__name__ for b in self.balancers],
            "recent_commands": list(self.history[-20:]),
        }

    async def run_once(self) -> int:
        if not self.enabled:
            return 0
        executed = 0
        for b in self.balancers:
            for cmd in b.balance(self.store):
                try:
                    if isinstance(cmd, SplitCommand):
                        sib = await self.store.split(cmd.range_id,
                                                     cmd.split_key)
                        log.info("split %s -> %s", cmd.range_id, sib)
                        self.history.append(
                            {"cmd": "split", "range": cmd.range_id})
                        executed += 1
                    elif isinstance(cmd, MergeCommand):
                        await self.store.merge(cmd.left_id, cmd.right_id)
                        log.info("merged %s <- %s", cmd.left_id,
                                 cmd.right_id)
                        self.history.append(
                            {"cmd": "merge", "left": cmd.left_id,
                             "right": cmd.right_id})
                        executed += 1
                except Exception:  # noqa: BLE001 — keep balancing others
                    log.exception("balance command failed: %r", cmd)
        del self.history[:-100]
        return executed

    async def start(self) -> None:
        async def loop():
            while True:
                await asyncio.sleep(self.interval)
                await self.run_once()
        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except BaseException:  # noqa: BLE001
                pass
            self._task = None
