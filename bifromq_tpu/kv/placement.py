"""Replica placement balancers + cluster placement controller.

≈ base-kv-store-balance-controller's FULL placement balancer set
(impl/ReplicaCntBalancer.java:51, RangeLeaderBalancer.java,
UnreachableReplicaRemovalBalancer.java, RangeBootstrapBalancer.java:52,
RedundantRangeRemovalBalancer.java, RuleBasedPlacementBalancer.java:30 —
the last fed by operator rule documents like the reference's LoadRules
admin API) re-expressed over this repo's landscape (kv/meta.py) instead
of CRDT store descriptors.

Decentralized like the reference: every store runs the controller against
its own view, but a balancer only emits commands for ranges whose LEADER
replica is local — one decision-maker per range at any moment. Commands:

- ``EnsureReplicaCommand``: open a replica shell on a target store (RPC),
  then grow the range's voter config to include it; raft catch-up (append
  backfill or snapshot dump session) does the data motion.
- ``ConfigChangeCommand``: shrink/grow the voter set via joint consensus;
  replicas excluded by the committed config zombie-quit on their own store
  (kv/store.py tick).
- ``TransferLeaderCommand``: move leadership to spread leaders per store.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Callable, Dict, List, Optional, Set

from .store import KVRangeStore

log = logging.getLogger(__name__)


def _node_of(member_id: str) -> str:
    return member_id.split(":", 1)[0]


def _voter_nodes(raft) -> Set[str]:
    return {_node_of(v) for v in raft.voters}


class EnsureReplicaCommand:
    def __init__(self, store_id: str, range_id: str, boundary,
                 voter_nodes: List[str],
                 learner_nodes: Optional[List[str]] = None) -> None:
        self.store_id = store_id
        self.range_id = range_id
        self.boundary = boundary
        self.voter_nodes = voter_nodes
        self.learner_nodes = list(learner_nodes or [])

    def __repr__(self) -> str:
        return f"EnsureReplica({self.range_id} on {self.store_id})"


class ConfigChangeCommand:
    def __init__(self, range_id: str, voter_nodes: List[str],
                 learner_nodes: Optional[List[str]] = None) -> None:
        self.range_id = range_id
        self.voter_nodes = voter_nodes
        self.learner_nodes = learner_nodes   # None = keep current

    def __repr__(self) -> str:
        return f"ConfigChange({self.range_id} -> {self.voter_nodes})"


class TransferLeaderCommand:
    def __init__(self, range_id: str, target_node: str) -> None:
        self.range_id = range_id
        self.target_node = target_node

    def __repr__(self) -> str:
        return f"TransferLeader({self.range_id} -> {self.target_node})"


class BootstrapCommand:
    """Create the genesis full-boundary range on this store
    (≈ balance/command/BootstrapCommand.java)."""

    def __init__(self, range_id: str) -> None:
        self.range_id = range_id

    def __repr__(self) -> str:
        return f"Bootstrap({self.range_id})"


class QuitCommand:
    """Retire a local (conflicting) replica
    (≈ balance/command/QuitCommand.java)."""

    def __init__(self, range_id: str) -> None:
        self.range_id = range_id

    def __repr__(self) -> str:
        return f"Quit({self.range_id})"


class ReplicaCntBalancer:
    """Keep every local-leader range at ``target`` voters
    (≈ ReplicaCntBalancer.java:51): under-replicated ranges grow onto
    rendezvous-picked live stores (EnsureReplica + ConfigChange); over-
    replicated ranges shed a non-leader voter, preferring dead stores."""

    def __init__(self, target: int = 3) -> None:
        self.target = target

    def balance(self, store: KVRangeStore, alive: Set[str]) -> List:
        out: List = []
        for rid, r in store.ranges.items():
            if not r.is_leader or r.raft.voters_old is not None:
                continue    # no stacking on an in-flight change
            nodes = _voter_nodes(r.raft)
            learner_nodes = {_node_of(m) for m in r.raft.learners}
            if len(nodes) + len(learner_nodes) < self.target:
                candidates = sorted(alive - nodes - learner_nodes)
                if not candidates:
                    continue

                def score(n: str, rid=rid) -> int:
                    h = hashlib.blake2b(f"{n}|{rid}".encode(),
                                        digest_size=8).digest()
                    return int.from_bytes(h, "big")
                new_node = max(candidates, key=score)
                # stage as LEARNER: the shell catches up via appends or a
                # dump session WITHOUT weakening quorum; the promotion
                # balancer flips it to voter once caught up (the
                # reference's learner->voter placement flow)
                new_learners = sorted(learner_nodes | {new_node})
                out.append(EnsureReplicaCommand(
                    new_node, rid, store.boundaries[rid], sorted(nodes),
                    new_learners))
                out.append(ConfigChangeCommand(rid, sorted(nodes),
                                               new_learners))
            elif len(nodes) > self.target:
                dead = sorted(nodes - alive - {store.node_id})
                live_followers = sorted(nodes & alive - {store.node_id})
                victim = (dead or live_followers or [None])[0]
                if victim is not None:
                    out.append(ConfigChangeCommand(
                        rid, sorted(nodes - {victim})))
        return out


class UnreachableReplicaRemovalBalancer:
    """Drop voters whose store has been out of the live set for
    ``miss_rounds`` consecutive balance rounds
    (≈ UnreachableReplicaRemovalBalancer): only when the surviving set
    still forms a quorum of the current config — a majority loss is
    recover()'s job, not an automatic one."""

    def __init__(self, miss_rounds: int = 3) -> None:
        self.miss_rounds = miss_rounds
        self._misses: Dict[str, int] = {}   # "rid/node" -> rounds missing

    def balance(self, store: KVRangeStore, alive: Set[str]) -> List:
        out: List = []
        seen = set()
        for rid, r in store.ranges.items():
            if not r.is_leader or r.raft.voters_old is not None:
                continue
            nodes = _voter_nodes(r.raft)
            live = nodes & alive | {store.node_id}
            if len(live) * 2 <= len(nodes):
                continue    # majority gone: recover territory
            learner_nodes = {_node_of(m) for m in r.raft.learners}
            removed_this_range = False
            for node in sorted(nodes - alive - {store.node_id}):
                key = f"{rid}/{node}"
                seen.add(key)
                n = self._misses.get(key, 0) + 1
                self._misses[key] = n
                if n >= self.miss_rounds:
                    out.append(ConfigChangeCommand(
                        rid, sorted(nodes - {node})))
                    removed_this_range = True
                    break   # one removal per range per round
            if removed_this_range:
                continue
            for node in sorted(learner_nodes - alive):
                # a dead LEARNER wedges re-replication (it counts toward
                # the target but can never promote); dropping it never
                # touches quorum, so prune on the same miss schedule
                key = f"{rid}/L/{node}"
                seen.add(key)
                n = self._misses.get(key, 0) + 1
                self._misses[key] = n
                if n >= self.miss_rounds:
                    out.append(ConfigChangeCommand(
                        rid, sorted(nodes),
                        sorted(learner_nodes - {node})))
                    break
        for key in list(self._misses):
            if key not in seen:
                del self._misses[key]
        return out


class LearnerPromotionBalancer:
    """Promote caught-up learners to voters (the second half of the
    learner->voter placement flow): a learner whose match index reached
    the leader's commit gets a one-voter-delta config change."""

    LAG_SLACK = 4   # entries a learner may trail and still promote

    def balance(self, store: KVRangeStore, alive: Set[str]) -> List:
        out: List = []
        for rid, r in store.ranges.items():
            raft = r.raft
            if not r.is_leader or raft.voters_old is not None \
                    or not raft.learners:
                continue
            for member in sorted(raft.learners):
                if _node_of(member) not in alive:
                    continue    # never promote a dead learner to voter
                match = raft._match_index.get(member, 0)
                if match and match >= raft.commit_index - self.LAG_SLACK:
                    nodes = _voter_nodes(raft)
                    learner_nodes = {_node_of(m) for m in raft.learners}
                    promoted = _node_of(member)
                    out.append(ConfigChangeCommand(
                        rid, sorted(nodes | {promoted}),
                        sorted(learner_nodes - {promoted})))
                    break   # one promotion per range per round
        return out


class RangeLeaderBalancer:
    """Spread range leadership across stores
    (≈ RangeLeaderBalancer.java): when this store leads ≥2 more ranges
    than the least-loaded voter store in the landscape, hand one over."""

    def balance(self, store: KVRangeStore, alive: Set[str],
                leader_counts: Dict[str, int]) -> List:
        my_leads = [rid for rid, r in store.ranges.items()
                    if r.is_leader and r.raft.voters_old is None]
        mine = len(my_leads)
        for rid in sorted(my_leads):
            r = store.ranges[rid]
            followers = sorted((_voter_nodes(r.raft) & alive)
                               - {store.node_id})
            if not followers:
                continue
            target = min(followers,
                         key=lambda n: (leader_counts.get(n, 0), n))
            if mine - leader_counts.get(target, 0) >= 2:
                return [TransferLeaderCommand(rid, target)]
        return []


class RangeBootstrapBalancer:
    """Create the first full-boundary range when a store group comes up
    empty (≈ RangeBootstrapBalancer.java:52: bootstrap-as-a-balancer-
    decision, replacing manual ensure_range bootstrap).

    The reference races randomized suspicion timers and lets
    RedundantRangeRemovalBalancer clean up a double bootstrap; here the
    decision is deterministic — only the smallest-id alive store
    bootstraps — so a conflict cannot arise in a connected landscape. The
    debounce (``wait_rounds``) covers slow landscape convergence at cold
    start, like the reference's suspicion window."""

    def __init__(self, wait_rounds: int = 10) -> None:
        self.wait_rounds = wait_rounds
        self._rounds_empty = 0

    def balance(self, store: KVRangeStore, alive: Set[str],
                landscape: Dict[str, dict]) -> List:
        if store.ranges or any(d.get("ranges")
                               for d in landscape.values()):
            self._rounds_empty = 0
            return []
        if alive and store.node_id != min(alive):
            return []
        self._rounds_empty += 1
        if self._rounds_empty < self.wait_rounds:
            return []
        self._rounds_empty = 0
        return [BootstrapCommand("r0")]


class RedundantRangeRemovalBalancer:
    """Retire local leader ranges whose boundary overlaps another leader
    range in the landscape (≈ RedundantRangeRemovalBalancer.java's
    boundary/id-conflict cleanup; config-excluded replicas are handled by
    the store's zombie-quit instead). Deterministic survivor rule: among
    conflicting leader ranges, the lexicographically smallest range id
    wins; the local leader of any other conflicting range quits after
    ``wait_rounds`` consecutive observations (debounce against stale
    landscape views)."""

    def __init__(self, wait_rounds: int = 5) -> None:
        self.wait_rounds = wait_rounds
        self._pending: Dict[str, int] = {}   # rid -> consecutive rounds

    @staticmethod
    def _overlaps(a_start: bytes, a_end, b_start: bytes, b_end) -> bool:
        if a_end is not None and a_end <= b_start:
            return False
        if b_end is not None and b_end <= a_start:
            return False
        return True

    def balance(self, store: KVRangeStore, alive: Set[str],
                landscape: Dict[str, dict]) -> List:
        # all leader ranges in the landscape, deduped by id
        leaders: Dict[str, tuple] = {}
        for desc in landscape.values():
            for rd in desc.get("ranges", ()):
                if rd.get("is_leader"):
                    leaders[rd["id"]] = (
                        bytes.fromhex(rd["start"]),
                        bytes.fromhex(rd["end"]) if rd["end"] else None)
        out: List = []
        still_pending = set()
        for rid, r in store.ranges.items():
            if not r.is_leader:
                continue
            s, e = store.boundaries[rid]
            conflicted = any(
                other != rid and other < rid
                and self._overlaps(s, e, os_, oe)
                for other, (os_, oe) in leaders.items())
            if not conflicted:
                continue
            n = self._pending.get(rid, 0) + 1
            self._pending[rid] = n
            still_pending.add(rid)
            if n >= self.wait_rounds:
                log.info("redundant-range-removal: retiring %s "
                         "(boundary conflict with a smaller-id leader)",
                         rid)
                out.append(QuitCommand(rid))
                still_pending.discard(rid)
        self._pending = {rid: n for rid, n in self._pending.items()
                         if rid in still_pending}
        return out


class RuleBasedPlacementBalancer:
    """Declarative placement rules → convergence commands
    (≈ RuleBasedPlacementBalancer.java:30: an operator-fed rule document
    generates the expected range layout; the balancer diffs it against the
    current config and emits one migration step per round per range).

    Rule document (set via the placement controller / admin API):
      - ``replica_count``: target voter count per range
      - ``exclude_stores``: drain list — replicas migrate off these stores
      - ``pin_leaders``: {range_id: store_id} — desired leadership
    """

    def __init__(self, rules: Optional[dict] = None) -> None:
        self.rules = rules or {}

    @staticmethod
    def validate(rules: dict) -> Optional[str]:
        """Returns an error string, or None when the document is valid
        (≈ RuleBasedPlacementBalancer.validate)."""
        if not isinstance(rules, dict):
            return "rules must be an object"
        rc = rules.get("replica_count")
        if rc is not None and (not isinstance(rc, int) or rc < 1):
            return "replica_count must be a positive integer"
        ex = rules.get("exclude_stores", [])
        if not isinstance(ex, list) or any(not isinstance(s, str)
                                           for s in ex):
            return "exclude_stores must be a list of store ids"
        pins = rules.get("pin_leaders", {})
        if not isinstance(pins, dict):
            return "pin_leaders must be an object of range_id -> store_id"
        return None

    def _expected_voters(self, rid: str, current: Set[str],
                         alive: Set[str]) -> Optional[List[str]]:
        rc = self.rules.get("replica_count") or len(current)
        excluded = set(self.rules.get("exclude_stores", ()))
        eligible = alive - excluded
        if not eligible:
            return None
        # keep current eligible voters (stability), then fill by
        # per-range rendezvous hash — same placement everywhere
        keep = sorted(current & eligible)

        def score(n: str) -> int:
            h = hashlib.blake2b(f"{n}|{rid}".encode(),
                                digest_size=8).digest()
            return int.from_bytes(h, "big")
        fill = sorted(eligible - current, key=score, reverse=True)
        expected = (keep + fill)[:rc]
        return sorted(expected) if expected else None

    def balance(self, store: KVRangeStore, alive: Set[str]) -> List:
        if not self.rules:
            return []
        out: List = []
        for rid, r in store.ranges.items():
            if not r.is_leader or r.raft.voters_old is not None:
                continue
            current = _voter_nodes(r.raft)
            expected = self._expected_voters(rid, current, alive)
            if expected is None or set(expected) == current:
                # voters converged: apply leader pin if any
                pin = self.rules.get("pin_leaders", {}).get(rid)
                if (pin and pin != store.node_id and pin in current
                        and pin in alive):
                    out.append(TransferLeaderCommand(rid, pin))
                continue
            learner_nodes = {_node_of(m) for m in r.raft.learners}
            to_add = sorted(set(expected) - current - learner_nodes)
            if to_add:
                # stage ONE newcomer as learner (promotion balancer flips
                # it to voter once caught up), like ReplicaCntBalancer
                new_learners = sorted(learner_nodes | {to_add[0]})
                out.append(EnsureReplicaCommand(
                    to_add[0], rid, store.boundaries[rid],
                    sorted(current), new_learners))
                out.append(ConfigChangeCommand(rid, sorted(current),
                                               new_learners))
                continue
            to_drop = sorted(current - set(expected) - {store.node_id})
            if to_drop:
                out.append(ConfigChangeCommand(
                    rid, sorted(current - {to_drop[0]})))
            elif store.node_id not in expected and len(current) > 1:
                # the leader itself must drain: hand off first, quit on a
                # later round once a peer leads
                peers = sorted((current - {store.node_id}) & alive)
                if peers:
                    out.append(TransferLeaderCommand(rid, peers[0]))
        return out


class ClusterPlacementController:
    """Executes placement commands for one store (run by its
    BaseKVStoreServer): ensure-replica travels over the RPC fabric; config
    changes and leader transfers act on the local leader raft."""

    def __init__(self, server, balancers=None, *,
                 interval: float = 0.5,
                 alive_fn: Optional[Callable[[], Set[str]]] = None) -> None:
        self.server = server            # BaseKVStoreServer
        self.store: KVRangeStore = server.store
        self.balancers = balancers if balancers is not None else [
            RangeBootstrapBalancer(), ReplicaCntBalancer(),
            LearnerPromotionBalancer(),
            UnreachableReplicaRemovalBalancer(), RangeLeaderBalancer(),
            RedundantRangeRemovalBalancer(), RuleBasedPlacementBalancer()]
        self.interval = interval
        # default liveness = landscape membership (gossip deployments pass
        # AgentHost.alive_members)
        self.alive_fn = alive_fn or (lambda: set(
            self.server.meta.landscape(self.server.cluster)))
        self._task = None
        self.enabled = True

    def state(self) -> dict:
        return {
            "enabled": self.enabled,
            "interval_s": self.interval,
            "balancers": [type(b).__name__ for b in self.balancers],
            "rules": self.rules,
        }

    @property
    def rules(self) -> dict:
        for b in self.balancers:
            if isinstance(b, RuleBasedPlacementBalancer):
                return b.rules
        return {}

    def set_rules(self, rules: dict) -> Optional[str]:
        """Install a declarative placement-rule document
        (≈ KVStoreBalanceController.updateLoadRules). Returns an error
        string or None on success."""
        err = RuleBasedPlacementBalancer.validate(rules)
        if err is not None:
            return err
        for b in self.balancers:
            if isinstance(b, RuleBasedPlacementBalancer):
                b.rules = rules
                return None
        self.balancers.append(RuleBasedPlacementBalancer(rules))
        return None

    def _leader_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for sid, desc in self.server.meta.landscape(
                self.server.cluster).items():
            counts[sid] = sum(1 for rd in desc["ranges"]
                              if rd["is_leader"])
        return counts

    async def run_once(self) -> int:
        if not self.enabled:
            return 0
        alive = set(self.alive_fn())
        executed = 0
        landscape = None
        rules_active = bool(self.rules)
        for b in self.balancers:
            if rules_active and isinstance(b, ReplicaCntBalancer):
                # an operator rule document owns replica counts while
                # installed — running the default-count balancer alongside
                # would oscillate (add/drop forever) against any rule with
                # a different count or an exclude list
                continue
            if isinstance(b, RangeLeaderBalancer):
                if rules_active and self.rules.get("pin_leaders"):
                    # pinned leadership would fight the spread balancer
                    continue
                cmds = b.balance(self.store, alive, self._leader_counts())
            elif isinstance(b, (RangeBootstrapBalancer,
                                RedundantRangeRemovalBalancer)):
                if landscape is None:
                    landscape = self.server.meta.landscape(
                        self.server.cluster)
                cmds = b.balance(self.store, alive, landscape)
            else:
                cmds = b.balance(self.store, alive)
            failed_ranges: Set[str] = set()
            for cmd in cmds:
                if cmd.range_id in failed_ranges:
                    continue    # its paired predecessor failed: a config
                    # change must not commit a voter whose ensure failed
                try:
                    await self._execute(cmd)
                    executed += 1
                except Exception:  # noqa: BLE001 — keep balancing others
                    failed_ranges.add(cmd.range_id)
                    log.exception("placement command failed: %r", cmd)
        return executed

    async def _execute(self, cmd) -> None:
        import asyncio
        import json

        from ..rpc.fabric import _len16

        if isinstance(cmd, EnsureReplicaCommand):
            addr = self.server.messenger.address_of(cmd.store_id)
            if addr is None:
                raise RuntimeError(f"no address for {cmd.store_id}")
            s, e = cmd.boundary
            payload = _len16(cmd.range_id.encode()) + json.dumps({
                "start": s.hex(),
                "end": e.hex() if e is not None else None,
                "voters": cmd.voter_nodes,
                "learners": cmd.learner_nodes}).encode()
            await asyncio.wait_for(
                self.server.registry.client_for(addr).call(
                    self.server.service, "ensure_range", payload),
                10.0)
        elif isinstance(cmd, ConfigChangeCommand):
            r = self.store.ranges[cmd.range_id]
            voters = [f"{n}:{cmd.range_id}" for n in cmd.voter_nodes]
            learners = (None if cmd.learner_nodes is None else
                        [f"{n}:{cmd.range_id}"
                         for n in cmd.learner_nodes])
            await asyncio.wait_for(
                asyncio.shield(r.raft.change_config(voters, learners)),
                10.0)
        elif isinstance(cmd, TransferLeaderCommand):
            r = self.store.ranges[cmd.range_id]
            r.raft.transfer_leadership(
                f"{cmd.target_node}:{cmd.range_id}")
        elif isinstance(cmd, BootstrapCommand):
            # genesis: single-voter full-boundary range on this store;
            # ReplicaCntBalancer grows it to target on later rounds
            self.store.ensure_range(cmd.range_id, (b"", None),
                                    [self.store.node_id])
        elif isinstance(cmd, QuitCommand):
            self.store.retire_replica(cmd.range_id)

    async def start(self) -> None:
        import asyncio

        async def loop():
            while True:
                await asyncio.sleep(self.interval)
                try:
                    await self.run_once()
                except Exception:  # noqa: BLE001
                    log.exception("placement round failed")
        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except BaseException:  # noqa: BLE001 — cancellation
                pass
            self._task = None
