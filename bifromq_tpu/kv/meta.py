"""Cluster-wide KV landscape + remote store access.

Three pieces, mirroring the reference's base-kv deployment plane:

- ``MetaService`` ≈ base-kv-meta-service (BaseKVMetaService.java:32 /
  IBaseKVClusterMetadataManager): every store publishes a
  ``KVRangeStoreDescriptor`` (store id, RPC address, hosted ranges with
  boundaries + leader flags) into a replicated CRDT map; clients observe
  the union and route by boundary. A static in-proc map backs tests and
  single-process deployments, exactly like ServiceRegistry's static tier.
- ``BaseKVStoreServer`` ≈ base-kv-store-server's RPC facade
  (KVRangeStoreService: query/mutate per range over gRPC): hosts a
  ``KVRangeStore`` behind the RPC fabric, attaches the raft
  ``StoreMessenger``, ticks raft, and re-publishes its descriptor when
  ranges/leadership change.
- ``ClusterKVClient`` ≈ base-kv-store-client (BaseKVStoreClient.java's
  ``latestEffectiveRouter``): boundary-routes a key to the leader replica's
  store, follows ``not_leader`` hints, refreshes the landscape on topology
  change, and retries sealed-range bounces (``b"retry"``).

Status bytes on the query/mutate wire:
  0 ok ‖ result   1 not_leader ‖ len16 leader-store hint
  2 no_range      3 retry (seal/boundary bounce)
"""

from __future__ import annotations

import asyncio
import json
import logging
import struct
import time
from typing import Dict, List, Optional, Tuple

from ..rpc.fabric import RPCServer, ServiceRegistry, _len16, _read16
from ..raft.node import NotLeaderError
from .messenger import StoreMessenger, node_of
from .store import KVRangeStore

log = logging.getLogger(__name__)

_OK, _NOT_LEADER, _NO_RANGE, _RETRY = 0, 1, 2, 3


class MetaService:
    """Replicated store-descriptor map (landscape)."""

    URI = "landscape"

    def __init__(self, crdt_store=None) -> None:
        self.crdt = crdt_store
        self._static: Dict[str, dict] = {}   # "cluster/store" -> descriptor

    def announce(self, cluster: str, descriptor: dict) -> None:
        key = f"{cluster}/{descriptor['store_id']}"
        if self.crdt is not None:
            # latest-wins register semantics over the AWORSet: retire every
            # superseded element, then add the new one
            for el in self.crdt.elements(self.URI, key):
                self.crdt.set_remove(self.URI, key, el)
            self.crdt.set_add(self.URI, key, json.dumps(descriptor,
                                                        sort_keys=True))
        self._static[key] = descriptor

    def withdraw(self, cluster: str, store_id: str) -> None:
        key = f"{cluster}/{store_id}"
        if self.crdt is not None:
            self.crdt.remove_key(self.URI, key)
        self._static.pop(key, None)

    def landscape(self, cluster: str) -> Dict[str, dict]:
        """store_id → freshest descriptor (max epoch wins across tiers)."""
        out: Dict[str, dict] = {}

        def fold(desc: dict) -> None:
            sid = desc["store_id"]
            if sid not in out or desc["epoch"] > out[sid]["epoch"]:
                out[sid] = desc

        prefix = f"{cluster}/"
        if self.crdt is not None:
            for key in self.crdt.keys(self.URI):
                if key.startswith(prefix):
                    for el in self.crdt.elements(self.URI, key):
                        try:
                            fold(json.loads(el))
                        except (ValueError, KeyError):
                            continue
        for key, desc in self._static.items():
            if key.startswith(prefix):
                fold(desc)
        return out


def _store_descriptor(store: KVRangeStore, address: str,
                      epoch: int) -> dict:
    ranges = []
    for rid, r in sorted(store.ranges.items()):
        s, e = store.boundaries[rid]
        leader = r.raft.leader_id
        ranges.append({
            "id": rid, "start": s.hex(),
            "end": e.hex() if e is not None else None,
            "is_leader": r.is_leader,
            "leader_store": node_of(leader) if leader else None,
            "voters": sorted(node_of(v) for v in r.raft.voters),
            "learners": sorted(node_of(m) for m in r.raft.learners),
        })
    return {"store_id": store.node_id, "address": address, "epoch": epoch,
            "ranges": ranges}


class BaseKVStoreServer:
    """RPC facade for one KVRangeStore process."""

    ANNOUNCE_INTERVAL = 0.1

    def __init__(self, store: KVRangeStore, messenger: StoreMessenger,
                 server: RPCServer, registry: ServiceRegistry,
                 meta: MetaService, *, cluster: str = "dist",
                 tick_interval: float = 0.02) -> None:
        self.store = store
        self.messenger = messenger
        self.server = server
        self.registry = registry
        self.meta = meta
        self.cluster = cluster
        self.tick_interval = tick_interval
        self.service = f"basekv:{cluster}"
        self._epoch = 0
        self._last_published = None
        self._zombie_rounds: Dict[str, int] = {}
        self._tasks: List[asyncio.Task] = []
        server.register(self.service, {
            "query": self._on_query,
            "mutate": self._on_mutate,
            "mutate_fwd": self._on_mutate_fwd,
            "describe": self._on_describe,
            "ensure_range": self._on_ensure_range,
            "recover": self._on_recover,
            "range_stats": self._on_range_stats,
        })
        messenger.attach(server)

    async def start(self) -> None:
        if self.server._server is None:
            await self.server.start()
        addr = self.server.address
        # per-node raft ingress: EXCLUSIVE ownership — a crashed
        # predecessor's stale address must not shadow this incarnation
        # (peers' messengers resolve the first endpoint)
        node_svc = f"{self.messenger.service}:{self.store.node_id}"
        for stale in list(self.registry.endpoints(node_svc)):
            if stale != addr:
                self.registry.withdraw(node_svc, stale)
        self.registry.announce(node_svc, addr)
        self.registry.announce(self.service, addr)
        await self.messenger.start()
        self._publish(force=True)

        async def tick_loop() -> None:
            while True:
                try:
                    self.store.tick()
                    self._check_zombies()
                    self._publish()
                except Exception:  # noqa: BLE001 — a tick error must not
                    log.exception("store tick failed")  # zombie the store
                await asyncio.sleep(self.tick_interval)
        self._tasks.append(asyncio.create_task(tick_loop()))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        await self.messenger.stop()
        addr = self.server.address
        self.registry.withdraw(f"{self.messenger.service}:"
                               f"{self.store.node_id}", addr)
        self.registry.withdraw(self.service, addr)
        self.meta.withdraw(self.cluster, self.store.node_id)
        self.store.stop()
        await self.server.stop()

    ZOMBIE_ROUNDS = 50

    def _check_zombies(self) -> None:
        """Zombie-quit (≈ the reference's quit of a replica outside the
        latest config): retire a local replica only when BOTH its own raft
        sees itself excluded AND the landscape's current leader for the
        range persistently publishes a voter set without this store — an
        appended-but-uncommitted config (leader crashed mid-change) never
        destroys state, because the next leader elected under the old
        config re-includes us in its descriptor."""
        landscape = None
        for rid, r in list(self.store.ranges.items()):
            if not r.raft.is_zombie:
                self._zombie_rounds.pop(rid, None)
                continue
            if landscape is None:
                landscape = self.meta.landscape(self.cluster)
            excluded = False
            for sid, desc in landscape.items():
                if sid == self.store.node_id:
                    continue
                for rd in desc["ranges"]:
                    if (rd["id"] == rid and rd["is_leader"]
                            and self.store.node_id
                            not in rd.get("voters", [])
                            and self.store.node_id
                            not in rd.get("learners", [])):
                        excluded = True
            if not excluded:
                self._zombie_rounds.pop(rid, None)
                continue
            n = self._zombie_rounds.get(rid, 0) + 1
            self._zombie_rounds[rid] = n
            if n >= self.ZOMBIE_ROUNDS:
                self._zombie_rounds.pop(rid, None)
                log.info("zombie-quit: retiring excluded replica %s", rid)
                self.store.retire_replica(rid)

    def _publish(self, force: bool = False) -> None:
        desc = _store_descriptor(self.store, self.server.address,
                                 self._epoch)
        fingerprint = json.dumps(desc["ranges"], sort_keys=True)
        if not force and fingerprint == self._last_published:
            return
        # restart-monotonic: a rebooted store's fresh descriptors must
        # outrank its pre-crash ones in the landscape's max-epoch fold
        self._epoch = max(self._epoch + 1, time.time_ns() // 1_000_000)
        desc["epoch"] = self._epoch
        self._last_published = fingerprint
        self.meta.announce(self.cluster, desc)

    # ---------------- handlers ---------------------------------------------

    def _range(self, range_id: str):
        return self.store.ranges.get(range_id)

    @staticmethod
    def _leader_hint(r) -> bytes:
        leader = r.raft.leader_id
        hint = node_of(leader) if leader else ""
        return bytes([_NOT_LEADER]) + _len16(hint.encode())

    async def _on_query(self, payload: bytes, _okey: str) -> bytes:
        from .range import BoundaryBounce

        rid_b, pos = _read16(payload, 0)
        linearized = bool(payload[pos])
        r = self._range(rid_b.decode())
        if r is None:
            return bytes([_NO_RANGE])
        try:
            out = await r.query_coproc(payload[pos + 1:],
                                       linearized=linearized)
        except NotLeaderError:
            return self._leader_hint(r)
        except BoundaryBounce:      # split/merge raced: re-resolve
            return bytes([_RETRY])
        return bytes([_OK]) + out

    async def _on_mutate(self, payload: bytes, okey: str) -> bytes:
        return await self._mutate_impl(payload, okey, may_forward=True)

    async def _on_mutate_fwd(self, payload: bytes, okey: str) -> bytes:
        # forwarded hop: never re-forward (loop guard)
        return await self._mutate_impl(payload, okey, may_forward=False)

    async def _mutate_impl(self, payload: bytes, okey: str,
                           may_forward: bool) -> bytes:
        rid_b, pos = _read16(payload, 0)
        r = self._range(rid_b.decode())
        if r is None:
            return bytes([_NO_RANGE])
        try:
            out = await r.mutate_coproc(payload[pos:])
        except NotLeaderError:
            # follower-received proposal: forward to the leader instead of
            # bouncing to the caller (the reference's store client follows
            # leaders; here the store proxies one hop so callers don't
            # need retry logic at all)
            fwd = await self._forward_to_leader(r, payload, okey) \
                if may_forward else None
            return fwd if fwd is not None else self._leader_hint(r)
        if out == b"retry":         # sealed for a merge: re-resolve
            return bytes([_RETRY])
        return bytes([_OK]) + out

    async def _forward_to_leader(self, r, payload: bytes,
                                 okey: str) -> Optional[bytes]:
        leader = r.raft.leader_id
        if leader is None:
            return None
        leader_node = node_of(leader)
        if leader_node == self.store.node_id:
            return None
        addr = self.messenger.address_of(leader_node)
        if addr is None:
            return None
        try:
            return await asyncio.wait_for(
                self.registry.client_for(addr).call(
                    self.service, "mutate_fwd", payload, order_key=okey),
                ClusterKVClient.CALL_TIMEOUT)
        except Exception:  # noqa: BLE001 — dead leader: caller re-routes
            return None

    async def _on_describe(self, _payload: bytes, _okey: str) -> bytes:
        return json.dumps(_store_descriptor(
            self.store, self.server.address, self._epoch)).encode()

    async def _on_ensure_range(self, payload: bytes, _okey: str) -> bytes:
        """Open a replica shell (placement target half, kv/placement.py)."""
        rid_b, pos = _read16(payload, 0)
        spec = json.loads(payload[pos:].decode())
        boundary = (bytes.fromhex(spec["start"]),
                    bytes.fromhex(spec["end"])
                    if spec["end"] is not None else None)
        self.store.ensure_range(rid_b.decode(), boundary, spec["voters"],
                                spec.get("learners"))
        return b"ok"

    async def _on_range_stats(self, _payload: bytes, _okey: str) -> bytes:
        """Per-range observability (≈ KVRangeMetricManager snapshot)."""
        from .metrics import range_stats
        return json.dumps(range_stats(self.store)).encode()

    async def _on_recover(self, payload: bytes, _okey: str) -> bytes:
        """Operator quorum-loss recovery RPC
        (≈ BaseKVStoreService.proto:33 RecoverRequest)."""
        rid_b, pos = _read16(payload, 0)
        live = json.loads(payload[pos:].decode()) if payload[pos:] else None
        self.store.recover(rid_b.decode(), live)
        return b"ok"


class ClusterKVClient:
    """Boundary router + leader-following query/mutate pipelines."""

    MAX_ATTEMPTS = 8
    CALL_TIMEOUT = 10.0

    def __init__(self, meta: MetaService, registry: ServiceRegistry, *,
                 cluster: str = "dist",
                 seeds: Optional[List[str]] = None) -> None:
        self.meta = meta
        self.registry = registry
        self.cluster = cluster
        self.seeds = list(seeds or [])   # store addresses to poll when the
        self.service = f"basekv:{cluster}"  # landscape isn't CRDT-replicated
        # NOTE: basekv deliberately does NOT use the idempotency
        # whitelist — _call below is its own at-least-once retry loop
        # (leader-hint rerouting incl. mutations, whose idempotence the
        # keyspace contracts guarantee; see mutate()'s docstring)
        # range_id -> (start, end, leader_store, {store_id: address})
        self._routes: List[Tuple[bytes, Optional[bytes], str,
                                 Optional[str], Dict[str, str]]] = []
        self.refresh()

    def refresh(self) -> None:
        landscape = self.meta.landscape(self.cluster)
        by_range: Dict[str, dict] = {}
        for sid, desc in landscape.items():
            for rd in desc["ranges"]:
                rec = by_range.setdefault(rd["id"], {
                    "start": bytes.fromhex(rd["start"]),
                    "end": (bytes.fromhex(rd["end"])
                            if rd["end"] is not None else None),
                    "boundary_from_leader": rd["is_leader"],
                    "boundary_epoch": desc["epoch"],
                    "leader": None, "leader_epoch": -1, "stores": {}})
                rec["stores"][sid] = desc["address"]
                # boundary: trust the LEADER's descriptor — a range's
                # leader has always applied its latest split/merge (they
                # commit through its own log), while a lagging follower
                # republishing for unrelated reasons can carry a stale
                # wide boundary at a fresher store epoch. Follower
                # boundaries are only a fallback while no leader claims.
                if rd["is_leader"] and (
                        not rec["boundary_from_leader"]
                        or desc["epoch"] > rec["boundary_epoch"]):
                    rec["start"] = bytes.fromhex(rd["start"])
                    rec["end"] = (bytes.fromhex(rd["end"])
                                  if rd["end"] is not None else None)
                    rec["boundary_from_leader"] = True
                    rec["boundary_epoch"] = desc["epoch"]
                # freshest leader claim wins: a dead store's stale
                # is_leader flag must not shadow a newer election result
                if rd["is_leader"] and desc["epoch"] > rec["leader_epoch"]:
                    rec["leader"] = sid
                    rec["leader_epoch"] = desc["epoch"]
                elif rec["leader"] is None and rd["leader_store"]:
                    rec["leader"] = rd["leader_store"]
        self._routes = sorted(
            ((rec["start"], rec["end"], rid, rec["leader"], rec["stores"])
             for rid, rec in by_range.items()),
            key=lambda t: t[0])

    def find(self, key: bytes):
        for start, end, rid, leader, stores in self._routes:
            if key >= start and (end is None or key < end):
                return rid, leader, stores
        return None

    def ranges(self) -> List[Tuple[bytes, Optional[bytes], str]]:
        return [(s, e, rid) for s, e, rid, _l, _st in self._routes]

    async def refresh_remote(self) -> None:
        """Fold fresh descriptors polled from seed stores into the local
        landscape (cross-process deployments without a shared CRDT); a seed
        that fails the poll is evicted so its stale descriptor can't keep
        routing traffic at a dead address."""
        for addr in self.seeds:
            try:
                desc = await asyncio.wait_for(self.describe(addr),
                                              self.CALL_TIMEOUT)
                self.meta.announce(self.cluster, desc)
            except Exception:  # noqa: BLE001 — dead seed: evict + skip
                for sid, desc in self.meta.landscape(self.cluster).items():
                    if desc["address"] == addr:
                        self.meta.withdraw(self.cluster, sid)
        self.refresh()

    async def _refresh(self) -> None:
        if self.seeds:
            await self.refresh_remote()
        else:
            self.refresh()

    @staticmethod
    def _replica_pick(stores: Dict[str, str], key: bytes,
                      exclude=()) -> Optional[str]:
        import hashlib
        candidates = [s for s in stores if s not in exclude] or list(stores)
        if not candidates:
            return None

        def score(sid: str) -> int:
            h = hashlib.blake2b(sid.encode() + b"|" + key,
                                digest_size=8).digest()
            return int.from_bytes(h, "big")
        return max(candidates, key=score)

    async def _call(self, method: str, key: bytes, payload: bytes,
                    *, order_key: str = "",
                    any_replica: bool = False) -> bytes:
        last_err: Optional[Exception] = None
        prefer: Optional[str] = None
        failed: set = set()     # stores that errored THIS call: a dead
        for attempt in range(self.MAX_ATTEMPTS):  # rendezvous winner must
            route = self.find(key)                # not eat every retry
            if route is None:
                await asyncio.sleep(0.05)
                await self._refresh()
                continue
            rid, leader, stores = route
            if any_replica and prefer is None:
                target = self._replica_pick(stores, key, exclude=failed)
            else:
                target = prefer or leader
            addr = stores.get(target) if target else None
            if addr is None:            # no known leader: probe any replica
                addr = next(iter(stores.values()), None)
            if addr is None:
                await asyncio.sleep(0.05)
                await self._refresh()
                continue
            body = _len16(rid.encode()) + payload
            try:
                # wait_for bounds connection establishment too (a
                # blackholed store must not stall the call for the OS
                # SYN-retry window)
                out = await asyncio.wait_for(
                    self.registry.client_for(addr).call(
                        self.service, method, body, order_key=order_key),
                    self.CALL_TIMEOUT)
            except Exception as e:  # noqa: BLE001 — dead store: re-route
                last_err = e
                prefer = None
                if target is not None:
                    failed.add(target)
                await asyncio.sleep(0.05 * (attempt + 1))
                await self._refresh()
                continue
            status = out[0]
            if status == _OK:
                return out[1:]
            if status == _NOT_LEADER:
                hint_b, _ = _read16(out, 1)
                prefer = hint_b.decode() or None
                if prefer == target:    # stale self-hint: re-elect soon
                    prefer = None
                await asyncio.sleep(0.02 * (attempt + 1))
                await self._refresh()
                continue
            # no_range (post-split/merge topology) or sealed retry
            prefer = None
            await asyncio.sleep(0.02 * (attempt + 1))
            await self._refresh()
        raise RuntimeError(
            f"kv {method} failed after {self.MAX_ATTEMPTS} attempts"
            + (f": {last_err!r}" if last_err else ""))

    async def query(self, key: bytes, payload: bytes, *,
                    linearized: bool = True) -> bytes:
        """Linearized queries go to the leader (read-index barrier);
        non-linearized ones REPLICA-SPREAD by rendezvous hash over every
        store hosting the range (≈ BatchDistServerCall.replicaSelect:245
        scaling reads across followers)."""
        return await self._call(
            "query", key, bytes([int(linearized)]) + payload,
            any_replica=not linearized)

    async def mutate(self, key: bytes, payload: bytes, *,
                     order_key: str = "") -> bytes:
        """Mutations MUST be idempotent: a reply lost to a connection drop
        re-proposes an already-committed op (the same at-least-once
        contract range.py's crash re-apply already imposes — route upserts
        carry incarnation guards, inbox inserts op-nonce dedup)."""
        return await self._call("mutate", key, payload,
                                order_key=order_key)

    async def describe(self, address: str) -> dict:
        out = await self.registry.client_for(address).call(
            self.service, "describe", b"")
        return json.loads(out.decode())
