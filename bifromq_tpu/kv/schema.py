"""KV key/value codecs for the domain stores.

Order-preserving, tenant-scoped binary encodings with the same structural
properties as the reference schemas (not byte-identical — the wire/storage
format is ours):

- dist routes (≈ bifromq-dist-worker-schema .../schema/KVSchemaUtil.java:96):
  one record per (tenant, filter, flag, group?, receiver); keys sort so a
  tenant's whole route table is one contiguous range (prefix scan rebuilds
  the matcher), and escaped filter levels sort in trie DFS order.
- inbox records (≈ inbox-store-schema KVSchemaUtil.java:40): per (tenant,
  inbox, incarnation): a metadata record plus two seq-keyed message queues
  (qos0 and send-buffer) whose keys sort by sequence number.
- retained messages (≈ retain-store schema): (tenant, topic) records.

Values are framed with a tiny struct codec (no pickle: stable + safe).
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple

from ..models.oracle import Route
from ..types import (ClientInfo, Message, QoS, RouteMatcher, RouteMatcherType,
                     TopicFilterOption)
from ..utils import topic as topic_util

NUL = b"\x00"

# key-space tags (first byte)
TAG_DIST = b"\x00"
TAG_INBOX = b"\x01"
TAG_RETAIN = b"\x02"

SCHEMA_VER = b"\x01"

# route flags (≈ KVSchemaConstants flag byte)
FLAG_NORMAL = 0
FLAG_UNORDERED = 1
FLAG_ORDERED = 2


def _len16(b: bytes) -> bytes:
    return struct.pack(">H", len(b)) + b


def _read_len16(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n = struct.unpack_from(">H", buf, pos)[0]
    pos += 2
    return buf[pos:pos + n], pos + n


# ------------------------------- dist routes --------------------------------

def tenant_route_prefix(tenant_id: str) -> bytes:
    return TAG_DIST + SCHEMA_VER + _len16(tenant_id.encode())


def route_key(tenant_id: str, matcher: RouteMatcher,
              receiver_url: Tuple[int, str, str]) -> bytes:
    """Key = tenant prefix ‖ len16(escaped-filter) ‖ flag ‖ group ‖ receiver.

    The filter field is length-framed (NUL is the escaped level separator,
    so it cannot double as a terminator); tenant-prefix contiguity — the
    property the matcher rebuild scan relies on — is preserved.
    """
    flag = {RouteMatcherType.NORMAL: FLAG_NORMAL,
            RouteMatcherType.UNORDERED_SHARE: FLAG_UNORDERED,
            RouteMatcherType.ORDERED_SHARE: FLAG_ORDERED}[matcher.type]
    broker_id, receiver_id, deliverer_key = receiver_url
    return (tenant_route_prefix(tenant_id)
            + _len16(topic_util.escape(
                "/".join(matcher.filter_levels)).encode())
            + bytes([flag])
            + _len16((matcher.group or "").encode())
            + struct.pack(">I", broker_id)
            + _len16(receiver_id.encode())
            + _len16(deliverer_key.encode()))


def route_value(incarnation: int) -> bytes:
    return struct.pack(">q", incarnation)


def decode_route(tenant_id: str, key: bytes, value: bytes) -> Route:
    prefix = tenant_route_prefix(tenant_id)
    assert key.startswith(prefix)
    rest = key[len(prefix):]
    filter_b, pos = _read_len16(rest, 0)
    filter_levels = tuple(topic_util.unescape(filter_b.decode()).split("/"))
    flag = rest[pos]
    pos += 1
    group_b, pos = _read_len16(rest, pos)
    broker_id = struct.unpack_from(">I", rest, pos)[0]
    pos += 4
    receiver_b, pos = _read_len16(rest, pos)
    deliverer_b, pos = _read_len16(rest, pos)
    mtype = {FLAG_NORMAL: RouteMatcherType.NORMAL,
             FLAG_UNORDERED: RouteMatcherType.UNORDERED_SHARE,
             FLAG_ORDERED: RouteMatcherType.ORDERED_SHARE}[flag]
    group = group_b.decode() or None
    filter_str = "/".join(filter_levels)
    if mtype == RouteMatcherType.UNORDERED_SHARE:
        mqtt_filter = f"{topic_util.UNORDERED_SHARE}/{group}/{filter_str}"
    elif mtype == RouteMatcherType.ORDERED_SHARE:
        mqtt_filter = f"{topic_util.ORDERED_SHARE}/{group}/{filter_str}"
    else:
        mqtt_filter = filter_str
    incarnation = struct.unpack(">q", value)[0]
    return Route(
        matcher=RouteMatcher(type=mtype, filter_levels=filter_levels,
                             mqtt_topic_filter=mqtt_filter, group=group),
        broker_id=broker_id, receiver_id=receiver_b.decode(),
        deliverer_key=deliverer_b.decode(), incarnation=incarnation)


# ------------------------------- messages -----------------------------------

def encode_message(msg: Message) -> bytes:
    props = msg.user_properties or ()
    out = struct.pack(">QBQI?", msg.message_id, int(msg.pub_qos),
                      msg.timestamp, msg.expiry_seconds, msg.is_retain)
    out += _len16(msg.payload if isinstance(msg.payload, bytes)
                  else bytes(msg.payload))
    out += struct.pack(">H", len(props))
    for k, v in props:
        out += _len16(k.encode()) + _len16(v.encode())
    out += _len16(msg.content_type.encode())
    out += _len16(msg.response_topic.encode())
    out += _len16(msg.correlation_data)
    out += struct.pack(">B", msg.payload_format_indicator)
    return out


def decode_message(buf: bytes) -> Message:
    message_id, qos, ts, expiry, retain = struct.unpack_from(">QBQI?", buf, 0)
    pos = struct.calcsize(">QBQI?")
    payload, pos = _read_len16(buf, pos)
    n_props = struct.unpack_from(">H", buf, pos)[0]
    pos += 2
    props = []
    for _ in range(n_props):
        k, pos = _read_len16(buf, pos)
        v, pos = _read_len16(buf, pos)
        props.append((k.decode(), v.decode()))
    content_type, pos = _read_len16(buf, pos)
    response_topic, pos = _read_len16(buf, pos)
    correlation, pos = _read_len16(buf, pos)
    pfi = buf[pos]
    return Message(message_id=message_id, pub_qos=QoS(qos), payload=payload,
                   timestamp=ts, expiry_seconds=expiry, is_retain=retain,
                   user_properties=tuple(props),
                   content_type=content_type.decode(),
                   response_topic=response_topic.decode(),
                   correlation_data=correlation, payload_format_indicator=pfi)


# ------------------------------- inbox --------------------------------------

def inbox_prefix(tenant_id: str, inbox_id: str = None) -> bytes:
    out = TAG_INBOX + _len16(tenant_id.encode())
    if inbox_id is not None:
        out += _len16(inbox_id.encode())
    return out


# record kinds within an inbox (order matters: metadata first, then queues).
# The live incarnation lives INSIDE the metadata value, not the key path, so
# metadata is a direct get() — recreate deletes the whole prefix first.
_INBOX_META = b"\x00"
_INBOX_QOS0 = b"\x01"
_INBOX_BUF = b"\x02"
_INBOX_OP = b"\x03"   # last-applied op id (replicated-apply dedup)


def inbox_meta_key(tenant_id: str, inbox_id: str) -> bytes:
    return inbox_prefix(tenant_id, inbox_id) + _INBOX_META


def inbox_op_key(tenant_id: str, inbox_id: str) -> bytes:
    return inbox_prefix(tenant_id, inbox_id) + _INBOX_OP


def inbox_qos0_key(tenant_id: str, inbox_id: str, seq: int) -> bytes:
    return (inbox_prefix(tenant_id, inbox_id) + _INBOX_QOS0
            + struct.pack(">Q", seq))


def inbox_buffer_key(tenant_id: str, inbox_id: str, seq: int) -> bytes:
    return (inbox_prefix(tenant_id, inbox_id) + _INBOX_BUF
            + struct.pack(">Q", seq))


def seq_of(key: bytes) -> int:
    return struct.unpack(">Q", key[-8:])[0]


# ------------------------------- retain -------------------------------------

def retain_key(tenant_id: str, topic: str) -> bytes:
    return TAG_RETAIN + _len16(tenant_id.encode()) + topic.encode()


def split_retain_key(key: bytes) -> tuple:
    tenant_b, pos = _read_len16(key, 1)
    return tenant_b.decode(), key[pos:].decode()


def retain_prefix(tenant_id: str) -> bytes:
    return TAG_RETAIN + _len16(tenant_id.encode())


def prefix_end(prefix: bytes) -> bytes:
    """Smallest byte string greater than every key with this prefix."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return b"\xff" * 16
