"""Replicated KV range: raft-driven state machine over an IKVSpace.

A deliberately lean re-expression of the reference's range replica
(base-kv-store-server .../store/range/KVRangeFSM.java:164 — raft WAL + data
space + apply loop + coproc). Split and the two-phase merge handshake live
in the hosting store (kv/store.py) behind the on_split/on_seal/on_merge
apply hooks:

- mutations serialize into raft entries; the apply loop executes them on the
  local space in commit order on every replica
- reads go through ``read_index`` for linearizability
  (≈ KVRangeQueryLinearizer.java:37)
- the coproc SPI mirrors IKVRangeCoProc: ``query(input, reader)`` /
  ``mutate(input, reader, writer)`` / ``reset(boundary)``
- raft snapshots serialize the whole space (RocksDB-checkpoint analog)
"""

from __future__ import annotations

import struct
from typing import Awaitable, Callable, List, Optional, Tuple

from ..raft.node import LogEntry, RaftNode
from .engine import IKVSpace, KVWriteBatch


class BoundaryBounce(Exception):
    """Raised by a coproc QUERY whose key fell outside this range's
    boundary (split/merge raced the caller's routing): the RPC facade
    maps it to the RETRY status so the client re-resolves — the read-side
    twin of the mutate path's ``b"retry"`` sentinel."""


class IKVRangeCoProc:
    """Domain-logic plug point (≈ base-kv-store-coproc-api IKVRangeCoProc)."""

    def query(self, input_data: bytes, reader: IKVSpace) -> bytes:
        raise NotImplementedError

    def mutate(self, input_data: bytes, reader: IKVSpace,
               writer: KVWriteBatch) -> bytes:
        """Stage writes into ``writer``; return the output payload.

        ``b"retry"`` is RESERVED: it signals a boundary/seal bounce and
        makes the caller re-resolve the range and re-propose — coprocs
        return it for keys outside their boundary, never as user data.
        """
        raise NotImplementedError

    def reset(self, reader: IKVSpace) -> None:
        """Rebuild derived state after a snapshot restore
        (≈ DistWorkerCoProc.reset:283 rebuilding Fact/caches)."""


async def propose_with_leader_wait(rng, fn, *, timeout: float = 5.0,
                                   tick_single_voter: bool = False):
    """Run a consensus proposal with a bounded wait for leadership.

    The ONE retry idiom for every proposal path (dist mutations, inbox,
    retain, split/merge): a NotLeaderError during the initial-election
    window waits and retries; a steady-state follower (a DIFFERENT known
    leader) re-raises so callers redirect. ``tick_single_voter`` drives a
    sole-voter group's election synchronously (standalone ranges used
    without a tick loop).
    """
    import asyncio
    import time as _time

    from ..raft.node import NotLeaderError, Role

    deadline = _time.monotonic() + timeout
    while True:
        try:
            return await fn()
        except NotLeaderError:
            raft = rng.raft
            if _time.monotonic() >= deadline or raft.stopped:
                raise
            if tick_single_voter and len(raft.voters) == 1:
                for _ in range(200):
                    if raft.role == Role.LEADER:
                        break
                    raft.tick()
                continue
            if raft.leader_id not in (None, raft.id):
                raise
            await asyncio.sleep(0.01)


# wire ops inside raft entries
_OP_PUT = 0
_OP_DEL = 1
_OP_DEL_RANGE = 2
_OP_COPROC = 3


def _enc_kv_ops(ops: List[Tuple[str, bytes, Optional[bytes]]]) -> bytes:
    out = bytearray([0])  # kind 0 = raw kv batch
    out += struct.pack(">I", len(ops))
    for op, a, b in ops:
        code = {"put": _OP_PUT, "del": _OP_DEL, "del_range": _OP_DEL_RANGE}[op]
        out.append(code)
        out += struct.pack(">I", len(a)) + a
        b = b or b""
        out += struct.pack(">I", len(b)) + b
    return bytes(out)


def _enc_coproc(payload: bytes) -> bytes:
    return bytes([1]) + payload


_META_APPLIED = b"raft_applied"


class ReplicatedKVRange:
    """One raft-replicated range bound to a local space + coproc.

    With ``raft_store`` (an IRaftStateStore, e.g. over the durable native
    engine) the replica survives restart without violating raft safety: hard
    state/log/snapshot reload from the store, and the data space carries an
    applied-index watermark so entries already folded into durable FSM state
    are not re-applied. The watermark is written after the apply batch (not
    atomically with it), so a crash between the two re-applies ONE entry —
    all range ops (kv put/del/del_range, coproc route upserts with
    incarnation guards) are idempotent under re-apply.
    """

    def __init__(self, range_id: str, node_id: str, voters: List[str],
                 transport, space: IKVSpace,
                 coproc: Optional[IKVRangeCoProc] = None,
                 raft_store=None,
                 learners: Optional[List[str]] = None) -> None:
        self.range_id = range_id
        self.space = space
        self.coproc = coproc
        # results kept only for indices this node proposed (followers apply
        # the same entries but have no caller waiting — don't accumulate)
        self._mutation_results: dict = {}
        self._pending_results: set = set()
        applied = 0
        if raft_store is not None:
            raw = space.get_metadata(_META_APPLIED)
            applied = struct.unpack(">Q", raw)[0] if raw else 0
            snap = raft_store.load_snapshot()
            if snap is not None and snap.last_index > applied:
                # the FSM fell behind its own snapshot (e.g. fresh space on
                # an old store): reinstall before serving
                self._restore(snap.data)
                applied = snap.last_index
                space.put_metadata(_META_APPLIED,
                                   struct.pack(">Q", applied))
        self.raft = RaftNode(
            node_id, voters, transport,
            learners=learners,
            apply_cb=self._apply,
            snapshot_cb=self._snapshot,
            restore_cb=self._restore,
            store=raft_store,
            initial_applied=applied)

    # ---------------- raft callbacks ---------------------------------------

    # set by a hosting KVRangeStore: fn(split_key) runs the deterministic
    # split state transfer at this entry's apply position on every replica
    on_split = None
    # merge hooks (≈ KVRangeFSM's dual-range merge state machine):
    # on_seal(sealed: bool) toggles this range's write seal; on_merge(
    # payload) folds a sealed sibling into this range — both run at apply
    # position on every replica
    on_seal = None
    on_merge = None
    # derived deterministically from the log (seal/unseal apply positions);
    # blocks EVERY mutation kind, including raw kv batches
    sealed = False

    def _apply(self, entry: LogEntry) -> None:
        data = entry.data
        if not data:
            return
        kind = data[0]
        if kind == 0:
            if not self.sealed:  # sealed: content is frozen for the merge
                self._apply_kv_batch(data)
        elif kind == 2:  # split marker (≈ KVRangeFSM WALSplit command)
            if self.on_split is not None:
                self.on_split(data[1:])
        elif kind == 3:  # seal/unseal marker (merge ph.1, ≈ WALPrepareMerge)
            self.sealed = bool(data[1]) if len(data) > 1 else True
            if self.on_seal is not None:
                self.on_seal(self.sealed)
        elif kind == 4:  # merge-commit payload (phase 2, ≈ WALMerge)
            if self.on_merge is not None:
                self.on_merge(data[1:])
        else:
            if self.sealed:
                out = b"retry"
            else:
                writer = self.space.writer()
                out = (self.coproc.mutate(data[1:], self.space, writer)
                       if self.coproc is not None else b"")
                writer.done()
            if entry.index in self._pending_results:
                self._mutation_results[entry.index] = out
        if self.raft is not None and self.raft.store is not None:
            self.space.put_metadata(_META_APPLIED,
                                    struct.pack(">Q", entry.index))

    def _apply_kv_batch(self, data: bytes) -> None:
        n = struct.unpack_from(">I", data, 1)[0]
        pos = 5
        w = self.space.writer()
        for _ in range(n):
            code = data[pos]
            pos += 1
            alen = struct.unpack_from(">I", data, pos)[0]
            pos += 4
            a = data[pos:pos + alen]
            pos += alen
            blen = struct.unpack_from(">I", data, pos)[0]
            pos += 4
            b = data[pos:pos + blen]
            pos += blen
            if code == _OP_PUT:
                w.put(a, b)
            elif code == _OP_DEL:
                w.delete(a)
            else:
                w.delete_range(a, b)
        w.done()

    def _snapshot(self) -> bytes:
        out = bytearray()
        for k, v in self.space.iterate():
            out += struct.pack(">I", len(k)) + k
            out += struct.pack(">I", len(v)) + v
        return bytes(out)

    def _restore(self, data: bytes) -> None:
        w = self.space.writer()
        w.delete_range(b"", b"\xff" * 32)
        pos = 0
        while pos < len(data):
            klen = struct.unpack_from(">I", data, pos)[0]
            pos += 4
            k = data[pos:pos + klen]
            pos += klen
            vlen = struct.unpack_from(">I", data, pos)[0]
            pos += 4
            v = data[pos:pos + vlen]
            pos += vlen
            w.put(k, v)
        w.done()
        if self.coproc is not None:
            self.coproc.reset(self.space)

    # ---------------- public API -------------------------------------------

    async def put(self, key: bytes, value: bytes) -> None:
        await self.raft.propose(_enc_kv_ops([("put", key, value)]))

    async def delete(self, key: bytes) -> None:
        await self.raft.propose(_enc_kv_ops([("del", key, None)]))

    async def write_batch(self, ops) -> None:
        await self.raft.propose(_enc_kv_ops(ops))

    async def propose_split(self, split_key: bytes) -> None:
        """Replicate a split marker; the hosting store's ``on_split`` hook
        executes the state transfer when it applies."""
        await self.raft.propose(bytes([2]) + split_key)

    async def propose_seal(self, sealed: bool = True) -> None:
        """Merge phase 1: once this marker applies, no later mutation of
        ANY kind can change the space — every replica's content is frozen
        at the same log position (the precondition for a deterministic
        merge). ``sealed=False`` rolls the seal back (aborted merge)."""
        await self.raft.propose(bytes([3, int(sealed)]))

    async def propose_merge(self, payload: bytes) -> None:
        """Merge phase 2 (proposed on the SURVIVING range): payload carries
        the sealed sibling's id, boundary, and data."""
        await self.raft.propose(bytes([4]) + payload)

    async def mutate_coproc(self, payload: bytes) -> bytes:
        """RW coproc call through consensus (≈ KVRangeRWRequest execute)."""
        # register interest BEFORE proposing: a single-voter leader commits
        # and applies synchronously inside propose(), so registering after
        # would miss the result
        guess = self.raft.last_index + 1
        self._pending_results.add(guess)
        try:
            index = await self.raft.propose(_enc_coproc(payload))
        finally:
            self._pending_results.discard(guess)
        return self._mutation_results.pop(index, b"")

    async def get(self, key: bytes, *, linearized: bool = True
                  ) -> Optional[bytes]:
        if linearized:
            await self.raft.read_index()
        return self.space.get(key)

    async def query_coproc(self, payload: bytes, *,
                           linearized: bool = True) -> bytes:
        """RO coproc call (≈ KVRangeRORequest via KVRangeQueryRunner)."""
        if linearized:
            await self.raft.read_index()
        if self.coproc is None:
            return b""
        return self.coproc.query(payload, self.space)

    @property
    def is_leader(self) -> bool:
        from ..raft.node import Role
        return self.raft.role == Role.LEADER
