"""Multi-range KV store + boundary router + split (≈ base-kv elasticity).

``KVRangeStore`` hosts many ``ReplicatedKVRange`` replicas on one node
(≈ base-kv-store-server KVRangeStore.java:101 hosting KVRangeFSMs) and
executes BOTH halves of the reference's split/merge state machine
(KVRangeFSM.java:164 — the SURVEY §7 hard part):

- every range owns a key *boundary* ``[start, end)`` (None end = +inf) and
  its own raft group (per-range member ids ``node:range``);
- a split is a raft entry on the parent range; applying it is
  deterministic on every replica: keys ≥ split_key move to a freshly
  created sibling range (new space, new raft group seeded with identical
  FSM state — a snapshot at index 0), boundaries shrink/attach, and the
  coprocs reset to rebuild derived state;
- a merge is the two-phase seal → merge-commit handshake (see
  ``KVRangeStore.merge``): the mergee freezes at a log position, its
  sealed content ships inside the survivor's merge entry, and every
  replica retires its local mergee deterministically;
- ``KVRangeRouter`` is the client-side boundary map
  (≈ base-kv-store-client's NavigableMap<Boundary, KVRangeSetting>
  ``latestEffectiveRouter``): find_by_key / intersecting.

Range metadata (id → boundary) persists in a store-meta space so a durable
store reloads its range set on restart.
"""

from __future__ import annotations

import json
import struct
from typing import Callable, Dict, List, Optional, Tuple

from .engine import IKVEngine, IKVSpace
from .range import IKVRangeCoProc, ReplicatedKVRange

Boundary = Tuple[bytes, Optional[bytes]]   # [start, end); end None = +inf


def _intersects(b: Boundary, start: bytes, end: Optional[bytes]) -> bool:
    bs, be = b
    if be is not None and be <= start:
        return False
    if end is not None and bs >= end:
        return False
    return True


class KVRangeRouter:
    """Boundary-sorted range lookup (client-side router analog)."""

    def __init__(self) -> None:
        self._ranges: List[Tuple[Boundary, str]] = []  # sorted by start

    def update(self, range_id: str, boundary: Boundary) -> None:
        self._ranges = [(b, r) for b, r in self._ranges if r != range_id]
        self._ranges.append((boundary, range_id))
        self._ranges.sort(key=lambda x: x[0][0])

    def remove(self, range_id: str) -> None:
        self._ranges = [(b, r) for b, r in self._ranges if r != range_id]

    def find_by_key(self, key: bytes) -> Optional[str]:
        for (start, end), rid in self._ranges:
            if key >= start and (end is None or key < end):
                return rid
        return None

    def intersecting(self, start: bytes,
                     end: Optional[bytes]) -> List[str]:
        return [rid for b, rid in self._ranges if _intersects(b, start, end)]

    def ranges(self) -> List[Tuple[Boundary, str]]:
        return list(self._ranges)


_META_RANGES = b"ranges"


class KVRangeStore:
    """Hosts this node's range replicas over one engine + one transport."""

    def __init__(self, node_id: str, transport, engine: IKVEngine,
                 coproc_factory: Callable[[str], IKVRangeCoProc], *,
                 member_nodes: Optional[List[str]] = None,
                 raft_store_factory=None,
                 space_prefix: str = "",
                 legacy_space: Optional[str] = None) -> None:
        self.node_id = node_id
        self.transport = transport
        self.engine = engine
        self.coproc_factory = coproc_factory
        self.member_nodes = member_nodes or [node_id]
        self.raft_store_factory = raft_store_factory
        # namespaces this store's engine spaces so several KVRangeStores
        # (dist routes, inbox, retain) can share one durable engine
        self.space_prefix = space_prefix
        # this store's OWN pre-multi-range flat space, migrated into
        # genesis on first open (each store names only its own — a shared
        # engine must never let one store's bootstrap steal another's)
        self.legacy_space = legacy_space
        self.ranges: Dict[str, ReplicatedKVRange] = {}
        self.coprocs: Dict[str, IKVRangeCoProc] = {}
        self.boundaries: Dict[str, Boundary] = {}
        self.router = KVRangeRouter()
        self._meta = engine.create_space(f"{space_prefix}store_meta")
        self._split_seq = 0

    # ---------------- lifecycle -------------------------------------------

    def open(self, *, bootstrap: bool = True) -> None:
        """Load existing ranges from the meta space, or bootstrap genesis
        (≈ KVRangeStore.start loading IKVSpaces + RangeBootstrapBalancer).
        ``bootstrap=False`` joins an existing cluster empty: replicas
        arrive via ensure_range placement, never a competing genesis."""
        raw = self._meta.get_metadata(_META_RANGES)
        if raw:
            for rec in json.loads(raw.decode()):
                self._open_range(
                    rec["id"],
                    (bytes.fromhex(rec["start"]),
                     bytes.fromhex(rec["end"]) if rec["end"] else None),
                    voters=rec.get("voters"),
                    learners=rec.get("learners"))
        elif not bootstrap:
            return
        else:
            genesis = self._open_range("r0", (b"", None))
            # one-time migration from the pre-multi-range layout: this
            # store's keyspace persisted in a flat legacy space moves
            # into genesis
            if self.legacy_space:
                legacy = self.engine.create_space(self.legacy_space)
                moved = 0
                w = genesis.space.writer()
                for k, v in legacy.iterate():
                    w.put(k, v)
                    moved += 1
                w.done()
                if moved:
                    legacy.writer().delete_range(b"",
                                                 b"\xff" * 48).done()
                    self.coprocs["r0"].reset(genesis.space)
            self._persist_meta()

    def _persist_meta(self) -> None:
        recs = [{"id": rid, "start": b[0].hex(),
                 "end": b[1].hex() if b[1] is not None else None,
                 "voters": sorted(self.ranges[rid].raft.voters),
                 "learners": sorted(self.ranges[rid].raft.learners)}
                for rid, b in self.boundaries.items()]
        self._meta.put_metadata(_META_RANGES,
                                json.dumps(sorted(recs,
                                                  key=lambda r: r["id"])
                                           ).encode())

    def _open_range(self, range_id: str, boundary: Boundary, *,
                    voters: Optional[List[str]] = None,
                    learners: Optional[List[str]] = None
                    ) -> ReplicatedKVRange:
        space = self.engine.create_space(
            f"{self.space_prefix}range_{range_id}")
        coproc = self.coproc_factory(range_id)
        raft_store = (self.raft_store_factory(range_id)
                      if self.raft_store_factory else None)
        member_id = f"{self.node_id}:{range_id}"
        if voters is None:
            voters = [f"{n}:{range_id}" for n in self.member_nodes]
        r = ReplicatedKVRange(range_id, member_id, voters, self.transport,
                              space, coproc=coproc, raft_store=raft_store,
                              learners=learners)
        r.on_split = lambda split_key, rid=range_id: self._apply_split(
            rid, split_key)
        r.on_seal = lambda sealed, rid=range_id: self._apply_seal(
            rid, sealed)
        r.on_merge = lambda payload, rid=range_id: self._apply_merge(
            rid, payload)
        if hasattr(self.transport, "register"):
            self.transport.register(r.raft)
        self.ranges[range_id] = r
        self.coprocs[range_id] = coproc
        self.boundaries[range_id] = boundary
        self.router.update(range_id, boundary)
        if hasattr(coproc, "boundary"):
            coproc.boundary = boundary
        if space.get_metadata(b"sealed") == b"\x01":
            # a crash between seal and merge-commit must not forget the
            # seal on this replica while others still enforce it
            r.sealed = True
            self._apply_seal(range_id, True)
        coproc.reset(space)
        return r

    def tick(self) -> None:
        for r in self.ranges.values():
            r.raft.tick()

    def retire_replica(self, range_id: str) -> None:
        """Zombie-quit execution (the DECISION lives in BaseKVStoreServer,
        which corroborates the local exclusion against the landscape's
        current leader — an appended-but-never-committed config entry must
        not destroy replica state)."""
        self._retire_range(range_id)
        self._persist_meta()

    def stop(self) -> None:
        for r in self.ranges.values():
            r.raft.stop()

    # ---------------- routing ---------------------------------------------

    def range_for_key(self, key: bytes) -> ReplicatedKVRange:
        rid = self.router.find_by_key(key)
        if rid is None:
            raise KeyError(f"no range covers key {key!r}")
        return self.ranges[rid]

    # ---------------- split (≈ KVRangeFSM split command) -------------------

    async def split(self, range_id: str, split_key: bytes) -> str:
        """Propose a split of ``range_id`` at ``split_key``; resolves with
        the new sibling's id after the split applies on this replica."""
        from .range import propose_with_leader_wait

        r = self.ranges[range_id]
        start, end = self.boundaries[range_id]
        if not (split_key > start and (end is None or split_key < end)):
            raise ValueError("split key outside boundary")
        await propose_with_leader_wait(r,
                                       lambda: r.propose_split(split_key))
        # the apply hook (this replica) created the sibling synchronously
        return self._sibling_id(range_id, split_key)

    def _sibling_id(self, parent: str, split_key: bytes) -> str:
        # hash the WHOLE key: route keys share long tenant prefixes, so a
        # key-prefix id would collide across different split points (and the
        # replay guard would silently swallow real splits)
        import hashlib
        digest = hashlib.blake2b(split_key, digest_size=6).hexdigest()
        return f"{parent}.{digest}"

    def _apply_split(self, range_id: str, split_key: bytes) -> None:
        """Runs inside the raft apply of the split entry — on EVERY replica,
        at the same log position, so the state transfer is deterministic."""
        parent = self.ranges[range_id]
        start, end = self.boundaries[range_id]
        sibling_id = self._sibling_id(range_id, split_key)
        if sibling_id in self.ranges:
            return  # replayed entry (restart); already split
        sib_space = self.engine.create_space(
            f"{self.space_prefix}range_{sibling_id}")
        # move [split_key, end) into the sibling space
        w = sib_space.writer()
        moved = 0
        for k, v in parent.space.iterate(split_key, end):
            w.put(k, v)
            moved += 1
        w.done()
        parent.space.writer().delete_range(
            split_key, end if end is not None else b"\xff" * 48).done()
        # shrink parent, open sibling
        self.boundaries[range_id] = (start, split_key)
        self.router.update(range_id, (start, split_key))
        if hasattr(self.coprocs[range_id], "boundary"):
            self.coprocs[range_id].boundary = (start, split_key)
        coproc = self.coproc_factory(sibling_id)
        raft_store = (self.raft_store_factory(sibling_id)
                      if self.raft_store_factory else None)
        member_id = f"{self.node_id}:{sibling_id}"
        # the sibling inherits the PARENT's replica placement (its current
        # voter-node set), not the store's static template — dynamically
        # placed ranges keep their placement through splits
        parent_nodes = sorted({v.split(":", 1)[0]
                               for v in parent.raft.voters})
        voters = [f"{n}:{sibling_id}" for n in parent_nodes]
        sib = ReplicatedKVRange(sibling_id, member_id, voters,
                                self.transport, sib_space, coproc=coproc,
                                raft_store=raft_store)
        sib.on_split = lambda sk, rid=sibling_id: self._apply_split(rid, sk)
        sib.on_seal = lambda sealed, rid=sibling_id: self._apply_seal(
            rid, sealed)
        sib.on_merge = lambda payload, rid=sibling_id: self._apply_merge(
            rid, payload)
        if hasattr(self.transport, "register"):
            self.transport.register(sib.raft)
        self.ranges[sibling_id] = sib
        self.coprocs[sibling_id] = coproc
        self.boundaries[sibling_id] = (split_key, end)
        self.router.update(sibling_id, (split_key, end))
        if hasattr(coproc, "boundary"):
            coproc.boundary = (split_key, end)
        if parent_nodes == [self.node_id]:
            # sole-voter range: elect the new group synchronously so the
            # sibling serves immediately after the split applies
            from ..raft.node import Role
            for _ in range(200):
                if sib.raft.role == Role.LEADER:
                    break
                sib.raft.tick()
        # derived state rebuilds from the moved keyspaces
        self.coprocs[range_id].reset(parent.space)
        coproc.reset(sib_space)
        self._persist_meta()

    # ---------------- merge (≈ KVRangeFSM dual-range merge handshake) ------

    async def merge(self, left_id: str, right_id: str) -> None:
        """Merge the adjacent range ``right_id`` into ``left_id``.

        Two-phase, mirroring the reference's PrepareMerge/Merge handshake
        (KVRangeFSM.java:164 — the hard part SURVEY §7 names):

        1. a SEAL entry commits on the mergee: from its apply position no
           mutation can change the space, so every replica that applied it
           holds identical content;
        2. the sealed content ships inside a MERGE entry on the survivor:
           applying it is deterministic on every replica regardless of the
           local mergee replica's progress — write the data, extend the
           boundary, retire the local mergee replica.

        Between seal and merge-apply, mutations on the mergee's keys bounce
        (``b"retry"``) and re-resolve; once the router flips they land on
        the survivor (brief unavailability, as in the reference).
        """
        from .range import propose_with_leader_wait

        ls, le = self.boundaries[left_id]
        rs, re_ = self.boundaries[right_id]
        if le != rs:
            raise ValueError("ranges not adjacent")
        right = self.ranges[right_id]

        await propose_with_leader_wait(right, right.propose_seal)
        # the seal applied locally (propose resolves at apply): the local
        # mergee content is now the canonical sealed state
        payload = bytearray()
        payload += struct.pack(">H", len(right_id.encode()))
        payload += right_id.encode()
        payload += struct.pack(">H", len(re_ or b"\xff"))
        payload += b"\x01" if re_ is not None else b"\x00"
        payload += re_ if re_ is not None else b""
        body = bytearray()
        for k, v in right.space.iterate():
            body += struct.pack(">I", len(k)) + k
            body += struct.pack(">I", len(v)) + v
        payload += struct.pack(">Q", len(body)) + body
        left = self.ranges[left_id]
        try:
            await propose_with_leader_wait(
                left, lambda: left.propose_merge(bytes(payload)))
        except BaseException:
            # phase 2 failed: roll the seal back so the mergee's keyspan
            # does not stay write-unavailable
            try:
                await propose_with_leader_wait(
                    right, lambda: right.propose_seal(False))
            except BaseException:  # noqa: BLE001 — surface the original
                pass
            raise

    def _apply_seal(self, range_id: str, sealed: bool) -> None:
        coproc = self.coprocs.get(range_id)
        rng = self.ranges.get(range_id)
        if rng is not None:
            # durable so a restarted replica re-enforces the seal (the
            # applied-index watermark may already cover the seal entry)
            rng.space.put_metadata(b"sealed",
                                   b"\x01" if sealed else b"\x00")
        if coproc is not None and hasattr(coproc, "boundary"):
            start, end = self.boundaries[range_id]
            # sealed = empty boundary: every mutation bounces for
            # re-resolution; unsealed restores the real boundary
            coproc.boundary = (start, start) if sealed else (start, end)

    def _apply_merge(self, left_id: str, payload: bytes) -> None:
        (n,) = struct.unpack_from(">H", payload, 0)
        pos = 2
        right_id = payload[pos:pos + n].decode()
        pos += n
        (_elen,) = struct.unpack_from(">H", payload, pos)
        pos += 2
        has_end = payload[pos] == 1
        pos += 1
        new_end = None
        if has_end:
            new_end = payload[pos:pos + _elen]
            pos += _elen
        (blen,) = struct.unpack_from(">Q", payload, pos)
        pos += 8
        body = payload[pos:pos + blen]
        left = self.ranges[left_id]
        # fold the sealed content into the survivor
        w = left.space.writer()
        bpos = 0
        while bpos < len(body):
            (klen,) = struct.unpack_from(">I", body, bpos)
            bpos += 4
            k = body[bpos:bpos + klen]
            bpos += klen
            (vlen,) = struct.unpack_from(">I", body, bpos)
            bpos += 4
            w.put(k, body[bpos:bpos + vlen])
            bpos += vlen
        w.done()
        start, _ = self.boundaries[left_id]
        self.boundaries[left_id] = (start, new_end)
        self.router.update(left_id, (start, new_end))
        if hasattr(self.coprocs[left_id], "boundary"):
            self.coprocs[left_id].boundary = (start, new_end)
        self.coprocs[left_id].reset(left.space)
        # retire the local mergee replica (it may lag; its data is already
        # canonical inside this entry)
        self._retire_range(right_id)
        self._persist_meta()

    def _retire_range(self, range_id: str) -> None:
        r = self.ranges.pop(range_id, None)
        if r is None:
            return
        r.raft.stop()
        self.coprocs.pop(range_id, None)
        self.boundaries.pop(range_id, None)
        self.router.remove(range_id)
        # destroy ALL traces: data + metadata (applied watermark, seal) and
        # the per-range raft store — a later split reusing the same
        # deterministic sibling id must start from genuinely empty state
        r.space.destroy()
        if self.raft_store_factory is not None:
            try:
                self.raft_store_factory(range_id).clear()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                import logging
                logging.getLogger(__name__).exception(
                    "failed to clear raft store for %s", range_id)

    # ---------------- placement / recovery ---------------------------------

    def ensure_range(self, range_id: str, boundary: Boundary,
                     voter_nodes: List[str],
                     learner_nodes: Optional[List[str]] = None
                     ) -> ReplicatedKVRange:
        """Open a replica shell for ``range_id`` on this store (the target
        half of replica placement: a balancer adds this store to the
        range's config, then the leader catches the shell up via appends or
        a snapshot dump session)."""
        r = self.ranges.get(range_id)
        if r is not None:
            return r
        voters = [f"{n}:{range_id}" for n in sorted(voter_nodes)]
        learners = [f"{n}:{range_id}" for n in sorted(learner_nodes or [])]
        r = self._open_range(range_id, boundary, voters=voters,
                             learners=learners)
        self._persist_meta()
        return r

    def recover(self, range_id: str,
                live_nodes: Optional[List[str]] = None) -> None:
        """Quorum-loss recovery: force this range's config down to the
        known-live nodes (default: just this store). See RaftNode.recover
        for the safety caveat."""
        nodes = live_nodes or [self.node_id]
        self.ranges[range_id].raft.recover(
            [f"{n}:{range_id}" for n in nodes])
        self._persist_meta()

    # ---------------- introspection ---------------------------------------

    def describe(self) -> List[dict]:
        out = []
        for rid, r in sorted(self.ranges.items()):
            s, e = self.boundaries[rid]
            out.append({"id": rid, "start": s.hex(),
                        "end": e.hex() if e is not None else None,
                        "keys": len(r.space),
                        "leader": r.is_leader})
        return out
