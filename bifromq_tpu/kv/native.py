"""Native (C++) KV engine binding — the durable engine behind the SPI.

Fills the role RocksDB fills in the reference (data + WAL engines of
base-kv; SURVEY.md §2.9 "our equivalent: C++ behind the same KVSpace SPI"):
ordered memtable + append-only WAL with fsync + full-dump checkpoints, with
crash recovery on open (checkpoint load + WAL replay).

The shared library builds on first use with the baked-in g++ (no pybind11 —
plain C ABI + ctypes) and is cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Iterator, Optional, Tuple

from .engine import (IKVEngine, IKVSpace, IKVSpaceCheckpoint, KVWriteBatch)

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "kvengine.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libkvengine.so")

_lib = None
_lib_lock = threading.Lock()


def load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ..utils.nativelib import compile_and_load
        lib = compile_and_load(_SRC, _SO)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_space.restype = ctypes.c_void_p
        lib.kv_space.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.kv_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.kv_del_range.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_char_p),
                               ctypes.POINTER(ctypes.c_int)]
        lib.kv_free.argtypes = [ctypes.c_char_p]
        lib.kv_count.restype = ctypes.c_uint64
        lib.kv_count.argtypes = [ctypes.c_void_p]
        lib.kv_flush.argtypes = [ctypes.c_void_p]
        lib.kv_checkpoint.argtypes = [ctypes.c_void_p]
        lib.kv_set_sync.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.kv_commit.argtypes = [ctypes.c_void_p]
        lib.kv_wal_bytes.restype = ctypes.c_uint64
        lib.kv_wal_bytes.argtypes = [ctypes.c_void_p]
        lib.kv_iter.restype = ctypes.c_void_p
        lib.kv_iter.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_int]
        lib.kv_iter_valid.argtypes = [ctypes.c_void_p]
        lib.kv_iter_key.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.POINTER(ctypes.c_int)]
        lib.kv_iter_value.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_char_p),
                                      ctypes.POINTER(ctypes.c_int)]
        lib.kv_iter_next.argtypes = [ctypes.c_void_p]
        lib.kv_iter_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeKVSpace(IKVSpace):
    def __init__(self, engine: "NativeKVEngine", name: str,
                 handle: int) -> None:
        self.name = name
        self._engine = engine
        self._h = handle
        self._lib = engine._lib

    def get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.c_char_p()
        outlen = ctypes.c_int()
        if not self._lib.kv_get(self._h, key, len(key),
                                ctypes.byref(out), ctypes.byref(outlen)):
            return None
        # ctypes c_char_p.value stops at NUL; use string_at for binary safety
        raw = ctypes.string_at(out, outlen.value)
        self._lib.kv_free(out)
        return raw

    def iterate(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        it = self._lib.kv_iter(
            self._h, start or b"", len(start) if start is not None else -1,
            end or b"", len(end) if end is not None else -1, int(reverse))
        try:
            k = ctypes.c_char_p()
            klen = ctypes.c_int()
            v = ctypes.c_char_p()
            vlen = ctypes.c_int()
            while self._lib.kv_iter_valid(it):
                self._lib.kv_iter_key(it, ctypes.byref(k),
                                      ctypes.byref(klen))
                self._lib.kv_iter_value(it, ctypes.byref(v),
                                        ctypes.byref(vlen))
                yield (ctypes.string_at(k, klen.value),
                       ctypes.string_at(v, vlen.value))
                self._lib.kv_iter_next(it)
        finally:
            self._lib.kv_iter_close(it)

    def size(self, start: Optional[bytes] = None,
             end: Optional[bytes] = None) -> int:
        return sum(len(k) + len(v) for k, v in self.iterate(start, end))

    def checkpoint(self) -> IKVSpaceCheckpoint:
        # durability checkpoint + an in-memory read snapshot for callers
        self._lib.kv_checkpoint(self._h)
        snap = dict(self.iterate())
        return _NativeCheckpoint(snap)

    def flush(self) -> None:
        self._lib.kv_flush(self._h)

    def set_sync(self, fsync_on_commit: bool) -> None:
        """Toggle fsync-on-commit (the WALable SPI's sync contract); the
        default flushes each batch commit to the OS page cache, which
        survives a process crash but not power loss."""
        self._lib.kv_set_sync(self._h, int(fsync_on_commit))

    @property
    def wal_bytes(self) -> int:
        return self._lib.kv_wal_bytes(self._h)

    def destroy(self) -> None:
        self._apply([("del_range", b"", b"\xff" * 32)])

    def get_metadata(self, key: bytes) -> Optional[bytes]:
        return self.get(b"\xfeMETA" + key)

    def put_metadata(self, key: bytes, value: bytes) -> None:
        self._lib.kv_put(self._h, b"\xfeMETA" + key, len(key) + 5,
                         value, len(value))
        self._lib.kv_commit(self._h)

    def _apply(self, ops) -> None:
        for op, a, b in ops:
            if op == "put":
                self._lib.kv_put(self._h, a, len(a), b, len(b))
            elif op == "del":
                self._lib.kv_del(self._h, a, len(a))
            else:
                self._lib.kv_del_range(self._h, a, len(a), b, len(b))
        # group-commit barrier: the batch is acknowledged once it reaches the
        # kernel (or the platter, with set_sync(True))
        self._lib.kv_commit(self._h)

    def __len__(self) -> int:
        return int(self._lib.kv_count(self._h))


class _NativeCheckpoint(IKVSpaceCheckpoint):
    def __init__(self, snap: Dict[bytes, bytes]) -> None:
        self._snap = snap
        self._keys = sorted(snap)

    def iterate(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None):
        import bisect
        lo = 0 if start is None else bisect.bisect_left(self._keys, start)
        hi = (len(self._keys) if end is None
              else bisect.bisect_left(self._keys, end))
        for k in self._keys[lo:hi]:
            yield k, self._snap[k]

    def get(self, key: bytes) -> Optional[bytes]:
        return self._snap.get(key)


class NativeKVEngine(IKVEngine):
    """Durable engine rooted at ``dir``; spaces persist across restarts."""

    def __init__(self, dir: str) -> None:
        self.dir = dir
        self._lib = load_lib()
        self._eng = self._lib.kv_open(dir.encode())
        self._spaces: Dict[str, NativeKVSpace] = {}

    def create_space(self, name: str) -> IKVSpace:
        sp = self._spaces.get(name)
        if sp is None:
            h = self._lib.kv_space(self._eng, name.encode())
            sp = NativeKVSpace(self, name, h)
            self._spaces[name] = sp
        return sp

    def get_space(self, name: str) -> Optional[IKVSpace]:
        return self._spaces.get(name)

    def spaces(self) -> Dict[str, IKVSpace]:
        return dict(self._spaces)

    def close(self) -> None:
        if self._eng is not None:
            self._lib.kv_close(self._eng)
            self._eng = None
            self._spaces.clear()
