"""bifromq_tpu.kv — storage engine (analog of base-kv local engines + schemas)."""
