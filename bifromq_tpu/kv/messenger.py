"""Store messenger: raft traffic between KV stores over the RPC fabric.

Re-expression of the reference's AgentHostStoreMessenger
(base-kv/base-kv-store-server .../server/AgentHostStoreMessenger.java:41):
every store process hosts one messenger; raft messages (and snapshot dump
chunks) addressed to ``node:range`` member ids are batched per destination
store and shipped as one RPC frame; the receiving messenger fans them out
to its local raft nodes. Messages to members on THIS store short-circuit
in-process (the reference's local agent delivery).

Raft tolerates message loss by design, so delivery is fire-and-forget: an
unreachable peer's batch is dropped and heartbeat retransmission repairs
the gap once the peer returns — no queue grows without bound
(``MAX_BACKLOG`` per peer).
"""

from __future__ import annotations

import asyncio
import logging
import struct
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..raft.node import ITransport, RaftNode
from ..raft.wire import decode_msg, encode_msg
from ..rpc.fabric import RPCServer, ServiceRegistry, _len16, _read16

log = logging.getLogger(__name__)

SERVICE_PREFIX = "basekv-store"


def node_of(member_id: str) -> str:
    """``node:range`` member id → hosting store/node name."""
    return member_id.split(":", 1)[0]


class StoreMessenger(ITransport):
    """One per store process; shared by every hosted raft group."""

    MAX_BACKLOG = 4096          # queued messages per destination store
    CALL_TIMEOUT = 10.0         # snapshot chunks can be sizeable

    def __init__(self, node_id: str, registry: ServiceRegistry, *,
                 cluster: str = "dist") -> None:
        self.node_id = node_id
        self.registry = registry
        self.cluster = cluster
        self.service = f"{SERVICE_PREFIX}:{cluster}"
        self._local: Dict[str, RaftNode] = {}
        self._outbox: Dict[str, Deque[Tuple[str, str, bytes]]] = {}
        self._wakes: Dict[str, asyncio.Event] = {}
        self._senders: Dict[str, asyncio.Task] = {}
        self._running = False
        self.dropped = 0
        self.sent_batches = 0

    # ---------------- ITransport -------------------------------------------

    def register(self, node: RaftNode) -> None:
        self._local[node.id] = node

    def unregister(self, member_id: str) -> None:
        self._local.pop(member_id, None)

    def send(self, to: str, sender: str, msg) -> None:
        dest = node_of(to)
        if dest == self.node_id or to in self._local:
            # in-proc bypass — schedule (not inline) so a reply can't
            # re-enter the sending node mid-update
            try:
                asyncio.get_running_loop().call_soon(
                    self._deliver_local, to, sender, msg)
            except RuntimeError:    # no loop (sync test tick): inline
                self._deliver_local(to, sender, msg)
            return
        q = self._outbox.setdefault(dest, deque(maxlen=self.MAX_BACKLOG))
        if len(q) == q.maxlen:
            self.dropped += 1
        q.append((to, sender, encode_msg(msg)))
        if self._running:
            self._ensure_sender(dest).set()

    def _deliver_local(self, to: str, sender: str, msg) -> None:
        node = self._local.get(to)
        if node is not None:
            node.receive(sender, msg)

    # ---------------- server side ------------------------------------------

    def attach(self, server: RPCServer) -> None:
        server.register(self.service, {"raft_batch": self._on_batch})

    async def _on_batch(self, payload: bytes, _okey: str) -> bytes:
        (n,) = struct.unpack_from(">I", payload, 0)
        pos = 4
        for _ in range(n):
            to_b, pos = _read16(payload, pos)
            sender_b, pos = _read16(payload, pos)
            (mlen,) = struct.unpack_from(">I", payload, pos)
            pos += 4
            raw = payload[pos:pos + mlen]
            pos += mlen
            node = self._local.get(to_b.decode())
            if node is not None:        # unknown member: retired range; drop
                node.receive(sender_b.decode(), decode_msg(raw))
        return b""

    # ---------------- flush loop -------------------------------------------

    async def start(self) -> None:
        self._running = True
        for dest, q in self._outbox.items():
            if q:
                self._ensure_sender(dest).set()

    async def stop(self) -> None:
        self._running = False
        for t in self._senders.values():
            t.cancel()
        self._senders.clear()
        self._wakes.clear()

    def address_of(self, dest_node: str) -> Optional[str]:
        eps = self.registry.endpoints(f"{self.service}:{dest_node}")
        return eps[0] if eps else None

    def _ensure_sender(self, dest: str) -> asyncio.Event:
        ev = self._wakes.get(dest)
        if ev is None:
            ev = self._wakes[dest] = asyncio.Event()
            # one sender per destination: a blackholed peer (slow TCP
            # connect) must not stall heartbeats to healthy peers
            self._senders[dest] = asyncio.create_task(
                self._sender_loop(dest, ev))
        return ev

    async def _sender_loop(self, dest: str, wake: asyncio.Event) -> None:
        while True:
            await wake.wait()
            wake.clear()
            q = self._outbox.get(dest)
            if not q:
                continue
            batch = list(q)
            q.clear()
            # wait_for bounds the WHOLE ship — including connection
            # establishment, which RPCClient.call does before its own
            # timeout applies
            try:
                await asyncio.wait_for(self._ship(dest, batch),
                                       self.CALL_TIMEOUT)
            except asyncio.TimeoutError:
                self.dropped += len(batch)

    async def _ship(self, dest: str, batch) -> None:
        addr = self.address_of(dest)
        if addr is None:
            self.dropped += len(batch)
            return
        body = bytearray(struct.pack(">I", len(batch)))
        for to, sender, raw in batch:
            body += _len16(to.encode()) + _len16(sender.encode())
            body += struct.pack(">I", len(raw)) + raw
        try:
            await self.registry.client_for(addr).call(
                self.service, "raft_batch", bytes(body),
                timeout=self.CALL_TIMEOUT)
            self.sent_batches += 1
        except Exception:  # noqa: BLE001 — unreachable peer: drop, raft heals
            self.dropped += len(batch)
