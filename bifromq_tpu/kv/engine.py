"""KV engine SPI + in-memory engine (≈ base-kv-local-engine-spi / -memory).

Reference shape: ``IKVEngine`` owns named ``IKVSpace``s (one per range;
column-family-per-space in the RocksDB engine), each with point reads, range
iteration over byte-ordered keys, batched writes, metadata, and either
checkpoints (ICPableKVSpace) or WAL fsync (IWALableKVSpace) — see
base-kv/base-kv-local-engine-spi .../localengine/IKVEngine.java, IKVSpace.java,
ICPableKVSpace.java.

The in-memory engine (≈ localengine/memory/InMemKVEngine.java) is the
default for tests and the WAL engine; a native C++ engine can plug in behind
the same SPI.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Tuple


class KVWriteBatch:
    """Atomic multi-op write (≈ IKVSpaceWriter)."""

    def __init__(self, space: "IKVSpace") -> None:
        self._space = space
        self._ops: List[Tuple[str, bytes, Optional[bytes]]] = []

    def put(self, key: bytes, value: bytes) -> "KVWriteBatch":
        self._ops.append(("put", key, value))
        return self

    def delete(self, key: bytes) -> "KVWriteBatch":
        self._ops.append(("del", key, None))
        return self

    def delete_range(self, start: bytes, end: bytes) -> "KVWriteBatch":
        self._ops.append(("del_range", start, end))
        return self

    def done(self) -> None:
        self._space._apply(self._ops)
        self._ops = []


class IKVSpace:
    """One named keyspace (≈ IKVSpace): byte-ordered, range-iterable."""

    name: str

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        """Yield (key, value) with start <= key < end in byte order."""
        raise NotImplementedError

    def writer(self) -> KVWriteBatch:
        return KVWriteBatch(self)

    def size(self, start: Optional[bytes] = None,
             end: Optional[bytes] = None) -> int:
        """Approximate byte size of the range (used by split hinters)."""
        raise NotImplementedError

    def checkpoint(self) -> "IKVSpaceCheckpoint":
        raise NotImplementedError

    def destroy(self) -> None:
        raise NotImplementedError

    # metadata (≈ IKVSpace.metadata(): small control records, e.g. range
    # boundary + raft state, kept separate from data keys)
    def get_metadata(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put_metadata(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def _apply(self, ops) -> None:
        raise NotImplementedError


class IKVSpaceCheckpoint:
    """Read-only snapshot of a space (≈ IKVSpaceCheckpoint / RocksDB ckpt)."""

    def iterate(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class IKVEngine:
    """Engine = a collection of named spaces (≈ IKVEngine)."""

    def create_space(self, name: str) -> IKVSpace:
        raise NotImplementedError

    def get_space(self, name: str) -> Optional[IKVSpace]:
        raise NotImplementedError

    def spaces(self) -> Dict[str, IKVSpace]:
        raise NotImplementedError

    def close(self) -> None:
        pass


# --------------------------- in-memory engine -------------------------------

class _SortedBytesMap:
    """Sorted byte-key map: dict + bisect-maintained key list.

    Writes are O(n) worst case on inserts of new keys; reads and range scans
    are O(log n + k). Fine for tests and WAL duty; the native engine covers
    write-heavy data spaces.
    """

    def __init__(self) -> None:
        self._keys: List[bytes] = []
        self._map: Dict[bytes, bytes] = {}

    def put(self, key: bytes, value: bytes) -> None:
        if key not in self._map:
            bisect.insort(self._keys, key)
        self._map[key] = value

    def delete(self, key: bytes) -> None:
        if key in self._map:
            del self._map[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]

    def delete_range(self, start: bytes, end: bytes) -> None:
        lo = bisect.bisect_left(self._keys, start)
        hi = bisect.bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            del self._map[k]
        del self._keys[lo:hi]

    def get(self, key: bytes) -> Optional[bytes]:
        return self._map.get(key)

    def scan(self, start: Optional[bytes], end: Optional[bytes],
             reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        lo = 0 if start is None else bisect.bisect_left(self._keys, start)
        hi = len(self._keys) if end is None else bisect.bisect_left(
            self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = reversed(keys)
        for k in keys:
            yield k, self._map[k]

    def copy(self) -> "_SortedBytesMap":
        c = _SortedBytesMap()
        c._keys = list(self._keys)
        c._map = dict(self._map)
        return c

    def __len__(self) -> int:
        return len(self._keys)


class InMemKVSpace(IKVSpace):
    def __init__(self, engine: "InMemKVEngine", name: str) -> None:
        self.name = name
        self._engine = engine
        self._data = _SortedBytesMap()
        self._meta: Dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def iterate(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            yield from list(self._data.scan(start, end, reverse))

    def size(self, start: Optional[bytes] = None,
             end: Optional[bytes] = None) -> int:
        with self._lock:
            return sum(len(k) + len(v)
                       for k, v in self._data.scan(start, end))

    def checkpoint(self) -> IKVSpaceCheckpoint:
        with self._lock:
            return _InMemCheckpoint(self._data.copy())

    def destroy(self) -> None:
        self._engine._drop(self.name)

    def get_metadata(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._meta.get(key)

    def put_metadata(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._meta[key] = value

    def _apply(self, ops) -> None:
        with self._lock:
            for op, a, b in ops:
                if op == "put":
                    self._data.put(a, b)
                elif op == "del":
                    self._data.delete(a)
                elif op == "del_range":
                    self._data.delete_range(a, b)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)


class _InMemCheckpoint(IKVSpaceCheckpoint):
    def __init__(self, snapshot: _SortedBytesMap) -> None:
        self._snap = snapshot

    def iterate(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None) -> Iterator[Tuple[bytes, bytes]]:
        yield from self._snap.scan(start, end)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._snap.get(key)


class InMemKVEngine(IKVEngine):
    def __init__(self) -> None:
        self._spaces: Dict[str, InMemKVSpace] = {}
        self._lock = threading.Lock()

    def create_space(self, name: str) -> IKVSpace:
        with self._lock:
            sp = self._spaces.get(name)
            if sp is None:
                sp = InMemKVSpace(self, name)
                self._spaces[name] = sp
            return sp

    def get_space(self, name: str) -> Optional[IKVSpace]:
        return self._spaces.get(name)

    def spaces(self) -> Dict[str, IKVSpace]:
        return dict(self._spaces)

    def _drop(self, name: str) -> None:
        with self._lock:
            self._spaces.pop(name, None)
