"""Per-key load recording + load-hinted splits.

≈ base-kv-store-server's KVLoadRecorder (KVLoadRecorder.java:28, attached
to readers/writers via LoadRecordableKVReader) feeding split hinters
(KVLoadBasedSplitHinter, and bifromq-dist's FanoutSplitHinter.java:49
which weighs a query by its fan-out). Re-expressed host-side: coprocs
record (key, cost) samples into their range's recorder; the balancer
reads windowed totals and splits hot ranges at the load-weighted median
key instead of the key-count median.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class KVLoadRecorder:
    """Windowed (key → accumulated cost) samples for one range."""

    def __init__(self, *, clock=time.monotonic,
                 max_tracked_keys: int = 4096) -> None:
        self.clock = clock
        self.max_tracked_keys = max_tracked_keys
        self._samples: Dict[bytes, int] = {}
        self.window_start = clock()
        self.total = 0
        self.dropped = 0

    def record(self, key: bytes, cost: int = 1) -> None:
        self.total += cost
        cur = self._samples.get(key)
        if cur is None and len(self._samples) >= self.max_tracked_keys:
            self.dropped += cost    # bounded memory; totals stay honest
            return
        self._samples[key] = (cur or 0) + cost

    def window(self) -> Tuple[float, int]:
        """(window age seconds, total cost recorded in it)."""
        return self.clock() - self.window_start, self.total

    def load_per_second(self) -> float:
        age, total = self.window()
        return total / age if age > 0 else 0.0

    def hot_split_key(self) -> Optional[bytes]:
        """The load-weighted median key: splitting there puts ~half the
        observed load on each side (≈ KVLoadBasedSplitHinter picking the
        tracked key nearest half the total load)."""
        if not self._samples:
            return None
        items: List[Tuple[bytes, int]] = sorted(self._samples.items())
        half = sum(c for _, c in items) / 2
        acc = 0
        for key, cost in items:
            acc += cost
            if acc >= half:
                return key
        return items[-1][0]

    def reset_window(self) -> None:
        self._samples.clear()
        self.total = 0
        self.dropped = 0
        self.window_start = self.clock()


class LoadSplitBalancer:
    """Split any local leader range whose windowed load rate exceeds
    ``max_load_per_second``, at the recorder's load-median key — the
    fan-out-aware half of elasticity (key-count splits stay in
    RangeSplitBalancer). Coprocs may expose ``align_split_key`` to snap
    the hint onto a record-group boundary (e.g. an inbox prefix)."""

    MIN_WINDOW_SECONDS = 1.0

    def __init__(self, max_load_per_second: float = 10_000.0) -> None:
        self.max_load_per_second = max_load_per_second

    def balance(self, store) -> List:
        from .balance import SplitCommand

        out: List = []
        for rid, r in store.ranges.items():
            if not r.is_leader:
                continue
            coproc = store.coprocs.get(rid)
            rec: Optional[KVLoadRecorder] = getattr(coproc,
                                                    "load_recorder", None)
            if rec is None:
                continue
            age, _total = rec.window()
            if age < self.MIN_WINDOW_SECONDS:
                continue
            rate = rec.load_per_second()
            if rate <= self.max_load_per_second:
                rec.reset_window()
                continue
            key = rec.hot_split_key()
            rec.reset_window()
            if key is None:
                continue
            align = getattr(coproc, "align_split_key", None)
            if align is not None:
                key = align(key)
            start, end = store.boundaries[rid]
            if key is None or not (key > start
                                   and (end is None or key < end)):
                continue    # whole load on one record group: unsplittable
            out.append(SplitCommand(rid, key))
        return out
