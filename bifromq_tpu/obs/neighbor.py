"""Noisy-neighbor detection over the windowed RED state (ISSUE 3, part 2).

Scores every live tenant on the three signals a multi-tenant broker
actually contends on:

- **share of fan-out** — routes delivered on this tenant's behalf as a
  fraction of all delivery work in the window (the fan-out amplifier is
  how one tenant's publish costs everyone else);
- **share of queue-wait** — seconds this tenant's calls spent queued in
  the adaptive batcher, as a fraction of all queue-wait (the direct
  measurement of "who is filling the pipeline");
- **error rate** — errors per flow in the window (a tenant drowning in
  deliver errors/drops is burning retries and inbox space).

``evaluate()`` ranks tenants by the blended score, flags offenders
(``noisy`` when the blended share crosses the threshold with ≥2 active
tenants; ``slow`` when the tenant's windowed ingest p99 crosses the SLO),
emits ``NOISY_TENANT`` / ``SLOW_TENANT`` through the plugin event stream
(cooldown-limited per tenant), and caches the flag set for the throttler
advisory (`plugin.throttler.SLOAdvisedResourceThrottler` consults it on
the connect/publish guard path).
"""

from __future__ import annotations

import time
import weakref
from typing import Callable, Dict, List, Optional, Set

from ..plugin.events import Event, EventType, IEventCollector
from .slo import TenantSLO


class NoisyNeighborDetector:
    W_FANOUT = 0.4
    W_QUEUE_WAIT = 0.4
    W_ERRORS = 0.2

    # knobs a per-tenant SLO override may carry (ISSUE 5 satellite:
    # closes the "detector weights are constants" follow-up)
    TENANT_KNOBS = frozenset({"noisy_threshold", "slow_p99_ms",
                              "w_fanout", "w_queue_wait", "w_errors"})

    def __init__(self, slo: TenantSLO, *,
                 noisy_threshold: float = 0.5,
                 slow_p99_ms: float = 1000.0,
                 min_rate_per_s: float = 1.0,
                 event_cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.slo = slo
        self.noisy_threshold = noisy_threshold
        self.slow_p99_ms = slow_p99_ms
        # blend weights are runtime-configurable (PUT /obs / broker
        # config); the class constants stay as the documented defaults
        self.w_fanout = self.W_FANOUT
        self.w_queue_wait = self.W_QUEUE_WAIT
        self.w_errors = self.W_ERRORS
        # tenant → {knob: value} overrides (a latency-sensitive tenant
        # can run a tighter slow SLO; a fan-out-heavy-by-design tenant a
        # higher noisy threshold) — consulted per row in _row
        self.tenant_overrides: Dict[str, Dict[str, float]] = {}
        # a tenant must carry real traffic before it can be flagged —
        # shares of a near-empty window are noise, not neighbors
        self.min_rate_per_s = min_rate_per_s
        self.event_cooldown_s = event_cooldown_s
        self._clock = clock
        self._events_ref = None
        self._last_emit: Dict[tuple, float] = {}
        # flag cache for the throttler advisory (refreshed by evaluate())
        self._noisy: Set[str] = set()
        self._flags_at = -1e18
        self._last_rows: List[dict] = []
        self.advisory_ttl_s = 1.0
        # ISSUE 4 satellite: with a background refresh armed
        # (ObsHub.start_advisory_tick), is_noisy skips the lazy TTL
        # evaluation entirely — the guard path is a set probe
        self.tick_armed = False

    # ---------------- per-tenant config (ISSUE 5 satellite) -----------------

    def configure_tenant(self, tenant: str, **knobs: float) -> None:
        """Install (merge) per-tenant SLO knobs. Unknown knob names raise
        ``ValueError`` at the admin boundary — a typo must not silently
        leave the default in force."""
        bad = set(knobs) - self.TENANT_KNOBS
        if bad:
            raise ValueError(f"unknown detector knob(s) {sorted(bad)} "
                             f"(one of {sorted(self.TENANT_KNOBS)})")
        cfg = self.tenant_overrides.setdefault(tenant, {})
        cfg.update({k: float(v) for k, v in knobs.items()})

    def clear_tenant(self, tenant: str) -> None:
        self.tenant_overrides.pop(tenant, None)

    def config_snapshot(self) -> dict:
        """The effective detector config (``GET /obs``)."""
        return {"noisy_threshold": self.noisy_threshold,
                "slow_p99_ms": self.slow_p99_ms,
                "weights": {"fanout": self.w_fanout,
                            "queue_wait": self.w_queue_wait,
                            "errors": self.w_errors},
                "tenant_overrides": {t: dict(c) for t, c
                                     in self.tenant_overrides.items()}}

    def _knob(self, tenant: str, name: str, default: float) -> float:
        cfg = self.tenant_overrides.get(tenant)
        if cfg is None:
            return default
        return cfg.get(name, default)

    # ---------------- scoring ----------------------------------------------

    def _row(self, tenant: str, s: dict, totals: Dict[str, float],
             n_active: int) -> dict:
        """Score one tenant's windowed snapshot into a ranked row, under
        that tenant's effective (default or overridden) knobs."""
        fan_share = (s["fanout_per_s"] * self.slo.window_s
                     / totals["fanout"]) if totals["fanout"] else 0.0
        wait_share = (s["queue_wait_s"] / totals["queue_wait_s"]
                      if totals["queue_wait_s"] else 0.0)
        err = min(1.0, s["error_rate"])
        score = (self._knob(tenant, "w_fanout", self.w_fanout) * fan_share
                 + self._knob(tenant, "w_queue_wait",
                              self.w_queue_wait) * wait_share
                 + self._knob(tenant, "w_errors", self.w_errors) * err)
        flags = []
        eligible = s["rate_per_s"] >= self.min_rate_per_s
        if (eligible and n_active >= 2
                and score >= self._knob(tenant, "noisy_threshold",
                                        self.noisy_threshold)):
            flags.append("noisy")
        ingest_p99 = s["stages"].get("ingest", {}).get("p99_ms", 0.0)
        if eligible and ingest_p99 >= self._knob(tenant, "slow_p99_ms",
                                                 self.slow_p99_ms):
            flags.append("slow")
        return {"tenant": tenant,
                "score": round(score, 4),
                "fanout_share": round(fan_share, 4),
                "queue_wait_share": round(wait_share, 4),
                "flags": flags, **s}

    def evaluate(self, top_k: int = 10, emit: bool = True) -> List[dict]:
        """Rank tenants by blended contention score, refresh the advisory
        flag set, and (optionally) emit offender events."""
        snap = self.slo.snapshot()
        # derive share totals from the snapshot already in hand (a
        # second slo.totals() pass would re-walk every tenant's windows)
        totals = {"fanout": sum(s["fanout_per_s"] for s in snap.values())
                  * self.slo.window_s,
                  "queue_wait_s": sum(s["queue_wait_s"]
                                      for s in snap.values())}
        n_active = sum(1 for s in snap.values() if s["rate_per_s"] > 0)
        rows = [self._row(tenant, s, totals, n_active)
                for tenant, s in snap.items()]
        rows.sort(key=lambda r: (-r["score"], -r["rate_per_s"],
                                 r["tenant"]))
        self._noisy = {r["tenant"] for r in rows if "noisy" in r["flags"]}
        self._flags_at = self._clock()
        # full ranked rows from the latest evaluation: consumers running
        # right after a tick (the cluster digest) reuse them instead of
        # paying a second whole-registry scoring pass (ISSUE 5)
        self._last_rows = rows
        if emit:
            for r in rows:
                for flag in r["flags"]:
                    self._emit(flag, r)
        return rows[:top_k]

    def recent_rows(self, max_age_s: float) -> Optional[List[dict]]:
        """The last evaluation's FULL ranked rows, if no older than
        ``max_age_s`` — None forces the caller to evaluate itself."""
        if self._clock() - self._flags_at <= max_age_s:
            return self._last_rows
        return None

    def score_tenant(self, tenant: str) -> Optional[dict]:
        """One tenant's ranked row without evaluating every other tenant
        (``GET /tenants/<id>``): O(this tenant + counter totals), no
        advisory-cache refresh, no events."""
        s = self.slo.snapshot_tenant(tenant)
        if not s:
            return None
        return self._row(tenant, s, self.slo.totals(),
                         self.slo.active_count())

    # the outlet is WEAKLY held (last-binder wins — a process-global hub
    # discipline): a stopped broker's collector chain must not be pinned
    # by telemetry, and a dead ref degrades to silent non-emission
    @property
    def events(self) -> Optional[IEventCollector]:
        r = self._events_ref
        return r() if r is not None else None

    @events.setter
    def events(self, collector: Optional[IEventCollector]) -> None:
        self._events_ref = (weakref.ref(collector)
                            if collector is not None else None)

    def _emit(self, flag: str, row: dict) -> None:
        events = self.events
        if events is None:
            return
        key = (row["tenant"], flag)
        now = self._clock()
        if now - self._last_emit.get(key, -1e18) < self.event_cooldown_s:
            return
        if len(self._last_emit) > 1024:
            # an entry past its cooldown suppresses nothing — prune so
            # churning tenant ids can't grow the map forever
            self._last_emit = {
                k: t for k, t in self._last_emit.items()
                if now - t < self.event_cooldown_s}
        self._last_emit[key] = now
        etype = (EventType.NOISY_TENANT if flag == "noisy"
                 else EventType.SLOW_TENANT)
        try:
            events.report(Event(etype, row["tenant"], {
                "score": row["score"],
                "fanout_share": row["fanout_share"],
                "queue_wait_share": row["queue_wait_share"],
                "error_rate": row["error_rate"],
                "p99_ms": row["stages"].get("ingest", {}).get("p99_ms", 0.0),
            }))
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pass

    # ---------------- throttler advisory ------------------------------------

    def is_noisy(self, tenant: str) -> bool:
        """Advisory lookup for the resource throttler. With the background
        tick armed (ObsHub.start_advisory_tick) this is a pure set probe —
        zero added guard-path latency; otherwise the flag set refreshes
        lazily (bounded by ``advisory_ttl_s``), one full evaluation per
        TTL window at most."""
        if (not self.tick_armed
                and self._clock() - self._flags_at > self.advisory_ttl_s):
            self.evaluate(emit=False)
        return tenant in self._noisy

    def reset(self) -> None:
        self._last_emit.clear()
        self._noisy = set()
        self._flags_at = -1e18
        self._last_rows = []
        self.tenant_overrides.clear()
