"""Replication lag plane (ISSUE 18): per-stream apply-lag observability.

Every delta consumer — ``WarmStandby`` (single and mesh), the
``RetainedStandby``, the ``InvalidationPuller`` — reports into the
process-global :data:`LAG` keyed by ``(origin, range_id)``; the leader
side reports emit throughput from ``DeltaLog.append``.  Per stream we
keep a windowed log2 histogram of HLC apply lag (record stamp → apply
wall clock), windowed applied/emitted throughput, the reorder-buffer
occupancy gauge, and monotonic resync/gap counters.

A stream whose observed lag exceeds ``BIFROMQ_REPL_LAG_STALE_S`` is
flagged **stale**; the flag clears only after a full threshold-wide
quiet window of under-threshold applies (hysteresis — a stream that
oscillates around the threshold stays stale).  ``WarmStandby.promote``
consults the flag and refuses a stale promotion without ``force=True``.

:data:`REPL_EVENTS` is the bounded journal every delta-plane event
(stale transitions, gaps, resyncs, parity audits, autoscaler decisions)
appends to; the ObsHub persistence loop drains it through the segment
store via the usual ``since()`` cursor contract.

Layering: like the rest of ``obs`` this module must NOT import
``utils.metrics`` (that module imports ``obs`` at import time).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.env import env_float
from .window import WindowedCounter, WindowedLog2Histogram


def lag_stale_s() -> float:
    """Apply-lag threshold (seconds) beyond which a stream is stale."""
    return max(0.1, env_float("BIFROMQ_REPL_LAG_STALE_S", 5.0))


class EventJournal:
    """Bounded, cursor-addressable ring of delta-plane event records.

    ``append`` stamps a monotonically increasing ``seq``; ``since(cur)``
    returns every surviving record with ``seq > cur`` plus the new
    cursor, so the ObsHub persistence drain is idempotent across
    flushes and a flapping process still yields attributable records.
    """

    def __init__(self, cap: int = 1024) -> None:
        self.cap = max(16, int(cap))
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        self.next_seq = 0

    def append(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec = {"kind": kind, **fields}
        with self._lock:
            rec["seq"] = self.next_seq
            self.next_seq += 1
            self._ring.append(rec)
            if len(self._ring) > self.cap:
                del self._ring[: len(self._ring) - self.cap]
        return rec

    def since(self, cursor: int) -> Tuple[List[Dict[str, Any]], int]:
        with self._lock:
            out = [r for r in self._ring if r["seq"] > cursor]
            new_cursor = self.next_seq - 1
        return out, max(cursor, new_cursor)

    def tail(self, n: int = 64) -> List[Dict[str, Any]]:
        if n <= 0:
            return []
        with self._lock:
            return list(self._ring[-n:])

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.next_seq = 0


class _Stream:
    """One (origin, range) replication stream's live signal set."""

    __slots__ = ("origin", "range_id", "hist", "applied", "emitted",
                 "reorder_occupancy", "resyncs", "gaps", "last_lag_s",
                 "stale", "_last_over", "_clock")

    def __init__(self, origin: str, range_id: str, clock) -> None:
        self.origin = origin
        self.range_id = range_id
        self._clock = clock
        self.hist = WindowedLog2Histogram(clock=clock)
        self.applied = WindowedCounter(clock=clock)
        self.emitted = WindowedCounter(clock=clock)
        self.reorder_occupancy = 0
        self.resyncs = 0
        self.gaps = 0
        self.last_lag_s = 0.0
        self.stale = False
        self._last_over: Optional[float] = None

    def observe(self, lag_s: float, thr: float) -> Optional[bool]:
        """Fold one applied record's lag; returns the new stale flag on
        a transition, None when the flag did not move (hysteresis: the
        flag clears only after a full ``thr``-wide under-threshold
        window — oscillating streams stay stale)."""
        now = self._clock()
        lag_s = max(0.0, lag_s)
        self.last_lag_s = lag_s
        self.hist.record(lag_s)
        self.applied.add(1)
        if lag_s > thr:
            self._last_over = now
            if not self.stale:
                self.stale = True
                return True
        elif (self.stale and self._last_over is not None
              and now - self._last_over >= thr):
            self.stale = False
            return False
        return None

    def snapshot(self) -> Dict[str, Any]:
        h = self.hist.snapshot()
        return {
            "origin": self.origin,
            "range": self.range_id,
            "lag_s": round(self.last_lag_s, 6),
            "lag_p50_ms": h["p50_ms"],
            "lag_p99_ms": h["p99_ms"],
            "applied_window": h["count"],
            "applied_per_s": round(self.applied.rate(), 3),
            "emitted_per_s": round(self.emitted.rate(), 3),
            "reorder_occupancy": self.reorder_occupancy,
            "resyncs": self.resyncs,
            "gaps": self.gaps,
            "stale": self.stale,
        }


class LagPlane:
    """Process-global registry of replication-stream lag signals."""

    def __init__(self, *, clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._streams: Dict[Tuple[str, str], _Stream] = {}

    def _stream(self, origin: str, range_id: str) -> _Stream:
        key = (origin or "?", range_id or "?")
        with self._lock:
            st = self._streams.get(key)
            if st is None:
                st = self._streams[key] = _Stream(key[0], key[1],
                                                  self._clock)
            return st

    # ---------------- feed side ----------------------------------------

    def observe(self, origin: str, range_id: str, lag_s: float) -> None:
        st = self._stream(origin, range_id)
        flipped = st.observe(lag_s, lag_stale_s())
        if flipped is not None:
            REPL_EVENTS.append("lag_stale" if flipped else "lag_fresh",
                               origin=st.origin, range=st.range_id,
                               lag_s=round(st.last_lag_s, 6))

    def note_emit(self, origin: str, range_id: str, n: int = 1) -> None:
        self._stream(origin, range_id).emitted.add(n)

    def note_applied(self, origin: str, range_id: str,
                     n: int = 1) -> None:
        """Throughput-only feed for consumers whose records carry no HLC
        stamp (the invalidation puller)."""
        self._stream(origin, range_id).applied.add(n)

    def note_gap(self, origin: str, range_id: str) -> None:
        st = self._stream(origin, range_id)
        st.gaps += 1
        REPL_EVENTS.append("gap", origin=st.origin, range=st.range_id)

    def note_resync(self, origin: str, range_id: str) -> None:
        st = self._stream(origin, range_id)
        st.resyncs += 1
        REPL_EVENTS.append("resync", origin=st.origin, range=st.range_id)

    def set_occupancy(self, origin: str, range_id: str, n: int) -> None:
        self._stream(origin, range_id).reorder_occupancy = int(n)

    # ---------------- read side ----------------------------------------

    def is_stale(self, origin: str, range_id: str) -> bool:
        with self._lock:
            st = self._streams.get((origin or "?", range_id or "?"))
        return bool(st is not None and st.stale)

    def stale_streams(self) -> List[Tuple[str, str]]:
        with self._lock:
            return [k for k, st in self._streams.items() if st.stale]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            streams = [st.snapshot() for _, st in sorted(
                self._streams.items())]
        return {
            "stale_threshold_s": lag_stale_s(),
            "streams": streams,
            "stale": sum(1 for s in streams if s["stale"]),
        }

    def summary(self) -> Dict[str, Any]:
        """Compact digest field: stream count, stale count, worst lag."""
        with self._lock:
            streams = list(self._streams.values())
        if not streams:
            return {}
        return {
            "streams": len(streams),
            "stale": sum(1 for s in streams if s.stale),
            "worst_lag_s": round(max(s.last_lag_s for s in streams), 3),
        }

    def reset(self) -> None:
        with self._lock:
            self._streams.clear()


LAG = LagPlane()
REPL_EVENTS = EventJournal()
