"""Device capacity model & placement planner (ISSUE 8 tentpole, part 1).

The two headline ROADMAP items — real-TPU validation of the async
pipeline and the 10M-sub sharded matcher — are capacity questions before
they are performance questions: "will this tenant population's automaton
tables fit in HBM on this shard" and "can the fused kernel's VMEM gate
ever pass at this size" are answered today by dispatching and watching
for OOMs (the fused 12MB auto-gate vs the ~67MB 1M-sub edge table).
Tailwind (PAPERS.md) argues accelerator systems need a first-class
capacity/placement model instead; TrieJax's relational formulation makes
trie footprints exactly computable from arena shapes. This module is
that model:

- **Exact accounting** of everything the matcher puts on device, derived
  from the same shape math the upload paths use (``DeviceTrie.
  from_compiled``, ``MeshMatcher._compile_shadow``): level-packed
  node/edge arenas, the narrow count/route column tables, per-shard mesh
  slices (padded exactly as ``build_sharded`` pads them), probe/result
  buffers × dispatch-ring depth, and the transient compile-time double
  (old + new base both alive across a background compaction swap).
- **A planner** (``CapacityPlanner.fits``) that predicts table bytes for
  a subscription count that has never been built, from per-subscription
  coefficients — calibrated from any live ``CompiledTrie`` or defaulting
  to the repo's measured 1M-wildcard-sub build — and renders the HBM
  headroom verdict and the fused-kernel VMEM verdict using the *same*
  comparison ``models.kernels.fused_enabled`` applies at dispatch time.
- **Validation**: ``measure()`` reads the actually-uploaded device
  arrays, so ``GET /capacity`` can report model-vs-live parity (the
  tier-2 gate requires <10% error; the shape math makes it exact).

Layering: this module lives in ``obs`` but describes ``models``/
``parallel`` objects — every models import is deferred inside a function
so the obs package stays importable without jax, and no import cycle
forms (models.matcher imports the obs package at module level).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.env import env_int as _env_int

_I32 = 4            # every automaton table is int32
_EDGE_ENTRY_I32 = 4  # edge_tab entries are (node, h1, h2, child)


def _next_pow2(n: int, floor: int = 1) -> int:
    p = max(1, floor)
    while p < n:
        p *= 2
    return p




# ---------------------------------------------------------------------------
# exact accounting from compiled/placed objects
# ---------------------------------------------------------------------------

def compiled_trie_device_bytes(ct) -> Dict[str, int]:
    """Byte-exact footprint of one single-chip base snapshot as
    ``DeviceTrie.from_compiled`` places it: the full node arena, the
    bucketed edge hash table, the CSR child list, and the narrow
    count/route column tables derived at upload time."""
    from ..ops.match import CT_COLS, RT_COLS
    n = int(ct.node_tab.shape[0])
    out = dict(ct.arena_bytes())
    out["count_tab"] = n * CT_COLS * _I32
    out["route_tab"] = n * RT_COLS * _I32
    out["total"] = sum(out.values())
    return out


def fused_bytes_from_compiled(ct) -> int:
    """The bytes the fused-kernel VMEM gate weighs for this base —
    edge_tab + route_tab, the two tables ``models.kernels._table_bytes``
    sums on the live DeviceTrie — computed host-side from shapes so the
    verdict needs no device upload."""
    from ..ops.match import RT_COLS
    return (int(ct.edge_tab.size) + int(ct.node_tab.shape[0]) * RT_COLS) \
        * _I32


def sharded_tables_device_bytes(tables) -> Dict[str, object]:
    """Byte-exact footprint of a mesh base (``ShardedTables``) as
    ``MeshMatcher._compile_shadow`` places it: edge/child/route stacks
    sharded over the mesh — node_tab is intentionally NOT uploaded
    (route_tab carries every column the interval walk reads). Per-shard
    slices are the stacked (padded) rows divided by S, which is exactly
    what each shard's HBM holds."""
    s = int(tables.n_shards)
    total = {
        "edge_tab": int(tables.edge_tab.size) * _I32,
        "child_list": int(tables.child_list.size) * _I32,
        "route_tab": (int(tables.route_tab.size) * _I32
                      if tables.route_tab is not None else 0),
    }
    total["total"] = sum(total.values())
    per_shard = []
    for i, ct in enumerate(tables.compiled):
        # the shard's REAL rows vs its padded slice: padding waste is the
        # price of one common mesh shape (build_sharded pads to the max)
        real = fused_bytes_from_compiled(ct) \
            + int(ct.child_list.shape[0]) * _I32
        per_shard.append({
            "shard": i,
            "padded_bytes": total["total"] // s,
            "real_bytes": real,
            "n_nodes": int(ct.node_tab.shape[0]),
            "n_slots": ct.n_slots,
        })
    return {"n_shards": s, "total": total, "per_shard": per_shard,
            "pad_waste_ratio": round(
                1.0 - (sum(p["real_bytes"] for p in per_shard)
                       / max(1, total["total"])), 4)}


def probe_bytes(batch: int, max_levels: int = 16) -> int:
    """One uploaded probe batch (``Probes``): two [B, L+1] token-hash
    lanes, [B] lengths + roots, [B] bool sys mask."""
    width = max_levels + 1
    return batch * (2 * width * _I32 + 2 * _I32 + 1)


def result_bytes(batch: int, max_intervals: int = 32) -> int:
    """One walk result (``RouteIntervals``): [B, A] start + count,
    [B] n_routes, [B] bool overflow."""
    return batch * (2 * max_intervals * _I32 + _I32 + 1)


def inflight_bytes(batch: int, *, max_levels: int = 16,
                   max_intervals: int = 32, ring_depth: Optional[int] = None,
                   donated: Optional[bool] = None) -> Dict[str, int]:
    """Device bytes pinned by the async dispatch ring: ``ring_depth``
    in-flight slots, each holding a probe batch and its result arrays,
    plus ONE prep-ahead probe batch (ISSUE 11: stage-1 prep uploads
    before ring admission; the ring's prep tickets bound it to depth+1,
    so exactly one extra probe set can be resident). With buffer
    donation XLA may alias the results into the donated probe buffers,
    so a slot costs max(probes, results) instead of the sum — the
    "donated-aliasing double" the non-donated path pays."""
    if ring_depth is None:
        from ..models.pipeline import pipeline_depth
        ring_depth = pipeline_depth()
    if donated is None:
        from ..models.pipeline import donation_enabled
        donated = donation_enabled()
    pb = probe_bytes(batch, max_levels)
    rb = result_bytes(batch, max_intervals)
    per_slot = max(pb, rb) if donated else pb + rb
    return {"ring_depth": int(ring_depth), "batch": int(batch),
            "donated": bool(donated), "probe_bytes": pb,
            "result_bytes": rb, "per_slot": per_slot,
            "prep_ahead_bytes": pb,
            "total": per_slot * int(ring_depth) + pb}


def measure(matcher) -> Dict[str, object]:
    """Model-vs-live parity for one matcher's INSTALLED base: predicted
    bytes from the host-side shape math next to the bytes of the jax
    arrays actually resident on device. Single-chip and mesh bases both
    supported; an uninstalled matcher reports ``installed: False``."""
    base = getattr(matcher, "_base_ct", None)
    dev = getattr(matcher, "_device_trie", None)
    if base is None or dev is None:
        return {"installed": False}

    def arr_bytes(a) -> int:
        return int(a.size) * a.dtype.itemsize if a is not None else 0

    if hasattr(base, "compiled"):            # mesh ShardedTables
        predicted = sharded_tables_device_bytes(base)
        measured = sum(arr_bytes(a) for a in dev)
        predicted_total = predicted["total"]["total"]
        kind = "mesh"
    else:                                    # single-chip CompiledTrie
        predicted = compiled_trie_device_bytes(base)
        measured = sum(arr_bytes(a) for a in (
            dev.node_tab, dev.edge_tab, dev.child_list,
            dev.count_tab, dev.route_tab))
        predicted_total = predicted["total"]
        kind = "single"
    err = (abs(measured - predicted_total) / measured) if measured else 0.0
    out = {
        "installed": True,
        "kind": kind,
        "predicted": predicted,
        "measured_device_bytes": measured,
        "parity_error": round(err, 6),
        "overlay_routes": getattr(matcher, "overlay_size", 0),
    }
    # ISSUE 9: arena headroom + tombstone/fragmentation accounting for
    # patchable bases — the numbers the patch-vs-compact decision reads
    if hasattr(base, "patch_stats"):
        out["patch"] = base.patch_stats()
        out["patch_fallbacks"] = getattr(matcher, "patch_fallbacks", 0)
        out["patched_mutations"] = getattr(matcher, "patch_count", 0)
    elif kind == "mesh" and any(hasattr(c, "patch_stats")
                                for c in base.compiled):
        # ISSUE 15: per-shard arena accounting for the patched mesh base
        out["patch"] = {"shards": [
            c.patch_stats() if hasattr(c, "patch_stats") else None
            for c in base.compiled]}
        out["patch_fallbacks"] = getattr(matcher, "patch_fallbacks", 0)
        out["patched_mutations"] = getattr(matcher, "patch_count", 0)
    ring = getattr(matcher, "_ring", None)
    if ring is not None:
        out["inflight"] = inflight_bytes(
            getattr(ring, "base_floor", 16),
            max_levels=matcher.max_levels,
            max_intervals=getattr(matcher, "max_intervals", 32),
            ring_depth=ring.depth)
    if kind == "single":
        out["fused_table_bytes"] = fused_bytes_from_compiled(base)
    return out


# ---------------------------------------------------------------------------
# the planner: predict footprints that have never been built
# ---------------------------------------------------------------------------

@dataclass
class CapacityPlanner:
    """Per-subscription footprint coefficients → byte predictions.

    Defaults are calibrated from the repo's measured 1M-wildcard-sub
    build (ROADMAP: ~1.6M automaton nodes, ~67MB edge table =
    2^18 buckets × probe_len 16 × 4 × int32): ~1.6 trie nodes and ~1.6
    literal edges per subscription, hash buckets grown until no bucket
    overflows at ~0.4 entry load. ``calibrate`` replaces them with exact
    ratios from any live ``CompiledTrie`` so same-workload predictions
    are shape-exact.
    """

    nodes_per_sub: float = 1.6
    edges_per_sub: float = 1.6
    slots_per_sub: float = 1.0
    edge_load: float = 0.4       # valid entries / table entry capacity
    calibrated_from: Optional[str] = None

    def calibrate(self, ct, n_subs: int) -> "CapacityPlanner":
        """Fit the coefficients to a live base snapshot compiled from
        ``n_subs`` subscriptions (returns self for chaining)."""
        import numpy as np
        if n_subs <= 0:
            raise ValueError("n_subs must be positive")
        n = int(ct.node_tab.shape[0])
        entries = int(ct.edge_tab.size) // _EDGE_ENTRY_I32
        valid = int(np.count_nonzero(
            np.asarray(ct.edge_tab).reshape(-1, _EDGE_ENTRY_I32)[:, 0] >= 0))
        self.nodes_per_sub = n / n_subs
        self.edges_per_sub = valid / n_subs
        self.slots_per_sub = max(1, ct.n_slots) / n_subs
        self.edge_load = valid / entries if entries else self.edge_load
        self.calibrated_from = f"live:{n_subs}"
        return self

    def predict_tables(self, n_subs: int, *, probe_len: int = 16,
                       n_shards: int = 1,
                       mesh_placed: bool = False) -> Dict[str, int]:
        """Predicted per-device table bytes for ``n_subs`` subscriptions
        spread evenly over ``n_shards`` shards. ``mesh_placed`` models
        the mesh upload (no node_tab / count_tab on device) vs the
        single-chip upload (all five tables)."""
        from ..models.automaton import NODE_COLS
        from ..ops.match import CT_COLS, RT_COLS
        per_shard_subs = max(1, math.ceil(n_subs / max(1, n_shards)))
        n = max(1, math.ceil(per_shard_subs * self.nodes_per_sub))
        edges = max(1, math.ceil(per_shard_subs * self.edges_per_sub))
        # the builder grows the bucket table (power-of-two bucket counts,
        # min_edge_cap=8) until no bucket overflows; the calibrated load
        # factor folds that growth into one ratio
        buckets = _next_pow2(
            math.ceil(edges / (self.edge_load * probe_len)), floor=8)
        out = {
            "n_nodes": n,
            "n_edges": edges,
            "edge_buckets": buckets,
            "edge_tab": buckets * probe_len * _EDGE_ENTRY_I32 * _I32,
            "child_list": edges * _I32,
            "route_tab": n * RT_COLS * _I32,
        }
        if mesh_placed:
            out["node_tab"] = 0
            out["count_tab"] = 0
        else:
            out["node_tab"] = n * NODE_COLS * _I32
            out["count_tab"] = n * CT_COLS * _I32
        out["total"] = (out["edge_tab"] + out["child_list"]
                        + out["route_tab"] + out["node_tab"]
                        + out["count_tab"])
        return out

    def fits(self, n_subs: int, mesh: Optional[object] = None,
             fused: Optional[bool] = None, *, batch: int = 16,
             max_levels: int = 16, probe_len: int = 16,
             max_intervals: int = 32, ring_depth: Optional[int] = None,
             donated: Optional[bool] = None,
             hbm_limit_bytes: Optional[int] = None) -> Dict[str, object]:
        """The planner verdict: would ``n_subs`` subscriptions fit this
        device (or each shard of ``mesh``), and would the fused kernel's
        VMEM auto-gate pass — WITHOUT building or dispatching anything.

        ``mesh`` is ``None`` (single chip), an ``int`` shard count, or a
        ``(replicas, shards)`` tuple / ``jax.sharding.Mesh``. The HBM
        verdict compares predicted resident bytes — tables + the
        dispatch ring's in-flight buffers + the transient compile-time
        double (old and new base both alive across a compaction swap) —
        against ``hbm_limit_bytes`` (default: the live device's
        ``memory_stats`` limit when probeable, else the
        ``BIFROMQ_HBM_BYTES`` env knob, else unknown). The fused VMEM
        verdict applies the same ``table_bytes <= budget`` comparison
        ``models.kernels.fused_enabled`` runs per dispatch.
        """
        n_shards = 1
        n_replicas = 1
        if mesh is not None:
            if isinstance(mesh, int):
                n_shards = mesh
            elif isinstance(mesh, (tuple, list)):
                n_replicas, n_shards = int(mesh[0]), int(mesh[1])
            else:                       # jax Mesh
                from ..parallel.sharded import REPLICA_AXIS, SHARD_AXIS
                n_replicas = int(mesh.shape[REPLICA_AXIS])
                n_shards = int(mesh.shape[SHARD_AXIS])
        tables = self.predict_tables(n_subs, probe_len=probe_len,
                                     n_shards=n_shards,
                                     mesh_placed=n_shards > 1)
        flight = inflight_bytes(batch, max_levels=max_levels,
                                max_intervals=max_intervals,
                                ring_depth=ring_depth, donated=donated)
        # a background compaction holds TWO bases alive across the swap
        # (in-flight dispatches pin the old tables) — plan for the peak
        transient = tables["total"]
        per_device = tables["total"] + flight["total"]
        peak = per_device + transient
        if hbm_limit_bytes is None:
            hbm_limit_bytes = _live_hbm_limit()
        headroom = (hbm_limit_bytes - peak
                    if hbm_limit_bytes is not None else None)
        fused_tb = tables["edge_tab"] + tables["route_tab"]
        from ..models.kernels import (fused_fits_vmem,
                                      fused_vmem_budget_bytes)
        vmem_budget = fused_vmem_budget_bytes()
        # the exact comparison the dispatch-time gate applies
        vmem_fits = fused_fits_vmem(fused_tb)
        return {
            "n_subs": n_subs,
            "mesh": {"replicas": n_replicas, "shards": n_shards},
            "tables": tables,
            "inflight": flight,
            "compile_transient_bytes": transient,
            "per_device_bytes": per_device,
            "per_device_peak_bytes": peak,
            "hbm": {
                "limit_bytes": hbm_limit_bytes,
                "headroom_bytes": headroom,
                "fits": (headroom >= 0 if headroom is not None else None),
            },
            "fused_vmem": {
                "table_bytes": fused_tb,
                "budget_bytes": vmem_budget,
                "fits": vmem_fits,
                # why: the gate also needs a TPU backend; `fits` answers
                # only the capacity half the planner owns
                "note": ("auto mode additionally requires a TPU backend"
                         if fused is None else
                         ("forced on" if fused else "killed by env")),
            },
        }

    def snapshot(self) -> dict:
        return {"nodes_per_sub": round(self.nodes_per_sub, 4),
                "edges_per_sub": round(self.edges_per_sub, 4),
                "slots_per_sub": round(self.slots_per_sub, 4),
                "edge_load": round(self.edge_load, 4),
                "calibrated_from": self.calibrated_from}


def _live_hbm_limit() -> Optional[int]:
    """The live device's HBM byte limit: the env override first, then
    the guarded memory probe (never triggers backend init — same
    discipline as ``DeviceGauges._memory_stats``)."""
    env = _env_int("BIFROMQ_HBM_BYTES", 0)
    if env > 0:
        return env
    from . import OBS
    ms = OBS.device.memory_stats()
    if ms.get("available"):
        limits = [d.get("bytes_limit", 0) for d in ms.get("devices", ())]
        limits = [x for x in limits if x > 0]
        if limits:
            return min(limits)
    return None


# ---------------------------------------------------------------------------
# report surfaces (GET /capacity, the gossip digest, bench records)
# ---------------------------------------------------------------------------

def default_planner(matchers: Sequence = ()) -> CapacityPlanner:
    """A planner calibrated from the largest installed single-chip base
    among ``matchers`` (n_subs approximated by slot count — every
    subscription contributes ≥1 matching slot), else the 1M-sub
    defaults."""
    planner = CapacityPlanner()
    best = None
    for m in matchers:
        base = getattr(m, "_base_ct", None)
        if base is None or hasattr(base, "compiled"):
            continue
        if best is None or base.n_slots > best.n_slots:
            best = base
    if best is not None and best.n_slots >= 64:
        # small bases calibrate to noise (fixed pow2 floors dominate);
        # keep the defaults below that
        planner.calibrate(best, best.n_slots)
    return planner


def calibrate_report(*, n_subs: Optional[int] = None,
                     matchers: Optional[Sequence] = None,
                     before: Optional[CapacityPlanner] = None
                     ) -> Dict[str, object]:
    """Operational ``calibrate`` (ISSUE 11 satellite, ROADMAP sharding
    follow-up (c)): re-fit the planner's per-subscription coefficients
    from the live base using the TRUE logical subscription count (one
    per live route in the authoritative tries — the slot-count proxy
    ``default_planner`` uses overcounts group slots and tombstones), and
    report old-vs-new coefficient deltas plus the predicted-bytes shift
    at a target population. Served by ``GET /capacity?calibrate=1``;
    ``scripts/calibrate_capacity.sh`` is the one-liner.
    ``matchers``/``before`` let ``capacity_report`` hand over its
    already-computed scan instead of walking every base twice."""
    if matchers is None:
        from . import OBS
        matchers = OBS.device.matchers()
    if before is None:
        before = default_planner(matchers)
    best = best_m = None
    for m in matchers:
        base = getattr(m, "_base_ct", None)
        if base is None or hasattr(base, "compiled"):
            continue
        if best is None or base.n_slots > best.n_slots:
            best, best_m = base, m
    if best is None:
        return {"calibrated": False,
                "reason": "no installed single-chip base"}
    live_subs = sum(len(t) for t in
                    (getattr(best_m, "tries", None) or {}).values())
    if live_subs <= 0:
        live_subs = max(1, best.n_slots)
    after = CapacityPlanner().calibrate(best, live_subs)
    fields = ("nodes_per_sub", "edges_per_sub", "slots_per_sub",
              "edge_load")
    target = n_subs or live_subs
    return {
        "calibrated": True,
        "n_subs_live": live_subs,
        "before": before.snapshot(),
        "after": after.snapshot(),
        "delta": {k: round(getattr(after, k) - getattr(before, k), 4)
                  for k in fields},
        "predicted_table_bytes": {
            "n_subs": target,
            "before": before.predict_tables(target)["total"],
            "after": after.predict_tables(target)["total"],
        },
    }


def capacity_report(*, n_subs: Optional[int] = None,
                    mesh: Optional[object] = None,
                    memory: bool = True,
                    calibrate: bool = False) -> Dict[str, object]:
    """The ``GET /capacity`` payload: model-vs-live parity for every
    registered matcher, the guarded HBM stats, the planner coefficients,
    and (when ``n_subs`` is given) a full ``fits`` verdict. With
    ``calibrate`` the response also carries the live re-fit + deltas
    (and the ``fits`` verdict uses the re-fit coefficients)."""
    from . import OBS
    matchers = OBS.device.matchers()
    rows = [measure(m) for m in matchers]
    planner = default_planner(matchers)
    out: Dict[str, object] = {
        "matchers": rows,
        "planner": planner.snapshot(),
        "table_bytes": sum(r.get("measured_device_bytes", 0) for r in rows),
    }
    if calibrate:
        cal = calibrate_report(n_subs=n_subs, matchers=matchers,
                               before=planner)
        out["calibrate"] = cal
        if cal.get("calibrated"):
            planner = CapacityPlanner(**{
                k: cal["after"][k] for k in
                ("nodes_per_sub", "edges_per_sub", "slots_per_sub",
                 "edge_load")})
            planner.calibrated_from = cal["after"]["calibrated_from"]
    installed = [r for r in rows if r.get("installed")]
    if installed:
        out["parity_error"] = max(r["parity_error"] for r in installed)
    if memory:
        out["hbm"] = OBS.device.memory_stats()
        out["hbm_limit_bytes"] = _live_hbm_limit()
    if n_subs is not None:
        out["fits"] = planner.fits(n_subs, mesh=mesh)
    return out


def record_compile_event(base, *, reason: str, duration_s: float,
                         salt=None,
                         generation_bumped: bool = False) -> None:
    """Stamp one base build into the process compile ledger — the ONE
    site deriving a ledger event's table bytes + fused-VMEM verdict
    from a compiled base (single-chip or mesh). Matcher installs and
    bench builds both route here, so their records cannot diverge.
    Best-effort: accounting must never fail a build."""
    from . import OBS
    try:
        if hasattr(base, "compiled"):        # mesh ShardedTables
            tb = sharded_tables_device_bytes(base)["total"]["total"]
            n_nodes = sum(int(c.node_tab.shape[0])
                          for c in base.compiled)
            vmem = None
            kind = "mesh"
            if salt is None:
                salt = tuple(getattr(c, "salt", None)
                             for c in base.compiled)
        else:                                # single-chip CompiledTrie
            from ..models.kernels import fused_fits_vmem
            tb = compiled_trie_device_bytes(base)["total"]
            n_nodes = base.n_nodes
            vmem = fused_fits_vmem(fused_bytes_from_compiled(base))
            kind = "single"
            if salt is None:
                salt = base.salt
        OBS.profiler.ledger.record(
            reason=reason, duration_s=duration_s, salt=salt,
            n_nodes=n_nodes, table_bytes=tb, vmem_fits=vmem,
            generation_bumped=generation_bumped, kind=kind)
    except Exception:  # noqa: BLE001 — telemetry must not raise
        pass


def digest_capacity(hub) -> Dict[str, object]:
    """The compact capacity field gossiped in the health digest (ISSUE 8:
    ``GET /cluster/capacity`` federates these — no extra RPC plane).
    Host-side shape math + cached watermarks only: the digest refresh
    must never block on the device tunnel."""
    table_bytes = 0
    vmem_fits: Optional[bool] = None
    logical: List[Tuple[str, int]] = []
    for m in hub.device.matchers():
        # ISSUE 9 satellite (PR 8 follow-up): dedup-aware LOGICAL
        # subscription count next to the physical table bytes — counted
        # from the authoritative tries (one entry per live subscription,
        # regardless of arena padding/tombstones), fingerprinted so the
        # cluster rollup can count replicated tables once
        for tenant_id, trie in (getattr(m, "tries", None) or {}).items():
            logical.append((tenant_id, len(trie)))
        base = getattr(m, "_base_ct", None)
        if base is None:
            continue
        try:
            if hasattr(base, "compiled"):
                table_bytes += sharded_tables_device_bytes(
                    base)["total"]["total"]
            else:
                table_bytes += compiled_trie_device_bytes(base)["total"]
                from ..models.kernels import fused_fits_vmem
                ok = fused_fits_vmem(fused_bytes_from_compiled(base))
                vmem_fits = ok if vmem_fits is None else (vmem_fits and ok)
        except Exception:  # noqa: BLE001 — telemetry must not raise
            continue
    out: Dict[str, object] = {"table_bytes": table_bytes,
                              "mem_peak_bytes": hub.device.peak_memory_bytes}
    out["logical_subs"] = sum(c for _, c in logical)
    if logical:
        import hashlib
        h = hashlib.blake2b(digest_size=8)
        for tenant_id, c in sorted(logical):
            h.update(f"{tenant_id}:{c};".encode("utf-8"))
        out["subs_fp"] = h.hexdigest()
    if vmem_fits is not None:
        out["vmem_fits"] = vmem_fits
    limit = _env_int("BIFROMQ_HBM_BYTES", 0)
    if limit > 0:
        out["hbm_limit_bytes"] = limit
    return out
