"""Per-tenant windowed RED aggregation (ISSUE 3 tentpole, part 1).

Rate / Errors / Duration per tenant over a sliding ~10s window, fed from
three directions:

- **flows** — every metered ``TenantMetric`` event increments the tenant's
  rate window (``MeteringEventCollector`` forwards into the hub);
- **errors** — the error-classed subset (deliver errors, QoS drops, inbox
  overflow) additionally lands in the error window;
- **durations** — the hot path records per-(tenant, stage) windowed log2
  histograms (ingest / queue_wait / device / deliver), the per-tenant twin
  of the process-global ``utils.metrics.STAGES``.

Plus the two share signals the noisy-neighbor detector scores on: fan-out
(routes actually delivered per publish) and batch queue-wait seconds.

Tenant cardinality is bounded: past ``max_tenants`` the oldest-inserted
tenant's windows are dropped (dict FIFO, same discipline as the dist match
cache) — a tenant that keeps publishing simply re-enters.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List

from .window import WindowedCounter, WindowedLog2Histogram


class _TenantWindows:
    """One tenant's live RED state."""

    __slots__ = ("flows", "errors", "fanout", "queue_wait_s",
                 "cache_hits", "cache_misses", "stages", "_mk_hist")

    def __init__(self, mk_counter, mk_hist) -> None:
        self.flows = mk_counter()
        self.errors = mk_counter()
        self.fanout = mk_counter()
        self.queue_wait_s = mk_counter()
        # match-result cache lookups (ISSUE 4): per-tenant hit rate for
        # GET /tenants
        self.cache_hits = mk_counter()
        self.cache_misses = mk_counter()
        self.stages: Dict[str, WindowedLog2Histogram] = {}
        self._mk_hist = mk_hist

    def stage(self, name: str) -> WindowedLog2Histogram:
        h = self.stages.get(name)
        if h is None:
            h = self.stages.setdefault(name, self._mk_hist())
        return h


class TenantSLO:
    """The windowed per-tenant registry. Thread-safe for registration
    (sessions run on the loop; compaction threads may report too) but
    recording into an existing window is GIL-atomic list arithmetic —
    no lock on the steady-state path."""

    def __init__(self, *, window_s: float = 10.0, n_slices: int = 5,
                 max_tenants: int = 512,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.window_s = float(window_s)
        self.n_slices = int(n_slices)
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._tenants: Dict[str, _TenantWindows] = {}
        self._lock = threading.Lock()

    def _mk_counter(self) -> WindowedCounter:
        return WindowedCounter(self.window_s, self.n_slices, self._clock)

    def _mk_hist(self) -> WindowedLog2Histogram:
        return WindowedLog2Histogram(self.window_s, self.n_slices,
                                     self._clock)

    def _windows(self, tenant: str) -> _TenantWindows:
        w = self._tenants.get(tenant)
        if w is None:
            with self._lock:
                w = self._tenants.get(tenant)
                if w is None:
                    if len(self._tenants) >= self.max_tenants:
                        # bounded: drop the oldest-inserted tenant
                        self._tenants.pop(next(iter(self._tenants)))
                    w = _TenantWindows(self._mk_counter, self._mk_hist)
                    self._tenants[tenant] = w
        return w

    # ---------------- recording (hot path) ---------------------------------

    def record_flow(self, tenant: str, n: float = 1.0) -> None:
        self._windows(tenant).flows.add(n)

    def record_error(self, tenant: str, n: float = 1.0) -> None:
        self._windows(tenant).errors.add(n)

    def record_fanout(self, tenant: str, n: float) -> None:
        if n > 0:
            self._windows(tenant).fanout.add(n)

    def record_queue_wait(self, tenant: str, seconds: float) -> None:
        self._windows(tenant).queue_wait_s.add(seconds)

    def record_match_cache(self, tenant: str, hits: float,
                           misses: float) -> None:
        w = self._windows(tenant)
        if hits:
            w.cache_hits.add(hits)
        if misses:
            w.cache_misses.add(misses)

    def record_latency(self, tenant: str, stage: str,
                       seconds: float) -> None:
        self._windows(tenant).stage(stage).record(seconds)

    # ---------------- snapshots --------------------------------------------

    def tenants(self) -> List[str]:
        return list(self._tenants)

    def snapshot_tenant(self, tenant: str) -> dict:
        w = self._tenants.get(tenant)
        if w is None:
            return {}
        flows = w.flows.total()
        errors = w.errors.total()
        stages = {}
        for name, h in w.stages.items():
            s = h.snapshot()        # ONE merge per histogram
            if s["count"]:
                stages[name] = s
        cache_hits = w.cache_hits.total()
        cache_lookups = cache_hits + w.cache_misses.total()
        return {
            "rate_per_s": round(flows / self.window_s, 3),
            "errors_per_s": round(errors / self.window_s, 3),
            "error_rate": round(errors / flows, 4) if flows else 0.0,
            "fanout_per_s": round(w.fanout.total() / self.window_s, 3),
            "queue_wait_s": round(w.queue_wait_s.total(), 6),
            "match_cache_hit_rate": (round(cache_hits / cache_lookups, 4)
                                     if cache_lookups else 0.0),
            "stages": stages,
        }

    def snapshot(self) -> Dict[str, dict]:
        out = {}
        for tenant in list(self._tenants):
            snap = self.snapshot_tenant(tenant)
            if snap and (snap["rate_per_s"] or snap["fanout_per_s"]
                         or snap["queue_wait_s"] or snap["stages"]):
                out[tenant] = snap
        return out

    def raw_tenant(self, tenant: str) -> dict:
        """One tenant's UN-derived window state (ISSUE 5): scalar totals
        plus per-stage merged log2 bucket arrays — the federation unit
        ``/cluster/tenants`` merges bucket-wise across nodes (derived
        percentiles cannot be merged; buckets add exactly)."""
        w = self._tenants.get(tenant)
        if w is None:
            return {}
        stages = {}
        for name, h in w.stages.items():
            b = h.merged()
            if any(b):
                stages[name] = b
        return {"flows": w.flows.total(),
                "errors": w.errors.total(),
                "fanout": w.fanout.total(),
                "queue_wait_s": round(w.queue_wait_s.total(), 6),
                "cache_hits": w.cache_hits.total(),
                "cache_misses": w.cache_misses.total(),
                "stages": stages}

    def raw_snapshot(self) -> Dict[str, dict]:
        out = {}
        for tenant in list(self._tenants):
            r = self.raw_tenant(tenant)
            if r and (r["flows"] or r["fanout"] or r["queue_wait_s"]
                      or r["stages"]):
                out[tenant] = r
        return out

    def active_count(self) -> int:
        """Tenants with live flow traffic in the window — counter sums
        only, no histogram merges (cheap enough for per-request use)."""
        return sum(1 for w in list(self._tenants.values())
                   if w.flows.total() > 0)

    # share totals the detector normalizes against
    def totals(self) -> Dict[str, float]:
        fanout = wait = 0.0
        for w in list(self._tenants.values()):
            fanout += w.fanout.total()
            wait += w.queue_wait_s.total()
        return {"fanout": fanout, "queue_wait_s": wait}

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()
