"""Always-on continuous profiler for the device match path (ISSUE 8
tentpole, part 2).

PR 6's async pipeline left "where do the microseconds go between
dispatch and fetch on a ~70ms-RTT tunnel" answerable only by an offline
bench run. This module keeps the answer live, at a cost the pipelined
path cannot feel (<2% — the recording site is a handful of attribute
increments plus one ring store, the ``SpanRing`` discipline: GIL-atomic
enough for telemetry, no locks, no allocation beyond the record):

- **Per-batch stage decomposition.** Every device batch (sync or async)
  records its tokenize / dispatch / ready / fetch / expand seconds
  (ISSUE 11 split the byte-plane prep out of dispatch) plus batch
  geometry (queries vs padded rows) and the kernel that served it. The
  snapshot splits the wall time into a tunnel-RTT estimate (a tiny
  TTL-cached scalar round trip, same guarded-probe discipline as the
  memory watermarks — CPU pays microseconds, the axon tunnel ~70ms) and
  the residual device-kernel time, so CPU-fallback and real-TPU records
  stay comparable.
- **Efficiency counters.** Padding waste (pow2 pad rows that walk for
  nothing), in-batch dedup savings and cache-hit bypasses (rows that
  never reached the device), batcher emit occupancy, and degraded
  serves by reason.
- **Compile-event ledger.** Every base install is attributable: what
  triggered it (first_base / threshold / forced / refresh), how long the
  compile ran, the table salt, node count, table bytes, the fused VMEM
  verdict, and whether it bumped the match-cache generation — so a
  rebuild storm reads as a sequence of causes, not a mystery latency
  cliff.

Records drain into the bounded segment store (``obs.segstore``) via
``since()`` cursors for post-hoc analysis after a TPU session ends.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..trace.recorder import SpanRing


class BatchRecord:
    """One device batch's profile. Plain slots — built once per batch on
    the serving path, so no dataclass/dict overhead."""

    __slots__ = ("ts", "n_queries", "batch", "kernel", "path",
                 "tokenize_s", "dispatch_s", "ready_s", "fetch_s",
                 "expand_s", "dev_expand_s", "degraded")

    def __init__(self, ts, n_queries, batch, kernel, path, tokenize_s,
                 dispatch_s, ready_s, fetch_s, expand_s, degraded,
                 dev_expand_s=0.0) -> None:
        self.ts = ts
        self.n_queries = n_queries
        self.batch = batch
        self.kernel = kernel
        self.path = path
        self.tokenize_s = tokenize_s
        self.dispatch_s = dispatch_s
        self.ready_s = ready_s
        self.fetch_s = fetch_s
        self.expand_s = expand_s
        # ISSUE 19: the DEVICE expansion stage (fan-out pairing +
        # peer bucketing enqueue) — distinct from expand_s, which is the
        # host's stage-3 leg (escalation + overlay + route assembly;
        # with device expansion on, the residual last hop)
        self.dev_expand_s = dev_expand_s
        self.degraded = degraded

    def to_dict(self) -> dict:
        return {"ts": round(self.ts, 3), "n_queries": self.n_queries,
                "batch": self.batch, "kernel": self.kernel,
                "path": self.path,
                "tokenize_ms": round(self.tokenize_s * 1e3, 4),
                "dispatch_ms": round(self.dispatch_s * 1e3, 4),
                "ready_ms": round(self.ready_s * 1e3, 4),
                "fetch_ms": round(self.fetch_s * 1e3, 4),
                "expand_ms": round(self.expand_s * 1e3, 4),
                "dev_expand_ms": round(self.dev_expand_s * 1e3, 4),
                "degraded": self.degraded}


class CompileLedger:
    """Bounded ledger of base-install events (ISSUE 8: rebuild storms
    must be attributable). Appended from the matcher's install path —
    once per compile, so a deque with a lock-free append is plenty.

    ISSUE 9: the ledger also carries the PATCH stream — every coalesced
    device patch flush records its trigger (``rows`` scatter vs a
    ``node``/``edge`` reshape re-upload), how many mutations it folded,
    rows touched and host→device bytes shipped — so subscription churn
    reads as a sequence of narrow updates next to the (now rare)
    compiles, not as silence."""

    CAP = 256

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._clock = clock
        self._events: deque = deque(maxlen=self.CAP)
        self.total = 0
        self.total_compile_s = 0.0
        self.generation_bumps = 0
        self._patch_events: deque = deque(maxlen=self.CAP)
        self.patch_flushes = 0
        self.patch_mutations = 0
        self.patch_rows = 0
        self.patch_bytes = 0
        self.patch_total_s = 0.0

    def record(self, *, reason: str, duration_s: float, salt,
               n_nodes: int, table_bytes: int,
               vmem_fits: Optional[bool],
               generation_bumped: bool, kind: str = "single") -> None:
        self.total += 1
        self.total_compile_s += duration_s
        if generation_bumped:
            self.generation_bumps += 1
        self._events.append({
            "ts": round(self._clock(), 3),
            "reason": reason,
            "compile_s": round(duration_s, 4),
            "salt": salt,
            "n_nodes": n_nodes,
            "table_bytes": table_bytes,
            "vmem_fits": vmem_fits,
            "generation_bumped": generation_bumped,
            "kind": kind,
        })

    def record_patch(self, *, reason: str, mutations: int, rows: int,
                     bytes_shipped: int, duration_s: float) -> None:
        self.patch_flushes += 1
        self.patch_mutations += mutations
        self.patch_rows += rows
        self.patch_bytes += bytes_shipped
        self.patch_total_s += duration_s
        self._patch_events.append({
            "ts": round(self._clock(), 3),
            "reason": reason,
            "mutations": mutations,
            "rows": rows,
            "bytes": bytes_shipped,
            "apply_ms": round(duration_s * 1e3, 4),
        })

    def events(self, limit: int = 0) -> List[dict]:
        evs = list(self._events)
        return evs[-limit:] if limit > 0 else evs

    def patch_events(self, limit: int = 0) -> List[dict]:
        evs = list(self._patch_events)
        return evs[-limit:] if limit > 0 else evs

    def snapshot(self, limit: int = 16) -> dict:
        return {"total": self.total,
                "total_compile_s": round(self.total_compile_s, 3),
                "generation_bumps": self.generation_bumps,
                "events": self.events(limit),
                "patch": {
                    "flushes": self.patch_flushes,
                    "mutations": self.patch_mutations,
                    "rows": self.patch_rows,
                    "bytes": self.patch_bytes,
                    "total_apply_s": round(self.patch_total_s, 4),
                    "events": self.patch_events(limit),
                }}

    def reset(self) -> None:
        self._events.clear()
        self.total = 0
        self.total_compile_s = 0.0
        self.generation_bumps = 0
        self._patch_events.clear()
        self.patch_flushes = 0
        self.patch_mutations = 0
        self.patch_rows = 0
        self.patch_bytes = 0
        self.patch_total_s = 0.0


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class ContinuousProfiler:
    RING_CAP = 2048
    RTT_PROBE_TTL_S = 30.0

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._clock = clock
        # the tracer's fixed-slot ring is record-type-agnostic — reuse
        # it (record/spans/since cursor math in ONE place) rather than
        # re-deriving the wrap/missed arithmetic here
        self._ring = SpanRing(self.RING_CAP)
        self.ledger = CompileLedger(clock=clock)
        # counters (monotonic; plain int adds on the hot path)
        self.batches_total = 0
        self.queries_total = 0
        self.padded_rows_total = 0
        self.cache_hits_total = 0
        self.dedup_saved_total = 0
        self.frontend_queries_total = 0
        self.degraded_total: Dict[str, int] = {}
        self.emits_total = 0
        self.emit_calls_total = 0
        self.emit_cap_total = 0
        self.emit_depth_total = 0
        # tunnel-RTT probe cache (guarded: never triggers backend init).
        # ISSUE 9 satellite (PR 8 follow-up): keyed per device_kind so a
        # process that falls back from TPU to CPU (or recovers) stops
        # blending the dispatch/kernel split across backends — a backend
        # change reads a different cache slot instead of a stale number.
        self._rtt_cache: dict = {}      # device_kind -> (ms|None, probed_at)
        self._rtt_ms: Optional[float] = None    # last-probed (compat view)
        self._rtt_kind: Optional[str] = None    # backend the split speaks for
        self._rtt_at = -1e18

    # ---------------- hot-path recording (the <2% budget) ------------------

    def record_batch(self, *, n_queries: int, batch: int, kernel: str,
                     dispatch_s: float, tokenize_s: float = 0.0,
                     ready_s: float = 0.0,
                     fetch_s: float = 0.0, expand_s: float = 0.0,
                     dev_expand_s: float = 0.0,
                     path: str = "async",
                     degraded: Optional[str] = None) -> None:
        self.batches_total += 1
        self.queries_total += n_queries
        self.padded_rows_total += max(0, batch - n_queries)
        if degraded is not None:
            self.degraded_total[degraded] = \
                self.degraded_total.get(degraded, 0) + 1
        self._ring.record(BatchRecord(
            self._clock(), n_queries, batch, kernel, path, tokenize_s,
            dispatch_s, ready_s, fetch_s, expand_s, degraded,
            dev_expand_s=dev_expand_s))

    def record_frontend(self, n_queries: int, hits: int,
                        dedup_saved: int) -> None:
        """Cache-plane bypasses: rows that never reached the device."""
        self.frontend_queries_total += n_queries
        self.cache_hits_total += hits
        self.dedup_saved_total += dedup_saved

    def record_emit(self, batch_size: int, cap: int, depth: int) -> None:
        """Batcher emit occupancy (scheduler side of padding waste: a
        batch far under its adaptive cap pads more downstream) plus the
        queue depth observed at emit (the saturation signal _adapt
        keys on)."""
        self.emits_total += 1
        self.emit_calls_total += batch_size
        self.emit_cap_total += cap
        self.emit_depth_total += depth

    # ---------------- snapshots --------------------------------------------

    def records(self, limit: int = 0) -> List[BatchRecord]:
        out = self._ring.spans()        # oldest first (generic ring)
        return out[-limit:] if limit > 0 else out

    def since(self, cursor: int):
        """Records after write-counter ``cursor`` (oldest first), the new
        cursor, and how many were overwritten unread — the segment
        store's incremental drain (``SpanRing.since``'s contract,
        verbatim, because it IS that implementation)."""
        return self._ring.since(cursor)

    @staticmethod
    def _backend_kind() -> Optional[str]:
        """The live backend's device_kind WITHOUT triggering backend init
        (a dead tunnel would hang it) — None until real device work ran."""
        try:
            import sys
            if "jax" not in sys.modules:
                return None
            import jax
            from jax._src import xla_bridge as _xb
            if not getattr(_xb, "_backends", None):
                return None
            d = jax.devices()[0]
            return getattr(d, "device_kind", None) or d.platform
        except Exception:  # noqa: BLE001 — backend probe is best-effort
            return None

    def rtt_probe_ms(self, *, force: bool = False) -> Optional[float]:
        """Median of 4 tiny scalar device round trips — the transport
        cost a sync readback pays (axon tunnel ~70ms, CPU ~µs). TTL
        cached PER device_kind (a CPU-fallback process that later reaches
        the TPU re-probes instead of reusing the µs CPU number); NEVER
        triggers backend init, so it returns None until real device work
        has run."""
        kind = self._backend_kind()
        now = self._clock()
        if kind is None:
            # no backend yet: keep the old TTL-on-failure behavior so a
            # flapping tunnel isn't probed on every snapshot
            if not force and now - self._rtt_at < self.RTT_PROBE_TTL_S:
                return None
            self._rtt_at = now
            self._rtt_ms = None
            self._rtt_kind = None
            return None
        cached = self._rtt_cache.get(kind)
        if not force and cached is not None \
                and now - cached[1] < self.RTT_PROBE_TTL_S:
            self._rtt_ms, self._rtt_kind = cached[0], kind
            return cached[0]
        try:
            import jax
            import numpy as np
            samples = []
            for _ in range(4):
                t0 = time.perf_counter()
                np.asarray(jax.device_put(np.zeros(1, np.int32)))
                samples.append(time.perf_counter() - t0)
            samples.sort()
            ms = round(samples[len(samples) // 2] * 1e3, 4)
        except Exception:  # noqa: BLE001 — tunnel down mid-probe
            ms = None
        self._rtt_cache[kind] = (ms, now)
        self._rtt_ms = ms
        self._rtt_kind = kind
        self._rtt_at = now
        return ms

    def split_snapshot(self, *, probe: bool = True) -> dict:
        """The rtt/kernel decomposition over the retained ring: stage
        p50/p99 plus the tunnel-RTT estimate and the residual kernel
        time (ready-wait minus transport). ``probe=False`` uses only
        the cached RTT (never touches the device) — the advisory-tick
        persistence path runs on the broker's event loop and must not
        stall it behind 4 tunnel round trips; operator-initiated
        scrapes (``GET /profile``, bench) pay the TTL-cached probe."""
        recs = self.records()
        out: Dict[str, object] = {"window_batches": len(recs)}
        for stage in ("tokenize_s", "dispatch_s", "ready_s", "fetch_s",
                      "expand_s", "dev_expand_s"):
            vals = sorted(getattr(r, stage) for r in recs)
            key = stage[:-2]
            out[f"{key}_ms_p50"] = round(_pctl(vals, 0.50) * 1e3, 4)
            out[f"{key}_ms_p99"] = round(_pctl(vals, 0.99) * 1e3, 4)
        if probe:
            rtt = self.rtt_probe_ms()
            kind = self._rtt_kind
        else:
            # cached-only path: still resolve the CURRENT backend's slot
            # so a backend change never serves the other backend's RTT
            kind = self._backend_kind()
            rtt = (self._rtt_cache.get(kind, (None, 0.0))[0]
                   if kind is not None else None)
        out["tunnel_rtt_ms"] = rtt
        out["rtt_device_kind"] = kind
        ready_p50 = out["ready_ms_p50"]
        fetch_p50 = out["fetch_ms_p50"]
        if rtt is not None:
            # the ready wait covers kernel compute + the readiness
            # round trip; the fetch pays the final host copy
            out["device_kernel_ms_est"] = round(
                max(0.0, ready_p50 + fetch_p50 - rtt), 4)
        else:
            out["device_kernel_ms_est"] = round(ready_p50 + fetch_p50, 4)
        kernels: Dict[str, int] = {}
        for r in recs:
            kernels[r.kernel] = kernels.get(r.kernel, 0) + 1
        out["kernels"] = kernels
        return out

    def snapshot(self, *, brief: bool = False,
                 probe: bool = True) -> dict:
        walked = self.queries_total
        padded = self.padded_rows_total
        fe = self.frontend_queries_total
        out = {
            "batches": self.batches_total,
            "queries": walked,
            "padding_waste_ratio": round(
                padded / max(1, walked + padded), 4),
            "cache_bypass_rate": round(
                self.cache_hits_total / max(1, fe), 4),
            "dedup_saved": self.dedup_saved_total,
            "degraded": dict(self.degraded_total),
            "split": self.split_snapshot(probe=probe),
            "compile_ledger": self.ledger.snapshot(
                limit=4 if brief else 16),
        }
        if not brief:
            out["emit"] = {
                "batches": self.emits_total,
                "avg_batch": round(self.emit_calls_total
                                   / max(1, self.emits_total), 2),
                "avg_cap": round(self.emit_cap_total
                                 / max(1, self.emits_total), 2),
                "avg_depth_at_emit": round(self.emit_depth_total
                                           / max(1, self.emits_total),
                                           2),
            }
            out["recent"] = [r.to_dict() for r in self.records(8)]
        return out

    def reset(self) -> None:
        self._ring.clear()
        self.ledger.reset()
        self.batches_total = 0
        self.queries_total = 0
        self.padded_rows_total = 0
        self.cache_hits_total = 0
        self.dedup_saved_total = 0
        self.frontend_queries_total = 0
        self.degraded_total = {}
        self.emits_total = 0
        self.emit_calls_total = 0
        self.emit_cap_total = 0
        self.emit_depth_total = 0
        self._rtt_cache = {}
        self._rtt_ms = None
        self._rtt_kind = None
        self._rtt_at = -1e18
