"""Always-on continuous profiler for the device match path (ISSUE 8
tentpole, part 2).

PR 6's async pipeline left "where do the microseconds go between
dispatch and fetch on a ~70ms-RTT tunnel" answerable only by an offline
bench run. This module keeps the answer live, at a cost the pipelined
path cannot feel (<2% — the recording site is a handful of attribute
increments plus one ring store, the ``SpanRing`` discipline: GIL-atomic
enough for telemetry, no locks, no allocation beyond the record):

- **Per-batch stage decomposition.** Every device batch (sync or async)
  records its dispatch / ready / fetch / expand seconds plus batch
  geometry (queries vs padded rows) and the kernel that served it. The
  snapshot splits the wall time into a tunnel-RTT estimate (a tiny
  TTL-cached scalar round trip, same guarded-probe discipline as the
  memory watermarks — CPU pays microseconds, the axon tunnel ~70ms) and
  the residual device-kernel time, so CPU-fallback and real-TPU records
  stay comparable.
- **Efficiency counters.** Padding waste (pow2 pad rows that walk for
  nothing), in-batch dedup savings and cache-hit bypasses (rows that
  never reached the device), batcher emit occupancy, and degraded
  serves by reason.
- **Compile-event ledger.** Every base install is attributable: what
  triggered it (first_base / threshold / forced / refresh), how long the
  compile ran, the table salt, node count, table bytes, the fused VMEM
  verdict, and whether it bumped the match-cache generation — so a
  rebuild storm reads as a sequence of causes, not a mystery latency
  cliff.

Records drain into the bounded segment store (``obs.segstore``) via
``since()`` cursors for post-hoc analysis after a TPU session ends.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..trace.recorder import SpanRing


class BatchRecord:
    """One device batch's profile. Plain slots — built once per batch on
    the serving path, so no dataclass/dict overhead."""

    __slots__ = ("ts", "n_queries", "batch", "kernel", "path",
                 "dispatch_s", "ready_s", "fetch_s", "expand_s",
                 "degraded")

    def __init__(self, ts, n_queries, batch, kernel, path, dispatch_s,
                 ready_s, fetch_s, expand_s, degraded) -> None:
        self.ts = ts
        self.n_queries = n_queries
        self.batch = batch
        self.kernel = kernel
        self.path = path
        self.dispatch_s = dispatch_s
        self.ready_s = ready_s
        self.fetch_s = fetch_s
        self.expand_s = expand_s
        self.degraded = degraded

    def to_dict(self) -> dict:
        return {"ts": round(self.ts, 3), "n_queries": self.n_queries,
                "batch": self.batch, "kernel": self.kernel,
                "path": self.path,
                "dispatch_ms": round(self.dispatch_s * 1e3, 4),
                "ready_ms": round(self.ready_s * 1e3, 4),
                "fetch_ms": round(self.fetch_s * 1e3, 4),
                "expand_ms": round(self.expand_s * 1e3, 4),
                "degraded": self.degraded}


class CompileLedger:
    """Bounded ledger of base-install events (ISSUE 8: rebuild storms
    must be attributable). Appended from the matcher's install path —
    once per compile, so a deque with a lock-free append is plenty."""

    CAP = 256

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._clock = clock
        self._events: deque = deque(maxlen=self.CAP)
        self.total = 0
        self.total_compile_s = 0.0
        self.generation_bumps = 0

    def record(self, *, reason: str, duration_s: float, salt,
               n_nodes: int, table_bytes: int,
               vmem_fits: Optional[bool],
               generation_bumped: bool, kind: str = "single") -> None:
        self.total += 1
        self.total_compile_s += duration_s
        if generation_bumped:
            self.generation_bumps += 1
        self._events.append({
            "ts": round(self._clock(), 3),
            "reason": reason,
            "compile_s": round(duration_s, 4),
            "salt": salt,
            "n_nodes": n_nodes,
            "table_bytes": table_bytes,
            "vmem_fits": vmem_fits,
            "generation_bumped": generation_bumped,
            "kind": kind,
        })

    def events(self, limit: int = 0) -> List[dict]:
        evs = list(self._events)
        return evs[-limit:] if limit > 0 else evs

    def snapshot(self, limit: int = 16) -> dict:
        return {"total": self.total,
                "total_compile_s": round(self.total_compile_s, 3),
                "generation_bumps": self.generation_bumps,
                "events": self.events(limit)}

    def reset(self) -> None:
        self._events.clear()
        self.total = 0
        self.total_compile_s = 0.0
        self.generation_bumps = 0


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class ContinuousProfiler:
    RING_CAP = 2048
    RTT_PROBE_TTL_S = 30.0

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._clock = clock
        # the tracer's fixed-slot ring is record-type-agnostic — reuse
        # it (record/spans/since cursor math in ONE place) rather than
        # re-deriving the wrap/missed arithmetic here
        self._ring = SpanRing(self.RING_CAP)
        self.ledger = CompileLedger(clock=clock)
        # counters (monotonic; plain int adds on the hot path)
        self.batches_total = 0
        self.queries_total = 0
        self.padded_rows_total = 0
        self.cache_hits_total = 0
        self.dedup_saved_total = 0
        self.frontend_queries_total = 0
        self.degraded_total: Dict[str, int] = {}
        self.emits_total = 0
        self.emit_calls_total = 0
        self.emit_cap_total = 0
        self.emit_depth_total = 0
        # tunnel-RTT probe cache (guarded: never triggers backend init)
        self._rtt_ms: Optional[float] = None
        self._rtt_at = -1e18

    # ---------------- hot-path recording (the <2% budget) ------------------

    def record_batch(self, *, n_queries: int, batch: int, kernel: str,
                     dispatch_s: float, ready_s: float = 0.0,
                     fetch_s: float = 0.0, expand_s: float = 0.0,
                     path: str = "async",
                     degraded: Optional[str] = None) -> None:
        self.batches_total += 1
        self.queries_total += n_queries
        self.padded_rows_total += max(0, batch - n_queries)
        if degraded is not None:
            self.degraded_total[degraded] = \
                self.degraded_total.get(degraded, 0) + 1
        self._ring.record(BatchRecord(
            self._clock(), n_queries, batch, kernel, path,
            dispatch_s, ready_s, fetch_s, expand_s, degraded))

    def record_frontend(self, n_queries: int, hits: int,
                        dedup_saved: int) -> None:
        """Cache-plane bypasses: rows that never reached the device."""
        self.frontend_queries_total += n_queries
        self.cache_hits_total += hits
        self.dedup_saved_total += dedup_saved

    def record_emit(self, batch_size: int, cap: int, depth: int) -> None:
        """Batcher emit occupancy (scheduler side of padding waste: a
        batch far under its adaptive cap pads more downstream) plus the
        queue depth observed at emit (the saturation signal _adapt
        keys on)."""
        self.emits_total += 1
        self.emit_calls_total += batch_size
        self.emit_cap_total += cap
        self.emit_depth_total += depth

    # ---------------- snapshots --------------------------------------------

    def records(self, limit: int = 0) -> List[BatchRecord]:
        out = self._ring.spans()        # oldest first (generic ring)
        return out[-limit:] if limit > 0 else out

    def since(self, cursor: int):
        """Records after write-counter ``cursor`` (oldest first), the new
        cursor, and how many were overwritten unread — the segment
        store's incremental drain (``SpanRing.since``'s contract,
        verbatim, because it IS that implementation)."""
        return self._ring.since(cursor)

    def rtt_probe_ms(self, *, force: bool = False) -> Optional[float]:
        """Median of 4 tiny scalar device round trips — the transport
        cost a sync readback pays (axon tunnel ~70ms, CPU ~µs). TTL
        cached; NEVER triggers backend init (a dead tunnel would hang
        it), so it returns None until real device work has run."""
        now = self._clock()
        if not force and now - self._rtt_at < self.RTT_PROBE_TTL_S:
            return self._rtt_ms
        self._rtt_at = now
        try:
            import sys
            if "jax" not in sys.modules:
                raise LookupError("jax not loaded")
            import jax
            from jax._src import xla_bridge as _xb
            if not getattr(_xb, "_backends", None):
                raise LookupError("jax backend not initialized")
            import numpy as np
            samples = []
            for _ in range(4):
                t0 = time.perf_counter()
                np.asarray(jax.device_put(np.zeros(1, np.int32)))
                samples.append(time.perf_counter() - t0)
            samples.sort()
            self._rtt_ms = round(samples[len(samples) // 2] * 1e3, 4)
        except Exception:  # noqa: BLE001 — tunnel down / jax absent
            self._rtt_ms = None
        return self._rtt_ms

    def split_snapshot(self, *, probe: bool = True) -> dict:
        """The rtt/kernel decomposition over the retained ring: stage
        p50/p99 plus the tunnel-RTT estimate and the residual kernel
        time (ready-wait minus transport). ``probe=False`` uses only
        the cached RTT (never touches the device) — the advisory-tick
        persistence path runs on the broker's event loop and must not
        stall it behind 4 tunnel round trips; operator-initiated
        scrapes (``GET /profile``, bench) pay the TTL-cached probe."""
        recs = self.records()
        out: Dict[str, object] = {"window_batches": len(recs)}
        for stage in ("dispatch_s", "ready_s", "fetch_s", "expand_s"):
            vals = sorted(getattr(r, stage) for r in recs)
            key = stage[:-2]
            out[f"{key}_ms_p50"] = round(_pctl(vals, 0.50) * 1e3, 4)
            out[f"{key}_ms_p99"] = round(_pctl(vals, 0.99) * 1e3, 4)
        rtt = self.rtt_probe_ms() if probe else self._rtt_ms
        out["tunnel_rtt_ms"] = rtt
        ready_p50 = out["ready_ms_p50"]
        fetch_p50 = out["fetch_ms_p50"]
        if rtt is not None:
            # the ready wait covers kernel compute + the readiness
            # round trip; the fetch pays the final host copy
            out["device_kernel_ms_est"] = round(
                max(0.0, ready_p50 + fetch_p50 - rtt), 4)
        else:
            out["device_kernel_ms_est"] = round(ready_p50 + fetch_p50, 4)
        kernels: Dict[str, int] = {}
        for r in recs:
            kernels[r.kernel] = kernels.get(r.kernel, 0) + 1
        out["kernels"] = kernels
        return out

    def snapshot(self, *, brief: bool = False,
                 probe: bool = True) -> dict:
        walked = self.queries_total
        padded = self.padded_rows_total
        fe = self.frontend_queries_total
        out = {
            "batches": self.batches_total,
            "queries": walked,
            "padding_waste_ratio": round(
                padded / max(1, walked + padded), 4),
            "cache_bypass_rate": round(
                self.cache_hits_total / max(1, fe), 4),
            "dedup_saved": self.dedup_saved_total,
            "degraded": dict(self.degraded_total),
            "split": self.split_snapshot(probe=probe),
            "compile_ledger": self.ledger.snapshot(
                limit=4 if brief else 16),
        }
        if not brief:
            out["emit"] = {
                "batches": self.emits_total,
                "avg_batch": round(self.emit_calls_total
                                   / max(1, self.emits_total), 2),
                "avg_cap": round(self.emit_cap_total
                                 / max(1, self.emits_total), 2),
                "avg_depth_at_emit": round(self.emit_depth_total
                                           / max(1, self.emits_total),
                                           2),
            }
            out["recent"] = [r.to_dict() for r in self.records(8)]
        return out

    def reset(self) -> None:
        self._ring.clear()
        self.ledger.reset()
        self.batches_total = 0
        self.queries_total = 0
        self.padded_rows_total = 0
        self.cache_hits_total = 0
        self.dedup_saved_total = 0
        self.frontend_queries_total = 0
        self.degraded_total = {}
        self.emits_total = 0
        self.emit_calls_total = 0
        self.emit_cap_total = 0
        self.emit_depth_total = 0
        self._rtt_ms = None
        self._rtt_at = -1e18
