"""Tenant SLO observability (ISSUE 3): windowed RED metrics, noisy-neighbor
detection, device-pipeline gauges, and push telemetry export.

The process-global ``OBS`` hub is the single attachment point:

- hot-path sites call ``OBS.record_*`` (one ``enabled`` check when the
  window layer is off — same no-op discipline as the tracer);
- ``MeteringEventCollector`` forwards every metered tenant flow/error;
- the API server serves ``GET /tenants`` (+ per-tenant detail) from the
  detector and folds ``OBS.device.snapshot()`` into ``/metrics``;
- the broker starts/stops the push exporter from env knobs
  (``BIFROMQ_OBS_EXPORT`` file path or ``BIFROMQ_OBS_EXPORT_URL`` HTTP
  sink, ``BIFROMQ_OBS_EXPORT_INTERVAL_S``, ``BIFROMQ_OBS_EXPORT_CAP``,
  ``BIFROMQ_OBS_EXPORT_SAMPLED=1`` to also ship sampled spans).

``BIFROMQ_OBS_WINDOWS=0`` disables the window layer entirely (records
become a single attribute check); the detector then reports nothing.

Env knobs are read ONCE when the hub is constructed at import (the same
discipline as ``trace.TRACER``'s ``BIFROMQ_TRACE_*``); everything is
reconfigurable at runtime through ``PUT /obs`` or the hub's attributes.

Layering: ``utils.metrics`` imports this package (feeding flows/errors
and sharing the log2 bucket math in ``window``); nothing in ``obs`` may
import ``utils.metrics`` — that would close an import cycle.
"""

from __future__ import annotations

import os
import time
import weakref
from typing import Callable, Optional

from ..utils.env import (env_bool as _env_bool, env_float as _env_float,
                         env_str as _env_str)
from .burnrate import BurnRateEngine
from .device import DeviceGauges
from .e2e import E2EPlane, ShardCompletionBoard
from .exporter import FileSink, HTTPSink, TelemetryExporter
from .neighbor import NoisyNeighborDetector
from .profiler import CompileLedger, ContinuousProfiler
from .segstore import SegmentStore
from .slo import TenantSLO
from .window import WindowedCounter, WindowedLog2Histogram


class ObsHub:
    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 window_s: Optional[float] = None) -> None:
        self.enabled = _env_bool("BIFROMQ_OBS_WINDOWS", True)
        ws = window_s or _env_float("BIFROMQ_OBS_WINDOW_S", 10.0)
        if ws <= 0:
            # a bad telemetry knob must never crash the publish hot path
            # (TenantSLO would raise on the first record)
            import logging
            logging.getLogger(__name__).error(
                "BIFROMQ_OBS_WINDOW_S=%r invalid; using 10.0", ws)
            ws = 10.0
        self.windows = TenantSLO(window_s=ws, clock=clock)
        self.detector = NoisyNeighborDetector(
            self.windows,
            slow_p99_ms=_env_float("BIFROMQ_OBS_SLO_MS", 1000.0),
            clock=clock)
        self.device = DeviceGauges(clock=clock)
        # ISSUE 20: full-population publish→deliver latency plane +
        # multi-window burn-rate SLO engine riding the same clock
        self.e2e = E2EPlane(window_s=ws, clock=clock)
        self.burnrate = BurnRateEngine(clock=clock)
        # ISSUE 8: always-on continuous profiler (per-batch stage split,
        # padding/dedup/cache efficiency, compile-event ledger) — wall
        # clock, not the hub's monotonic: its records persist across
        # process restarts and must be comparable post-hoc
        self.profiler = ContinuousProfiler()
        # ISSUE 8: bounded segment-file store for post-hoc analysis
        # (armed by start_persistence from env knobs)
        self.store: Optional[SegmentStore] = None
        self._store_refs = 0
        self._store_prof_cursor = 0
        self._store_slow_cursor = 0
        self._store_ledger_cursor = 0
        # ISSUE 18: delta-plane event journal drain (lag transitions,
        # parity audits, autoscaler decisions) into the same store
        self._store_repl_cursor = -1
        # ISSUE 20: SLO burn/recovery journal drain
        self._store_slo_cursor = -1
        self.exporter: Optional[TelemetryExporter] = None
        self._exporter_refs = 0
        self._registry_ref = None       # weakref to a MetricsRegistry
        # throttler-advisory background refresh (ISSUE 4 satellite): when
        # armed, the detector's flag set refreshes on this tick instead of
        # lazily on the connect/publish guard path
        self._advisory_task = None
        self._advisory_refs = 0
        self._advisory_interval = float("inf")
        # extra callbacks run on each advisory tick (ISSUE 5: the cluster
        # view refreshes its gossiped health digest here)
        self._tick_hooks: list = []
        # node identity for federated sinks (ISSUE 5 satellite): stamped
        # into every exporter record's resource envelope; the starter
        # overrides from the cluster config
        self.node_id = _env_str("BIFROMQ_NODE_ID") or f"pid-{os.getpid()}"
        self.cluster_id = _env_str("BIFROMQ_CLUSTER_ID")

    # ---------------- hot-path recording -----------------------------------

    def record_flow(self, tenant: str, n: float = 1.0) -> None:
        if self.enabled:
            self.windows.record_flow(tenant, n)

    def record_error(self, tenant: str, n: float = 1.0) -> None:
        if self.enabled:
            self.windows.record_error(tenant, n)

    def record_fanout(self, tenant: str, n: float) -> None:
        if self.enabled:
            self.windows.record_fanout(tenant, n)

    def record_queue_wait(self, tenant: str, seconds: float) -> None:
        if self.enabled:
            self.windows.record_queue_wait(tenant, seconds)

    def record_latency(self, tenant: str, stage: str,
                       seconds: float) -> None:
        if self.enabled:
            self.windows.record_latency(tenant, stage, seconds)

    def record_match_cache(self, tenant: str, hits: int,
                           misses: int) -> None:
        """Match-result cache lookups (ISSUE 4): feeds the per-tenant hit
        rate in ``GET /tenants``."""
        if self.enabled and (hits or misses):
            self.windows.record_match_cache(tenant, hits, misses)

    def record_delivery(self, tenant: str, qos: int, path: str,
                        publish_hlc: int) -> None:
        """ISSUE 20: one delivered message's publish-HLC→socket-write
        latency — full population, every delivery site calls this."""
        if self.enabled:
            seconds = self.e2e.record(tenant, qos, path, publish_hlc)
            # a retained replay's "latency" is the retained message's AGE
            # (publish may predate the SUBSCRIBE by hours) — it counts
            # toward delivery success but never as a latency-target miss
            self.burnrate.observe(
                tenant, 0.0 if path == "retained" else seconds)

    def record_delivery_violation(self, tenant: str, qos: int,
                                  reason: str) -> None:
        """ISSUE 20: a delivery that failed (expiry/discard/drop/shed/
        overflow) — counted against the tenant's SLO budget."""
        if self.enabled:
            self.e2e.record_violation(tenant, qos, reason)
            self.burnrate.observe_violation(tenant)

    # ---------------- wiring ------------------------------------------------

    def bind_events(self, collector) -> None:
        """Give the detector an event outlet (NOISY_TENANT/SLOW_TENANT)
        and the burn engine its SLO_BURN/SLO_RECOVERED outlet. Called by
        MeteringEventCollector so offender events ride the same stream
        operators already collect."""
        self.detector.events = collector
        self.burnrate.events = collector

    def register_pub_cache(self, cache) -> None:
        """ISSUE 12: the dist service registers its pub-side match cache
        so the gossip digest can ship the node's hot (tenant, topic) key
        set — a failover target pre-warms against it before taking
        traffic. Weakly held: a torn-down service must not pin its cache."""
        self._pub_cache_ref = weakref.ref(cache)

    def pub_cache(self):
        ref = getattr(self, "_pub_cache_ref", None)
        return ref() if ref is not None else None

    # ---------------- retained & session plane (ISSUE 13) -------------------

    def register_retained_plane(self, plane) -> None:
        """Weakly track a retained scan plane so ``/metrics`` can serve
        a "retained" section (scans/degradations/cache efficiency per
        range replica) without pinning torn-down services."""
        if not hasattr(self, "_retained_planes"):
            self._retained_planes = weakref.WeakSet()
        self._retained_planes.add(plane)

    def register_drain_governor(self, gov) -> None:
        if not hasattr(self, "_drain_governors"):
            self._drain_governors = weakref.WeakSet()
        self._drain_governors.add(gov)

    def drain_pressure(self) -> float:
        """Worst drain-governor occupancy on this node — (active +
        waiting) / capacity; >1.0 means reconnects are queueing. Gossiped
        in the health digest (ISSUE 15 satellite) so a clustered
        reconnect storm sheds toward quieter peers."""
        worst = 0.0
        for g in list(getattr(self, "_drain_governors", ()) or ()):
            try:
                worst = max(worst, g.pressure())
            except Exception:  # noqa: BLE001 — telemetry must not raise
                continue
        return round(worst, 3)

    def retained_snapshot(self) -> dict:
        """The ``/metrics`` "retained" section: every live scan plane's
        serve/degrade/cache counters + every drain governor's admission
        state (best-effort; introspection must never raise)."""
        planes = []
        for p in list(getattr(self, "_retained_planes", ()) or ()):
            try:
                planes.append(p.snapshot())
            except Exception:  # noqa: BLE001
                continue
        drains = []
        for g in list(getattr(self, "_drain_governors", ()) or ()):
            try:
                drains.append(g.snapshot())
            except Exception:  # noqa: BLE001
                continue
        return {"scan_planes": planes, "drain_governors": drains}

    def mesh_snapshot(self) -> list:
        """The ``/metrics`` "mesh" section + the digest ``mesh`` field:
        every live mesh matcher's shard-load rows, skew, map version and
        in-flight migrations (ISSUE 17; introspection must never raise).
        Single-chip matchers (no ``mesh_status``) are skipped."""
        out = []
        for m in self.device.matchers():
            status = getattr(m, "mesh_status", None)
            if status is None:
                continue
            try:
                s = status()
            except Exception:  # noqa: BLE001 — telemetry must not raise
                continue
            if s.get("n_shards", 0) > 1 or s.get("shard_load"):
                out.append(s)
        return out

    def bind_registry(self, registry) -> None:
        """Weakly remember the metrics registry so exporter snapshots can
        include the monotonic per-tenant counters."""
        self._registry_ref = weakref.ref(registry)

    def is_noisy(self, tenant: str) -> bool:
        """Throttler advisory: is this tenant currently flagged noisy?"""
        return self.enabled and self.detector.is_noisy(tenant)

    def is_burning(self, tenant: str) -> bool:
        """Shedder advisory (ISSUE 20): is this tenant's SLO budget
        burning? A set probe — evaluation happens on the advisory tick."""
        return self.enabled and self.burnrate.is_burning(tenant)

    def set_identity(self, node_id: Optional[str] = None,
                     cluster_id: Optional[str] = None) -> None:
        """Pin the node/cluster identity federated sinks attribute by."""
        if node_id:
            self.node_id = node_id
        if cluster_id is not None:
            self.cluster_id = cluster_id

    def resource_envelope(self) -> dict:
        """The per-record attribution envelope (ISSUE 5 satellite)."""
        from .exporter import SCHEMA_VERSION
        return {"node_id": self.node_id,
                "cluster_id": self.cluster_id,
                "schema_version": SCHEMA_VERSION}

    def on_advisory_tick(self, cb: Callable[[], None]) -> None:
        """Run ``cb`` on every advisory tick (after the detector refresh).
        Idempotent per callback."""
        if cb not in self._tick_hooks:
            self._tick_hooks.append(cb)

    def remove_advisory_hook(self, cb: Callable[[], None]) -> None:
        try:
            self._tick_hooks.remove(cb)
        except ValueError:
            pass

    # ---------------- snapshots --------------------------------------------

    def tenants_snapshot(self, top_k: int = 10, emit: bool = True) -> dict:
        rows = (self.detector.evaluate(top_k=top_k, emit=emit)
                if self.enabled else [])
        return {"window_s": self.windows.window_s,
                "enabled": self.enabled,
                "top_k": top_k,
                "tenants": rows}

    def device_snapshot(self, *, memory: bool = True) -> dict:
        return self.device.snapshot(memory=memory)

    def obs_snapshot(self) -> dict:
        out = {"windows_enabled": self.enabled}
        if self.exporter is not None:
            out["exporter"] = self.exporter.snapshot()
        if self.store is not None:
            out["store"] = self.store.snapshot()
        return out

    def profile_snapshot(self, *, brief: bool = False,
                         probe: bool = False) -> dict:
        """The ``GET /profile`` payload (ISSUE 8): rtt/kernel split,
        padding/dedup/cache efficiency, compile ledger, store state.
        ``probe=False`` by default: this serves from a sync handler on
        the broker's event loop, where 4 tunnel round trips (~280ms on
        axon) would stall every session — scrape loops get the cached
        RTT; an operator opts into a fresh probe explicitly."""
        out = self.profiler.snapshot(brief=brief, probe=probe)
        if self.store is not None and not brief:
            out["store"] = self.store.snapshot()
        return out

    def _export_snapshot(self) -> dict:
        """One exporter 'metrics' record: windowed SLO + device + the
        bound registry's monotonic counters (when still alive)."""
        out = {"slo": self.windows.snapshot() if self.enabled else {},
               "device": self.device_snapshot(memory=False)}
        if self.enabled:
            # ISSUE 20: e2e latency distributions + burn-rate state ride
            # every exporter metrics record in both framings
            out["e2e"] = self.e2e.snapshot()
            out["slo_burn"] = self.burnrate.snapshot()
        reg = self._registry_ref() if self._registry_ref else None
        if reg is not None:
            try:
                # the registry snapshot is counters/fabric/stages only
                # (composition of device/obs sections lives in the API
                # server) — the flush loop never runs the jax memory probe
                out["registry"] = reg.snapshot()
            except Exception:  # noqa: BLE001 — telemetry must not raise
                pass
        return out

    # ---------------- exporter lifecycle -----------------------------------

    def exporter_from_env(self) -> Optional[TelemetryExporter]:
        path = _env_str("BIFROMQ_OBS_EXPORT")
        url = _env_str("BIFROMQ_OBS_EXPORT_URL")
        if not path and not url:
            return None
        framing = _env_str("BIFROMQ_OBS_FORMAT", "jsonl").lower()
        if framing not in ("jsonl", "otlp"):
            import logging
            logging.getLogger(__name__).error(
                "BIFROMQ_OBS_FORMAT=%r unknown; using jsonl", framing)
            framing = "jsonl"
        try:
            sink = HTTPSink(url) if url else FileSink(path)
        except ValueError as e:
            # a bad telemetry knob must not abort broker startup
            import logging
            logging.getLogger(__name__).error(
                "telemetry export disabled: %s", e)
            return None
        return TelemetryExporter(
            sink,
            interval_s=_env_float("BIFROMQ_OBS_EXPORT_INTERVAL_S", 2.0),
            queue_cap=int(_env_float("BIFROMQ_OBS_EXPORT_CAP", 2048)),
            export_sampled=_env_bool("BIFROMQ_OBS_EXPORT_SAMPLED", False),
            snapshot_fn=self._export_snapshot,
            resource=self.resource_envelope(),
            framing=framing)

    def start_exporter(self,
                       exporter: Optional[TelemetryExporter] = None) -> bool:
        """Refcounted start (several brokers may share the process-global
        hub in tests): the first caller creates/starts, later callers just
        bump the count. Returns whether a ref was ACQUIRED — a caller
        whose start was a no-op (no sink configured at the time) must not
        release someone else's ref at stop."""
        if self.exporter is None:
            exporter = exporter or self.exporter_from_env()
            if exporter is None:
                return False
            self.exporter = exporter
            self.exporter.start()
        self._exporter_refs += 1
        return True

    async def stop_exporter(self) -> None:
        if self.exporter is None:
            return
        self._exporter_refs -= 1
        if self._exporter_refs <= 0:
            exp, self.exporter = self.exporter, None
            self._exporter_refs = 0
            await exp.stop()

    # ---------------- segment-store persistence (ISSUE 8) ------------------

    def store_from_env(self) -> Optional[SegmentStore]:
        """Build the segment store from env knobs: ``BIFROMQ_OBS_STORE``
        (directory; empty = disabled), ``BIFROMQ_OBS_STORE_SEGMENT_BYTES``
        and ``BIFROMQ_OBS_STORE_SEGMENTS`` (retention)."""
        path = _env_str("BIFROMQ_OBS_STORE")
        if not path:
            return None
        try:
            return SegmentStore(
                path,
                max_segment_bytes=int(_env_float(
                    "BIFROMQ_OBS_STORE_SEGMENT_BYTES", float(1 << 20))),
                max_segments=int(_env_float(
                    "BIFROMQ_OBS_STORE_SEGMENTS", 8.0)))
        except (ValueError, OSError) as e:
            # a bad persistence knob must not abort broker startup
            import logging
            logging.getLogger(__name__).error(
                "telemetry store disabled: %s", e)
            return None

    def start_persistence(self,
                          store: Optional[SegmentStore] = None) -> bool:
        """Refcounted start (same contract as the exporter): the first
        caller creates the store and hooks the flush onto the advisory
        tick; returns whether a ref was acquired."""
        if self.store is None:
            store = store or self.store_from_env()
            if store is None:
                return False
            self.store = store
            self.on_advisory_tick(self.persist_now)
        self._store_refs += 1
        return True

    def stop_persistence(self, final_flush: bool = True) -> None:
        if self.store is None:
            return
        self._store_refs -= 1
        if self._store_refs > 0:
            return
        self._store_refs = 0
        self.remove_advisory_hook(self.persist_now)
        if final_flush:
            try:
                self.persist_now()
            except Exception:  # noqa: BLE001
                pass
        self.store = None

    def persist_now(self) -> int:
        """Flush everything new — profiler batch records, compile-ledger
        events, slow spans — into the segment store as typed records.
        Incremental via cursors (the same ``since`` discipline as the
        push exporter's ring drains); returns records written."""
        store = self.store
        if store is None:
            return 0
        out = []
        recs, self._store_prof_cursor, _ = \
            self.profiler.since(self._store_prof_cursor)
        for r in recs:
            out.append({"type": "profile", **r.to_dict()})
        events = self.profiler.ledger.events()
        n_new = self.profiler.ledger.total - self._store_ledger_cursor
        for e in (events[-min(n_new, len(events)):] if n_new > 0 else []):
            out.append({"type": "compile", **e})
        self._store_ledger_cursor = self.profiler.ledger.total
        from .. import trace
        spans, self._store_slow_cursor, _ = \
            trace.TRACER.slow_ring.since(self._store_slow_cursor)
        for s in spans:
            out.append({"type": "span", **s.to_dict()})
        # ISSUE 18: lag-stale transitions, gaps/resyncs, parity audits
        # and autoscaler decisions — the post-hoc reader reconstructs
        # WHY the delta plane resynced or the mesh scaled
        from .lag import REPL_EVENTS
        evs, self._store_repl_cursor = \
            REPL_EVENTS.since(self._store_repl_cursor)
        for e in evs:
            out.append({"type": "repl_event", **e})
        # ISSUE 20: SLO burn/recovery transitions — the post-hoc reader
        # lines budget burns up against the profile/span records
        from .burnrate import SLO_EVENTS
        sevs, self._store_slo_cursor = \
            SLO_EVENTS.since(self._store_slo_cursor)
        for e in sevs:
            out.append({"type": "slo_event", **e})
        if out:
            # one summary record per flush stamps the aggregate view the
            # post-hoc reader anchors on; probe=False — this runs on the
            # broker's event loop every advisory tick and must never
            # stall behind tunnel round trips (cached RTT only)
            out.append({"type": "profile_summary",
                        "resource": self.resource_envelope(),
                        **self.profiler.snapshot(brief=True,
                                                 probe=False)})
        return store.append_many(out)

    # ---------------- throttler-advisory tick (ISSUE 4 satellite) ----------

    def start_advisory_tick(self,
                            interval_s: Optional[float] = None) -> None:
        """Refcounted background flag refresh: arming a
        ``SLOAdvisedResourceThrottler`` on a max-tenant deployment must not
        pay a full detector evaluation on the publish/connect guard path —
        the tick evaluates off-path and ``is_noisy`` becomes a set probe.

        Re-arming with a SHORTER interval restarts the shared task at the
        faster cadence (ISSUE 5: the cluster view's digest refresh must
        honor ``BIFROMQ_CLUSTER_OBS_INTERVAL_S`` even when the broker
        armed the tick first for the throttler advisory)."""
        import asyncio

        self._advisory_refs += 1
        if self._advisory_task is not None:
            if interval_s is not None and interval_s < self._advisory_interval:
                task, self._advisory_task = self._advisory_task, None
                task.cancel()
            else:
                return
        interval = interval_s or self.detector.advisory_ttl_s
        self._advisory_interval = interval
        self.detector.tick_armed = True

        async def loop() -> None:
            while True:
                await asyncio.sleep(interval)
                try:
                    # evaluate even with the window layer disabled: the
                    # decayed (or empty) windows then CLEAR stale noisy
                    # flags instead of freezing them — ObsHub.is_noisy
                    # short-circuits on enabled, but the flag set must
                    # not go stale for a later re-enable
                    self.detector.evaluate(emit=False)
                except Exception:  # noqa: BLE001 — telemetry must not die
                    import logging
                    logging.getLogger(__name__).exception("advisory tick")
                try:
                    # ISSUE 20: burn-rate transitions fire off-path here
                    # (same decay argument: windows must keep clearing)
                    self.burnrate.evaluate()
                except Exception:  # noqa: BLE001 — telemetry must not die
                    import logging
                    logging.getLogger(__name__).exception("burn evaluate")
                for cb in list(self._tick_hooks):
                    try:
                        cb()
                    except Exception:  # noqa: BLE001
                        import logging
                        logging.getLogger(__name__).exception(
                            "advisory tick hook")

        self._advisory_task = asyncio.get_event_loop().create_task(loop())

    async def stop_advisory_tick(self) -> None:
        if self._advisory_task is None:
            return
        self._advisory_refs -= 1
        if self._advisory_refs > 0:
            return
        task, self._advisory_task = self._advisory_task, None
        self._advisory_refs = 0
        self._advisory_interval = float("inf")
        self.detector.tick_armed = False
        task.cancel()
        try:
            await task
        except BaseException:  # noqa: BLE001 — cancellation
            pass

    def reset(self) -> None:
        """Test isolation: drop all windows/flags/gauges (exporter and
        advisory tick left to their owners)."""
        self.windows.reset()
        self.detector.reset()
        self.device.reset()
        self.profiler.reset()
        self._store_prof_cursor = 0
        self._store_slow_cursor = 0
        self._store_ledger_cursor = 0
        self._store_repl_cursor = -1
        self._store_slo_cursor = -1
        from .lag import LAG, REPL_EVENTS
        LAG.reset()
        REPL_EVENTS.reset()
        self.e2e.reset()
        self.burnrate.reset()
        from .burnrate import SLO_EVENTS
        SLO_EVENTS.reset()


# the process-global hub every instrumentation site reports into
OBS = ObsHub()

from .campaign import CampaignMonitor  # noqa: E402 — needs OBS defined

__all__ = [
    "OBS", "ObsHub", "TenantSLO", "NoisyNeighborDetector", "DeviceGauges",
    "TelemetryExporter", "FileSink", "HTTPSink", "WindowedCounter",
    "WindowedLog2Histogram", "ContinuousProfiler", "CompileLedger",
    "SegmentStore", "CampaignMonitor", "E2EPlane", "BurnRateEngine",
    "ShardCompletionBoard",
]
