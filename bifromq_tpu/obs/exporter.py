"""Push telemetry export (ISSUE 3, part 3 — closes PR 2's pull-only gap).

Batched JSON-lines records shipped to a file or HTTP sink by a background
asyncio task:

- **metric snapshots** — the windowed per-tenant SLO state, device gauges,
  process stage histograms and fabric counters, one record per flush tick;
- **spans** — incremental drains of the tracer's slow ring (always) and
  sampled ring (optional), via ``SpanRing.since`` cursors, so every slow
  trace reaches the sink even though /trace stays pull-able.

Discipline mirrors the delivery plane: the queue is **bounded** (overflow
increments ``dropped`` and evicts the oldest — telemetry may lag, memory
may not grow), flush failures retry with the resilience fabric's
``RetryPolicy`` (full-jitter backoff), and a batch that exhausts its
retries is counted dropped rather than wedging the loop.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from typing import Callable, Dict, List, Optional
from urllib.parse import urlsplit

from ..resilience.policy import RetryPolicy

EXPORT_RETRY = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=1.0)

# telemetry record schema generation (ISSUE 5 satellite): bumped when the
# line format changes shape, so federated sinks can route per version
SCHEMA_VERSION = "bifromq-tpu.telemetry/1"

# ---------------------------------------------------------------------------
# OTLP-JSON framing (ISSUE 8 satellite: BIFROMQ_OBS_FORMAT=otlp|jsonl)
#
# The jsonl mode ships our native records; otlp mode re-frames each flush
# batch into OpenTelemetry protocol JSON envelopes — spans into
# resourceSpans, metric snapshots flattened into resourceMetrics gauges,
# anything else into resourceLogs — so a stock OTLP collector ingests the
# exporter's stream without a custom shim. The resource envelope
# (node_id / cluster_id / schema_version) maps onto OTLP resource
# attributes; scripts/otlp_schema.json pins the emitted shape and the
# profile_check.sh gate validates against it.
# ---------------------------------------------------------------------------

_OTLP_SCOPE = {"name": "bifromq_tpu", "version": SCHEMA_VERSION}
_OTLP_METRIC_CAP = 512      # flattened gauges per metrics record


def _otlp_resource(resource: Optional[Dict]) -> dict:
    from ..trace.span import otlp_attributes
    attrs = {f"bifromq.{k}": v for k, v in (resource or {}).items()}
    attrs.setdefault("service.name", "bifromq_tpu")
    return {"attributes": otlp_attributes(attrs)}


def _flatten_numeric(prefix: str, obj, out: List[tuple]) -> None:
    if len(out) >= _OTLP_METRIC_CAP:
        return
    if isinstance(obj, bool):
        return
    if isinstance(obj, (int, float)):
        out.append((prefix, float(obj)))
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _flatten_numeric(f"{prefix}.{k}" if prefix else str(k), v, out)


def _otlp_metrics(rec: dict, ts: float) -> List[dict]:
    ns = str(int(ts * 1e9))
    leaves: List[tuple] = []
    for k, v in rec.items():
        if k in ("type", "ts", "resource"):
            continue
        _flatten_numeric(k, v, leaves)
    return [{"name": name,
             "gauge": {"dataPoints": [{"asDouble": val,
                                       "timeUnixNano": ns}]}}
            for name, val in leaves]


def otlp_frame(records: List[Dict],
               resource: Optional[Dict]) -> List[str]:
    """Frame one flush batch as OTLP-JSON lines: one resourceSpans
    envelope for the spans, one resourceMetrics for the metric
    snapshots, one resourceLogs for everything else."""
    from ..trace.span import otlp_attributes, otlp_span_from_dict
    res = _otlp_resource(resource)
    spans, metrics, logs = [], [], []
    for rec in records:
        kind = rec.get("type")
        if kind == "span":
            spans.append(otlp_span_from_dict(rec))
        elif kind == "metrics":
            metrics.extend(_otlp_metrics(rec, rec.get("ts", 0.0)))
        else:
            logs.append({
                "timeUnixNano": str(int(rec.get("ts", 0.0) * 1e9)),
                "body": {"stringValue": json.dumps(
                    {k: v for k, v in rec.items() if k != "resource"},
                    default=str)},
                "attributes": otlp_attributes(
                    {"type": kind or "record"}),
            })
    lines = []
    if spans:
        lines.append(json.dumps({"resourceSpans": [{
            "resource": res,
            "scopeSpans": [{"scope": _OTLP_SCOPE, "spans": spans}],
        }]}, default=str))
    if metrics:
        lines.append(json.dumps({"resourceMetrics": [{
            "resource": res,
            "scopeMetrics": [{"scope": _OTLP_SCOPE, "metrics": metrics}],
        }]}, default=str))
    if logs:
        lines.append(json.dumps({"resourceLogs": [{
            "resource": res,
            "scopeLogs": [{"scope": _OTLP_SCOPE, "logRecords": logs}],
        }]}, default=str))
    return lines


class FileSink:
    """Append JSON lines to a local file (fsync-free: the OS page cache is
    durable enough for telemetry)."""

    def __init__(self, path: str) -> None:
        self.path = path

    def _write(self, blob: str) -> None:
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(blob)

    async def ship(self, lines: List[str]) -> None:
        # off-loop: a slow/network filesystem must not stall the broker's
        # event loop (the same loop serving publishes) for the write
        await asyncio.get_running_loop().run_in_executor(
            None, self._write, "\n".join(lines) + "\n")

    def describe(self) -> str:
        return f"file:{self.path}"


class HTTPSink:
    """POST the batch as an ``application/x-ndjson`` body over a raw
    asyncio connection (dependency-free, same discipline as the API
    server's HTTP/1.1 plumbing). Any non-2xx status raises so the
    exporter's retry policy takes over."""

    def __init__(self, url: str, timeout_s: float = 5.0) -> None:
        u = urlsplit(url)
        if u.scheme != "http" or not u.hostname:
            raise ValueError(f"unsupported telemetry sink url {url!r}")
        self.host = u.hostname
        self.port = u.port or 80
        # keep the query string: auth-in-query (?token=...) is the common
        # telemetry-collector pattern
        self.path = (u.path or "/") + (f"?{u.query}" if u.query else "")
        self.timeout_s = timeout_s
        self.url = url

    async def ship(self, lines: List[str]) -> None:
        body = ("\n".join(lines) + "\n").encode()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout_s)
        try:
            writer.write(
                f"POST {self.path} HTTP/1.1\r\nhost: {self.host}\r\n"
                f"content-type: application/x-ndjson\r\n"
                f"content-length: {len(body)}\r\n"
                f"connection: close\r\n\r\n".encode() + body)
            await asyncio.wait_for(writer.drain(), self.timeout_s)
            status_line = await asyncio.wait_for(reader.readline(),
                                                self.timeout_s)
            parts = status_line.split()
            if len(parts) < 2 or not parts[1].startswith(b"2"):
                raise ConnectionError(
                    f"telemetry sink rejected batch: {status_line!r}")
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    def describe(self) -> str:
        return f"http:{self.url}"


class TelemetryExporter:
    def __init__(self, sink, *, interval_s: float = 2.0,
                 queue_cap: int = 2048, batch_max: int = 256,
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 export_sampled: bool = False,
                 retry: RetryPolicy = EXPORT_RETRY,
                 resource: Optional[Dict] = None,
                 framing: str = "jsonl",
                 clock: Callable[[], float] = time.time) -> None:
        if framing not in ("jsonl", "otlp"):
            raise ValueError(f"unknown telemetry framing {framing!r}")
        self.sink = sink
        # ISSUE 8 satellite: jsonl ships native records; otlp re-frames
        # each flush batch into OTLP-JSON envelopes (see otlp_frame)
        self.framing = framing
        self.interval_s = interval_s
        self.queue_cap = queue_cap
        self.batch_max = batch_max
        self.snapshot_fn = snapshot_fn
        self.export_sampled = export_sampled
        self.retry = retry
        # resource envelope (ISSUE 5 satellite): node/cluster identity +
        # schema version stamped on every record, so a federated sink
        # ingesting many brokers' lines can attribute each one
        self.resource = resource
        self._clock = clock
        self._queue: deque = deque()
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        # counters surfaced under /metrics "obs"
        self.enqueued = 0
        self.shipped = 0
        self.dropped = 0          # queue overflow + retry-exhausted batches
        self.ship_failures = 0    # individual failed ship attempts
        self.batches = 0
        # incremental ring cursors (slow ring always; main ring optional)
        self._slow_cursor = 0
        self._ring_cursor = 0
        # ISSUE 20: SLO burn/recovery journal cursor — events ship in
        # both framings (otlp re-frames them as resourceLogs)
        self._slo_cursor = -1
        # span ids already enqueued: a slow span lives in BOTH rings (and
        # a slow root's dragged-in children reach the slow ring a tick
        # after the sampled drain saw them) — dedupe so consumers never
        # double-count a span. Bounded FIFO.
        self._seen_ids: set = set()
        self._seen_fifo: deque = deque()
        self.SEEN_CAP = 8192

    # ---------------- producers --------------------------------------------

    def enqueue(self, record: Dict) -> None:
        """Bounded enqueue: past the cap the OLDEST record is evicted (the
        newest telemetry is the one an operator is paging through)."""
        if self.resource is not None:
            record.setdefault("resource", self.resource)
        if len(self._queue) >= self.queue_cap:
            self._queue.popleft()
            self.dropped += 1
        self._queue.append(record)
        self.enqueued += 1

    def _collect(self) -> None:
        """One flush tick's worth of records: a metric snapshot + any new
        spans since the last drain."""
        now = self._clock()
        if self.snapshot_fn is not None:
            try:
                snap = self.snapshot_fn()
            except Exception:  # noqa: BLE001 — telemetry must not raise
                snap = None
            if snap:
                self.enqueue({"type": "metrics", "ts": now, **snap})
        from .. import trace
        self._slow_cursor = self._drain(trace.TRACER.slow_ring,
                                        self._slow_cursor, now)
        if self.export_sampled:
            self._ring_cursor = self._drain(trace.TRACER.ring,
                                            self._ring_cursor, now)
        try:
            from .burnrate import SLO_EVENTS
            evs, self._slo_cursor = SLO_EVENTS.since(self._slo_cursor)
            for e in evs:
                self.enqueue({"type": "slo_event", "ts": now, **e})
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pass

    def _drain(self, ring, cursor: int, now: float) -> int:
        """Incrementally drain one span ring into the queue; returns the
        advanced cursor. The slow ring also holds FAST children dragged
        in by a slow root — ``slow`` is flagged per-span from its own
        duration so consumers alerting on slow==true don't count context
        spans as SLO violations; ``_first_sighting`` dedupes spans that
        live in both rings."""
        from .. import trace
        spans, cursor, missed = ring.since(cursor)
        self.dropped += missed
        slow_ms = trace.TRACER.slow_ms
        for s in spans:
            if not self._first_sighting(s.span_id):
                continue
            self.enqueue({"type": "span", "ts": now,
                          "slow": (slow_ms is not None
                                   and s.duration_ms >= slow_ms),
                          **s.to_dict()})
        return cursor

    def _first_sighting(self, span_id: int) -> bool:
        if span_id in self._seen_ids:
            return False
        self._seen_ids.add(span_id)
        self._seen_fifo.append(span_id)
        if len(self._seen_fifo) > self.SEEN_CAP:
            self._seen_ids.discard(self._seen_fifo.popleft())
        return True

    # ---------------- flush loop -------------------------------------------

    async def _flush_once(self) -> None:
        self._collect()
        while self._queue:
            batch = []
            while self._queue and len(batch) < self.batch_max:
                batch.append(self._queue.popleft())
            if self.framing == "otlp":
                lines = otlp_frame(batch, self.resource)
            else:
                lines = [json.dumps(r, default=str) for r in batch]
            attempt = 0
            try:
                while True:
                    try:
                        await self.sink.ship(lines)
                        self.shipped += len(batch)
                        self.batches += 1
                        break
                    except Exception:  # noqa: BLE001 — sink down: back off
                        self.ship_failures += 1
                        attempt += 1
                        if not self.retry.should_retry(attempt):
                            self.dropped += len(batch)
                            return  # sink is down — try again next tick
                        await asyncio.sleep(self.retry.backoff(attempt))
            except asyncio.CancelledError:
                # cancelled mid-ship (e.g. stop()'s 5s grace expired):
                # the de-queued batch must still be ACCOUNTED — silent
                # loss would break the drop-counter contract
                self.dropped += len(batch)
                raise

    async def _run(self) -> None:
        while True:
            try:
                await asyncio.wait_for(self._wake.wait(), self.interval_s)
            except asyncio.TimeoutError:
                pass
            if self._wake.is_set():     # stop requested: final flush below
                return
            try:
                await self._flush_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                import logging
                logging.getLogger(__name__).exception("telemetry flush")

    def start(self) -> None:
        if self._task is not None:
            return
        self._wake = asyncio.Event()
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="obs-exporter")

    async def stop(self, final_flush: bool = True) -> None:
        task, self._task = self._task, None
        if task is None:
            return
        self._wake.set()
        try:
            await asyncio.wait_for(task, 5.0)
        except asyncio.TimeoutError:
            task.cancel()
        except asyncio.CancelledError:
            # shutdown itself was cancelled: don't keep flushing into a
            # possibly-dead sink — propagate after killing the loop task
            task.cancel()
            raise
        if final_flush:
            try:
                await self._flush_once()
            except Exception:  # noqa: BLE001
                pass

    def snapshot(self) -> dict:
        return {"sink": self.sink.describe(),
                "framing": self.framing,
                "resource": self.resource,
                "interval_s": self.interval_s,
                "queue_depth": len(self._queue),
                "queue_cap": self.queue_cap,
                "enqueued": self.enqueued,
                "shipped": self.shipped,
                "batches": self.batches,
                "dropped": self.dropped,
                "ship_failures": self.ship_failures}
