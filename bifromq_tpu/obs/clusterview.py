"""Cluster observability plane (ISSUE 5 tentpole).

PRs 2–4 built rich per-node surfaces (``/metrics``, ``/tenants``,
``/trace``) that answer only for the local process. This module federates
them into one cluster-wide plane riding the broker's OWN gossip — no
external middleware, the same discipline as upstream BifroMQ:

- **Health digests.** Every node publishes a compact digest — non-closed
  breaker states per endpoint, device gauges (dispatch queue depth,
  compile count, memory watermark), match-cache hit rate, top-3 noisy
  tenants, an HLC stamp — into its gossip agent metadata
  (``AgentHost.host_agent("obs", ...)``), refreshed on the ObsHub
  advisory tick. Digests age out: a killed node's last digest goes
  *stale* in the table instead of lying forever.
- **Health-aware routing.** ``ClusterView.suspect(endpoint)`` answers
  from the gossiped digests: an endpoint some OTHER node's breaker holds
  open, or a node self-reporting a deep dispatch queue, is demoted by
  ``ServiceRegistry.pick`` *before* any local failure is observed —
  closing the PR-1 "breaker state is per-process" follow-up.
- **Federated views.** ``ClusterObsRPCService`` serves each node's raw
  tenant windows and span rings on the RPC fabric; ``federated_tenants``
  scatter-gathers them under a PR-1 deadline budget and merges per-tenant
  RED **bucket-wise** (log2 histograms add exactly), and
  ``federated_trace`` assembles a full cross-process trace ordered by the
  HLC stamps PR 2 already records.

Layering: this module lives in ``obs`` and therefore must not import
``utils.metrics`` at module level (``utils.metrics`` imports the obs
package); the match-cache scrape happens lazily inside ``build_digest``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Callable, Dict, List, Optional

from ..utils.env import env_float as _env_float
from ..utils.hlc import HLC
from .window import N_BUCKETS, percentile_ms_from

log = logging.getLogger(__name__)

# gossip agent carrying the digests (one per node, LWW by incarnation)
AGENT_ID = "obs"
# RPC fabric service for the scatter-gather plane
SERVICE = "cluster-obs"
DIGEST_VERSION = 1


# ---------------------------------------------------------------------------
# bucket-wise RED merge (the federation math, unit-testable on its own)
# ---------------------------------------------------------------------------

_RAW_SCALARS = ("flows", "errors", "fanout", "queue_wait_s",
                "cache_hits", "cache_misses")


def merge_tenant_raws(raws: List[Dict[str, dict]]) -> Dict[str, dict]:
    """Merge several nodes' raw per-tenant window exports
    (``TenantSLO.raw_snapshot``) into one: scalar windows add, per-stage
    log2 histograms add **bucket-wise** — mathematically identical to one
    histogram having observed every node's samples."""
    out: Dict[str, dict] = {}
    for raw in raws:
        for tenant, r in (raw or {}).items():
            dst = out.get(tenant)
            if dst is None:
                dst = out[tenant] = {k: 0.0 for k in _RAW_SCALARS}
                dst["stages"] = {}
            for k in _RAW_SCALARS:
                dst[k] += float(r.get(k, 0.0))
            for stage, buckets in (r.get("stages") or {}).items():
                cur = dst["stages"].get(stage)
                if cur is None:
                    dst["stages"][stage] = list(buckets)[:N_BUCKETS]
                else:
                    for i, c in enumerate(buckets[:N_BUCKETS]):
                        cur[i] += c
    return out


def derive_red_row(raw: dict, window_s: float) -> dict:
    """Raw merged windows → the same derived RED row shape
    ``TenantSLO.snapshot_tenant`` serves locally (rates, error rate,
    cache hit rate, per-stage count/p50/p99)."""
    flows = raw.get("flows", 0.0)
    errors = raw.get("errors", 0.0)
    hits = raw.get("cache_hits", 0.0)
    lookups = hits + raw.get("cache_misses", 0.0)
    stages = {}
    for stage, buckets in (raw.get("stages") or {}).items():
        count = sum(buckets)
        if count:
            stages[stage] = {"count": count,
                             "p50_ms": percentile_ms_from(buckets, 50),
                             "p99_ms": percentile_ms_from(buckets, 99)}
    return {
        "rate_per_s": round(flows / window_s, 3),
        "errors_per_s": round(errors / window_s, 3),
        "error_rate": round(errors / flows, 4) if flows else 0.0,
        "fanout_per_s": round(raw.get("fanout", 0.0) / window_s, 3),
        "queue_wait_s": round(raw.get("queue_wait_s", 0.0), 6),
        "match_cache_hit_rate": (round(hits / lookups, 4)
                                 if lookups else 0.0),
        "stages": stages,
    }


# ---------------------------------------------------------------------------
# the per-node view
# ---------------------------------------------------------------------------

class ClusterView:
    """One node's participation in the cluster observability plane.

    Publishes this node's digest, decodes peers', and keeps a cached
    unhealthy-endpoint set ``ServiceRegistry.pick`` probes per request
    (set membership only — the hot path never walks gossip state)."""

    def __init__(self, node_id: str, agent_host, *, hub=None,
                 registry=None, rpc_address: str = "", api_port: int = 0,
                 stale_after_s: Optional[float] = None,
                 queue_depth_threshold: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 hysteresis_s: Optional[float] = None,
                 full_every: Optional[int] = None,
                 demotion_weights: Optional[Dict[str, float]] = None,
                 demote_threshold: float = 1.0,
                 clock: Callable[[], float] = time.time) -> None:
        from . import OBS
        self.node_id = node_id
        self.agent_host = agent_host
        self.hub = hub if hub is not None else OBS
        self.registry = registry          # rpc.fabric.ServiceRegistry
        self.rpc_address = rpc_address
        self.api_port = api_port
        # a digest older than this is display-only: it neither demotes
        # nor clears endpoints (the node may be dead — its last report
        # says nothing about NOW)
        self.stale_after_s = (stale_after_s if stale_after_s is not None
                              else _env_float("BIFROMQ_CLUSTER_OBS_STALE_S",
                                              10.0))
        # a node self-reporting a dispatch queue at/after this depth is
        # browned out: its endpoints demote fleet-wide
        self.queue_depth_threshold = (
            queue_depth_threshold if queue_depth_threshold is not None
            else _env_float("BIFROMQ_CLUSTER_OBS_QUEUE_DEPTH", 4096.0))
        self.interval_s = (interval_s if interval_s is not None
                           else _env_float("BIFROMQ_CLUSTER_OBS_INTERVAL_S",
                                           1.0))
        # ISSUE 7 satellite: demotion hysteresis — an endpoint stays
        # demoted until it has looked healthy for a full cooldown window
        # since its LAST bad observation, so a node flapping between
        # healthy and suspect (a breaker oscillating open/half-open, a
        # queue sawtoothing around the threshold) cannot oscillate the
        # routing tier with it
        self.hysteresis_s = (hysteresis_s if hysteresis_s is not None
                             else _env_float(
                                 "BIFROMQ_CLUSTER_OBS_HYSTERESIS_S", 5.0))
        self._last_bad: Dict[str, float] = {}
        self._clock = clock
        self._unhealthy: frozenset = frozenset()
        # ISSUE 8 satellite — digest delta encoding: between full
        # snapshots (every ``full_every`` ticks) only the fields that
        # CHANGED since the last full are gossiped. Deltas are computed
        # against the last FULL (not the previous tick), so a consumer
        # that missed intermediate publishes (gossip metadata is
        # last-writer-wins, not a stream) can still apply any delta
        # directly onto its cached full snapshot.
        self.full_every = (full_every if full_every is not None
                           else max(1, int(_env_float(
                               "BIFROMQ_CLUSTER_OBS_FULL_EVERY", 10.0))))
        self._pub_seq = 0
        self._full_seq = 0
        self._last_full: Optional[dict] = None
        # consumer side: node -> (full_seq, full digest) and the live
        # reconstructed view (full ⊕ applied delta)
        self._digest_full: Dict[str, tuple] = {}
        self._digest_view: Dict[str, dict] = {}
        self.digest_deltas_applied = 0
        self.digest_gaps = 0
        # ISSUE 8 satellite — per-signal demotion weighting: signals
        # accumulate a score per endpoint instead of boolean-OR'ing, so
        # two sub-threshold signals (a half-open peer breaker + a
        # climbing-but-not-deep queue) can demote together while either
        # alone does not. Defaults reproduce the legacy single-signal
        # verdicts exactly (each full-strength signal alone reaches the
        # threshold).
        self.demote_threshold = demote_threshold
        self.demotion_weights = {
            "peer_breaker_open": 1.0,
            "peer_breaker_half": 0.5,
            "queue_depth": 1.0,          # × min(2, depth/threshold)
            "device_breaker_open": 1.0,
            "device_breaker_half": 1.0,
            **(demotion_weights or {}),
        }
        self.demotion_scores: Dict[str, float] = {}
        # node_id -> (last digest HLC stamp seen, local receipt time):
        # digest age is measured from when WE saw the stamp change, so
        # staleness is immune to inter-node wall-clock skew (a peer 15s
        # behind must not look permanently stale, nor a dead fast-clock
        # peer permanently fresh)
        self._digest_seen: Dict[str, tuple] = {}
        self._started = False

    # ---------------- digest (publisher side) -------------------------------

    def build_digest(self) -> dict:
        """This node's compact health digest. Kept small on purpose: it
        piggybacks on UDP gossip packets alongside up to 7 other member
        records."""
        hub = self.hub
        device = hub.device.snapshot(memory=False)
        digest = {
            "v": DIGEST_VERSION,
            "hlc": HLC.INST.get(),
            "breakers": self._breaker_states(),
            "device": {
                "dispatch_queue_depth": device.get("dispatch_queue_depth",
                                                   0),
                "batches_in_flight": device.get("batches_in_flight", 0),
                "compile_count": device.get("compile_count", 0),
                "mem_peak_bytes": hub.device.peak_memory_bytes,
                # ISSUE 7: worst local DEVICE breaker state — peers
                # demote a device-sick node (serving oracle-degraded)
                # before routing to it; "closed" is omitted to keep the
                # UDP payload small
                **self._device_breaker_field(),
            },
            "match_cache_hit_rate": self._match_cache_hit_rate(),
            "noisy": [{"tenant": r["tenant"], "score": r["score"],
                       "flags": r["flags"]}
                      for r in self._noisy_rows()[:3]],
            # ISSUE 8: compact capacity accounting rides the digest so
            # GET /cluster/capacity federates with no extra RPC plane
            "capacity": self._capacity_field(),
            # ISSUE 12: this node's hot (tenant, topic) working set — a
            # failover target pre-warms its match cache against the
            # cluster's union of these BEFORE taking traffic
            "hot_topics": self._hot_topics(),
            # ISSUE 15 satellite (ROADMAP retained follow-up (d)): this
            # node's reconnect-drain occupancy — a clustered reconnect
            # storm sheds herd drains toward peers reporting less
            "drain_pressure": self._drain_pressure(),
        }
        # ISSUE 17: compact mesh shard-load skew — peers (and /cluster)
        # see a lopsided mesh before its hot shard trips a breaker;
        # omitted on single-chip nodes to keep the UDP payload small
        mesh = self._mesh_field()
        if mesh:
            digest["mesh"] = mesh
        # ISSUE 18: compact replication-lag summary — a peer whose
        # standby is stale is a bad failover target, and /cluster shows
        # apply lag cluster-wide with no extra RPC plane; omitted when
        # this node consumes no delta streams
        repl = self._replication_field()
        if repl:
            digest["replication"] = repl
        # ISSUE 20: compact burn summary — which tenants burn their SLO
        # budget on this node, and the worst burner; /cluster/slo
        # federates these with no extra RPC plane. Omitted while no
        # tenant burns to keep the UDP payload small.
        slo = self._slo_field()
        if slo.get("burning") or slo.get("worst"):
            digest["slo"] = slo
        return digest

    @staticmethod
    def _mesh_field() -> dict:
        try:
            from . import OBS
            meshes = OBS.mesh_snapshot()
            if not meshes:
                return {}
            s = meshes[0]     # one mesh matcher per node in practice
            return {"skew": round(float(s.get("skew", 1.0)), 3),
                    "map_version": s.get("map_version", 0),
                    "migrating": len(s.get("migrating", {})),
                    "shard_load": [round(float(r.get("score", 0.0)), 3)
                                   for r in s.get("shard_load", [])],
                    # ISSUE 18: live-migration ladder progress rides the
                    # same field — peers see a dual-serve window open
                    "migrations": s.get("migrations", {})}
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return {}

    @staticmethod
    def _replication_field() -> dict:
        try:
            from .lag import LAG
            return LAG.summary()
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return {}

    @staticmethod
    def _slo_field() -> dict:
        try:
            from . import OBS
            return OBS.burnrate.summary()
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return {}

    def _drain_pressure(self) -> float:
        try:
            return self.hub.drain_pressure()
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return 0.0

    def peer_drain_pressures(self) -> Dict[str, float]:
        """Fresh peers' gossiped drain-governor occupancy (ISSUE 15
        satellite): what the local DrainGovernor consults before
        admitting a herd drain — a saturated broker with quieter peers
        sheds the reconnect so the client lands elsewhere."""
        out: Dict[str, float] = {}
        for node, p in self.peers().items():
            if p["stale"]:
                continue
            dp = (p["digest"] or {}).get("drain_pressure")
            if dp is not None:
                out[node] = float(dp)
        return out

    def _hot_topics(self) -> list:
        try:
            cache = self.hub.pub_cache()
            return cache.hot_keys(16) if cache is not None else []
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return []

    def _capacity_field(self) -> dict:
        try:
            from .capacity import digest_capacity
            return digest_capacity(self.hub)
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return {}

    def _noisy_rows(self) -> list:
        """Ranked rows for the digest: reuse the advisory tick's fresh
        evaluation when available (the tick just ran one; a second full
        scoring pass per second is pure waste on a max-tenant node)."""
        if not self.hub.enabled:
            return []
        rows = self.hub.detector.recent_rows(self.interval_s)
        if rows is None:
            rows = self.hub.detector.evaluate(top_k=3, emit=False)
        return rows

    def _breaker_states(self) -> Dict[str, str]:
        """Non-closed breaker states per endpoint (closed is the default
        — absent means healthy, keeping the gossip payload compact)."""
        if self.registry is None:
            return {}
        try:
            return self.registry.breakers.states(include_closed=False)
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return {}

    @staticmethod
    def _device_breaker_field() -> Dict[str, object]:
        try:
            from ..resilience.device import DEVICE_BREAKERS
            worst = DEVICE_BREAKERS.worst_state()
            if worst == "closed":
                return {}
            out: Dict[str, object] = {"breaker": worst}
            # ISSUE 15: per-SHARD breaker state rides the digest so peers
            # (and /cluster) can see exactly which fault domain of a mesh
            # node is sick — closed shards are omitted (compact UDP)
            shards = {label.rpartition(":")[2]: state
                      for label, state in DEVICE_BREAKERS.states().items()
                      if ":shard" in label}
            if shards:
                out["shard_breakers"] = shards
            return out
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return {}

    @staticmethod
    def _match_cache_hit_rate() -> float:
        # lazy: utils.metrics imports the obs package (layering note in
        # the module docstring)
        try:
            from ..utils.metrics import MATCH_CACHE
            snap = MATCH_CACHE.snapshot()
            hits = misses = 0
            for scope, s in snap.items():
                if scope == "dedup":
                    continue
                hits += s.get("hits", 0)
                misses += s.get("misses", 0)
            return round(hits / (hits + misses), 4) if hits + misses \
                else 0.0
        except Exception:  # noqa: BLE001
            return 0.0

    def refresh(self) -> None:
        """Publish a fresh digest (full or delta — see ``_publish_meta``)
        into the gossip agent metadata (bumping the member incarnation so
        peers merge it) and recompute the unhealthy set from what peers
        have gossiped back."""
        try:
            self.agent_host.host_agent(AGENT_ID, self._publish_meta())
        except Exception:  # noqa: BLE001 — telemetry must not raise
            log.exception("digest publish failed")
        self._recompute()

    def _publish_meta(self) -> dict:
        """Delta-encoded digest publication (ISSUE 8 satellite): a full
        snapshot every ``full_every`` ticks, otherwise only the top-level
        fields that changed since the last full (the HLC stamp always
        changes — it is the freshness signal — but a steady node's
        breakers/device/noisy/capacity sections stop riding every UDP
        gossip packet)."""
        digest = self.build_digest()
        self._pub_seq += 1
        meta = {"addr": self.rpc_address, "api": self.api_port,
                "seq": self._pub_seq}
        if (self._last_full is None or self.full_every <= 1
                or self._pub_seq - self._full_seq >= self.full_every):
            meta["digest"] = digest
            self._last_full = digest
            self._full_seq = self._pub_seq
        else:
            meta["digest_delta"] = {
                k: v for k, v in digest.items()
                if self._last_full.get(k) != v}
            meta["base_seq"] = self._full_seq
        return meta

    def _decode_digest(self, node: str, meta: Optional[dict]) -> dict:
        """Reconstruct a peer's digest from full-or-delta metadata.
        A delta applies only when we hold its base full snapshot; on a
        gap (we joined after the base was published, or the base was
        overwritten before we gossiped it in) the last good view keeps
        serving — it ages out naturally via ``digest_age_s`` if the gap
        persists — and the next full snapshot repairs the chain."""
        meta = meta or {}
        full = meta.get("digest")
        if full is not None:
            if meta.get("seq") is not None:
                self._digest_full[node] = (meta["seq"], full)
            self._digest_view[node] = full
            return full
        delta = meta.get("digest_delta")
        if delta is not None:
            cached = self._digest_full.get(node)
            if cached is not None and cached[0] == meta.get("base_seq"):
                view = {**cached[1], **delta}
                self._digest_view[node] = view
                self.digest_deltas_applied += 1
                return view
            # GAP: we never saw this delta's base full (gossip metadata
            # is last-writer-wins — the one tick holding the full can be
            # overwritten before we sample it). The delta's VALUES are
            # still current-absolute (it lists fields that differ from
            # the publisher's last full), so apply it best-effort onto
            # whatever view we hold: freshness (the hlc field, always in
            # the delta) keeps advancing — an alive, gossiping peer must
            # not age out as stale just because we missed one full —
            # while any field that changed since OUR base but matches
            # THEIR base stays ≤ one full cycle behind, until the next
            # full snapshot resyncs the chain exactly.
            self.digest_gaps += 1
            prev = self._digest_view.get(node)
            if prev is not None:
                view = {**prev, **delta}
                self._digest_view[node] = view
                return view
            return {}
        return {}

    # ---------------- peers (consumer side) ----------------------------------

    def digest_age_s(self, node: str,
                     digest: Optional[dict]) -> Optional[float]:
        """Seconds since this node's digest last CHANGED, measured on the
        LOCAL clock at receipt: a fresh HLC stamp resets the age. Skew
        between node wall clocks cannot fake freshness or staleness —
        only a peer actually going silent ages out."""
        if not digest or "hlc" not in digest:
            self._digest_seen.pop(node, None)
            return None
        now = self._clock()
        seen = self._digest_seen.get(node)
        if seen is None or seen[0] != digest["hlc"]:
            self._digest_seen[node] = (digest["hlc"], now)
            return 0.0
        return max(0.0, now - seen[1])

    def peers(self, include_self: bool = False) -> Dict[str, dict]:
        """node_id → {addr, api, digest, age_s, stale} for every ALIVE
        node hosting the obs agent."""
        out = {}
        members = self.agent_host.agent_members(AGENT_ID)
        for node, meta in members.items():
            if node == self.node_id and not include_self:
                continue
            digest = self._decode_digest(node, meta)
            age = self.digest_age_s(node, digest)
            out[node] = {
                "addr": (meta or {}).get("addr", ""),
                "api": (meta or {}).get("api", 0),
                "digest": digest,
                "age_s": age,
                "stale": age is None or age > self.stale_after_s,
            }
        # receipt entries for departed members must not pin forever
        for node in [n for n in self._digest_seen if n not in members]:
            del self._digest_seen[node]
        for cache in (self._digest_full, self._digest_view):
            for node in [n for n in cache if n not in members]:
                del cache[node]
        return out

    def cluster_table(self) -> Dict[str, dict]:
        """The merged node table behind ``GET /cluster``: every known
        member (any status) with its digest, digest age, and liveness."""
        peers = self.peers(include_self=True)
        out = {}
        for m in self.agent_host.members.values():
            row = {"status": m.status,
                   "alive": m.status == "alive",
                   "agents": sorted(m.agents)}
            p = peers.get(m.node_id)
            if p is not None:
                row.update(addr=p["addr"], api=p["api"],
                           digest=p["digest"],
                           digest_age_s=(round(p["age_s"], 3)
                                         if p["age_s"] is not None
                                         else None),
                           stale=p["stale"])
            out[m.node_id] = row
        return out

    # ---------------- health-aware routing -----------------------------------

    def _recompute(self) -> None:
        """Rebuild the cached unhealthy-endpoint set from fresh peer
        digests. Called on the advisory tick and on gossip membership
        change — never from ``suspect`` (the pick hot path)."""
        try:
            # ISSUE 8 satellite — per-signal weighted scoring: each
            # signal contributes its weight to the endpoint's score and
            # the endpoint demotes at ``demote_threshold``, instead of
            # any single signal boolean-OR'ing it out. Defaults keep
            # every legacy verdict (each full-strength signal alone
            # crosses the threshold) while letting sub-threshold signals
            # combine: a half-open peer breaker (0.5) plus a queue at
            # 60% of the brown-out depth (0.6) now demotes.
            w = self.demotion_weights
            scores: Dict[str, float] = {}

            def bump(ep: str, amount: float) -> None:
                if ep and amount > 0:
                    scores[ep] = scores.get(ep, 0.0) + amount

            for node, p in self.peers().items():
                if p["stale"]:
                    continue
                digest = p["digest"]
                # another node's circuit to an endpoint: OPEN is a full
                # vote, HALF_OPEN (still probing) a partial one
                for ep, state in (digest.get("breakers") or {}).items():
                    if state == "open":
                        bump(ep, w["peer_breaker_open"])
                    elif state == "half_open":
                        bump(ep, w["peer_breaker_half"])
                # the node itself reports a browning-out device pipeline:
                # queue depth scores proportionally (capped at 2× so one
                # signal saturates instead of dwarfing the rest), and
                # (ISSUE 7) a non-closed DEVICE breaker means the node
                # serves oracle-degraded — healthy accelerators first
                dev = digest.get("device") or {}
                if p["addr"]:
                    depth = dev.get("dispatch_queue_depth", 0)
                    if depth > 0 and self.queue_depth_threshold > 0:
                        bump(p["addr"], w["queue_depth"] * min(
                            2.0, depth / self.queue_depth_threshold))
                    db = dev.get("breaker")
                    if db == "open":
                        bump(p["addr"], w["device_breaker_open"])
                    elif db == "half_open":
                        bump(p["addr"], w["device_breaker_half"])
            # never let gossip rumors blackhole OUR OWN endpoint for the
            # local picker: local breakers already own that verdict
            scores.pop(self.rpc_address, None)
            self.demotion_scores = {ep: round(s, 3)
                                    for ep, s in scores.items()}
            bad = {ep for ep, s in scores.items()
                   if s >= self.demote_threshold}
            # ISSUE 7 satellite — demotion hysteresis: an endpoint leaves
            # the unhealthy set only after a full cooldown of CONSECUTIVE
            # healthy observations; any bad sighting restarts the clock,
            # so a flapping endpoint stays demoted instead of oscillating
            # the pick tier
            now = self._clock()
            for ep in bad:
                self._last_bad[ep] = now
            sticky = set()
            for ep, at in list(self._last_bad.items()):
                if now - at < self.hysteresis_s:
                    sticky.add(ep)
                else:       # cooled off: forget it (bounds the map too)
                    del self._last_bad[ep]
            bad |= sticky
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return
        self._unhealthy = frozenset(bad)

    def suspect(self, endpoint: str) -> bool:
        """Hot-path probe for ``ServiceRegistry.pick``: is this endpoint
        flagged unhealthy by gossiped remote state? Pure set membership."""
        return endpoint in self._unhealthy

    def unhealthy_endpoints(self) -> List[str]:
        return sorted(self._unhealthy)

    # ---------------- federation (scatter-gather) ----------------------------

    async def _scatter(self, method: str, payload: dict,
                       timeout_s: float) -> Dict[str, dict]:
        """Call ``cluster-obs/<method>`` on every fresh peer under one
        deadline budget; per-node failures degrade to error rows instead
        of failing the whole view (an operator debugging a sick node
        needs the healthy ones' answer MORE)."""
        from ..resilience.policy import deadline_scope
        if self.registry is None:
            return {}
        peers = {n: p for n, p in self.peers().items()
                 if p["addr"] and not p["stale"]}

        async def one(addr: str):
            out = await self.registry.client_for(addr).call(
                SERVICE, method, json.dumps(payload).encode(),
                timeout=timeout_s)
            return json.loads(out)

        results: Dict[str, dict] = {}
        with deadline_scope(timeout_s):
            done = await asyncio.gather(
                *(one(p["addr"]) for p in peers.values()),
                return_exceptions=True)
        for node, res in zip(peers, done):
            if isinstance(res, BaseException):
                results[node] = {"error": repr(res)}
            else:
                results[node] = res
        return results

    async def federated_tenants(self, timeout_s: float = 2.0,
                                top_k: int = 0) -> dict:
        """``GET /cluster/tenants``: per-tenant RED merged across every
        node (bucket-wise histogram merge), plus per-node fetch status.

        A peer running a different ``BIFROMQ_OBS_WINDOW_S`` has its
        scalar totals rescaled to the coordinator's window before the
        merge, so the derived rates stay true; its histogram BUCKETS
        merge raw (quantiles are window-agnostic, only the absolute
        stage counts then span mixed windows)."""
        hub = self.hub
        window_s = hub.windows.window_s
        local_raw = hub.windows.raw_snapshot() if hub.enabled else {}
        raws = [local_raw]
        nodes = {self.node_id: "local"}
        for node, res in (await self._scatter(
                "tenants", {}, timeout_s)).items():
            if "error" in res:
                nodes[node] = f"error: {res['error']}"
                continue
            nodes[node] = "ok"
            raw = res.get("tenants") or {}
            peer_w = float(res.get("window_s") or window_s)
            if peer_w > 0 and peer_w != window_s:
                scale = window_s / peer_w
                raw = {t: {**r, **{k: r.get(k, 0.0) * scale
                                   for k in _RAW_SCALARS}}
                       for t, r in raw.items()}
                nodes[node] = f"ok (window_s={peer_w:g}, rescaled)"
            raws.append(raw)
        merged = merge_tenant_raws(raws)
        rows = {t: derive_red_row(r, window_s) for t, r in merged.items()}
        if top_k > 0:
            keep = sorted(rows, key=lambda t: -rows[t]["rate_per_s"])[:top_k]
            rows = {t: rows[t] for t in keep}
        return {"window_s": window_s, "nodes": nodes, "tenants": rows}

    async def federated_trace(self, trace_id: str,
                              timeout_s: float = 2.0) -> dict:
        """``GET /cluster/trace/<id>``: assemble the full cross-process
        trace — every peer's span rings queried for the id, spans merged
        with the local ring's and ordered by the causal HLC stamps.

        ISSUE 7 satellite: when a contributing ring has WRAPPED (its
        oldest spans overwritten), the assembled trace may be missing
        spans that once existed. The response annotates the gap instead
        of silently returning a partial trace — and the wrap signal is
        PER-TRACE, not the ring's lifetime drop counter (which would
        brand every trace incomplete forever after one wrap on a
        long-running node): a ring counts as wrapped *for this trace*
        only when the trace shows a visible tear (a returned span
        references a parent absent from the assembly) or the trace's
        earliest known span starts at-or-before the ring's wrap horizon
        (the ``end_hlc`` of its oldest surviving span — everything
        overwritten ended before that, so only a trace overlapping the
        horizon can have lost leaf spans). ``spans_dropped`` counts the
        dangling parent ids, ``complete`` goes false whenever any ring
        wrapped over this trace's window, and ``rings_wrapped`` names
        the nodes. A fully-captured recent trace on a long-wrapped ring
        reports complete; without any wrapped ring, missing parents are
        attributed to slow-only captures / peer errors, not drops."""
        from .. import trace as tr
        spans = [dict(s, node=self.node_id)
                 for s in tr.TRACER.export(trace_id=trace_id, limit=1000)]
        # slow-only captures live in the slow ring exclusively
        seen = {s["span_id"] for s in spans}
        for s in tr.TRACER.export(trace_id=trace_id, limit=1000, slow=True):
            if s["span_id"] not in seen:
                spans.append(dict(s, node=self.node_id))
                seen.add(s["span_id"])
        horizons: Dict[str, int] = {}
        local_hz = tr.TRACER.ring.wrap_horizon()
        if local_hz is not None:
            horizons[self.node_id] = local_hz
        nodes = {self.node_id: "local"}
        peer_errors = False
        for node, res in (await self._scatter(
                "trace_spans", {"trace_id": trace_id},
                timeout_s)).items():
            if "error" in res:
                nodes[node] = f"error: {res['error']}"
                peer_errors = True
                continue
            nodes[node] = "ok"
            if res.get("wrap_horizon") is not None:
                horizons[res.get("node", node)] = res["wrap_horizon"]
            for s in res.get("spans") or []:
                if s.get("span_id") not in seen:
                    spans.append(dict(s, node=res.get("node", node)))
                    seen.add(s.get("span_id"))
        spans.sort(key=lambda s: s.get("start_hlc", 0))
        # the visible tears: parents referenced but absent everywhere.
        # A peer that ERRORED is the more plausible owner of a dangling
        # parent than some node's ancient wrap — with an error in the
        # response (already visible in ``nodes``) the tears are not
        # attributed to wraps at all.
        missing = {s.get("parent_id") for s in spans
                   if s.get("parent_id")
                   and s.get("parent_id") not in seen} \
            if not peer_errors else set()
        trace_min = min((s.get("start_hlc", 0) for s in spans),
                        default=None)
        wrapped = [node for node, hz in horizons.items()
                   if missing
                   or (trace_min is not None and trace_min <= hz)]
        dropped = len(missing) if wrapped else 0
        return {"trace_id": trace_id,
                "count": len(spans),
                "nodes": nodes,
                "processes": len({s.get("node") for s in spans}),
                "spans_dropped": dropped,
                "complete": not wrapped,
                "rings_wrapped": wrapped,
                "spans": spans}

    def capacity_table(self) -> dict:
        """``GET /cluster/capacity`` (ISSUE 8): per-node device capacity
        federated from the gossiped digests — automaton table bytes,
        memory watermarks, fused-VMEM verdicts — plus cluster totals.
        Pure digest reads: no scatter-gather RPC, a dead node's row just
        goes stale with its digest."""
        from .capacity import digest_capacity
        rows: Dict[str, dict] = {}
        local = digest_capacity(self.hub)
        rows[self.node_id] = {"capacity": local, "stale": False,
                              "self": True}
        total = int(local.get("table_bytes", 0))
        peak = int(local.get("mem_peak_bytes", 0))
        # ISSUE 9 satellite (PR 8 follow-up): logical-subscription rollup.
        # Physical table bytes sum per node (that IS what HBM holds, incl.
        # replicas); logical subs dedup by the gossiped subscription-set
        # fingerprint — nodes carrying an identical (tenant, count) census
        # hold replicas of one logical route table and count ONCE. Nodes
        # without a fingerprint (older digests, empty tables) count
        # individually — no dedup evidence, no dedup.
        logical_sum = 0
        fp_groups: Dict[str, int] = {}
        for node, p in self.peers().items():
            cap = (p["digest"] or {}).get("capacity") or {}
            rows[node] = {"capacity": cap, "stale": p["stale"]}
            if not p["stale"]:
                total += int(cap.get("table_bytes", 0))
                peak = max(peak, int(cap.get("mem_peak_bytes", 0)))
        for node, row in rows.items():
            if row.get("stale"):
                continue
            cap = row["capacity"]
            ls = int(cap.get("logical_subs", 0))
            logical_sum += ls
            if ls <= 0:
                # empty tables (or pre-rollup digests) form no replica
                # group — matches the apiserver single-node fallback
                continue
            key = cap.get("subs_fp") or f"node:{node}"
            fp_groups[key] = max(fp_groups.get(key, 0), ls)
        return {"nodes": rows,
                "total_table_bytes": total,
                "max_mem_peak_bytes": peak,
                "logical_subs": {
                    "sum": logical_sum,
                    "dedup": sum(fp_groups.values()),
                    "replica_groups": len(fp_groups),
                }}

    # ---------------- lifecycle ----------------------------------------------

    def start(self) -> None:
        """Publish the first digest and ride the ObsHub advisory tick for
        refreshes (refcounted — shares the tick with the throttler
        advisory)."""
        if self._started:
            return
        self._started = True
        self.refresh()
        self.agent_host.on_change(self._recompute)
        self.hub.on_advisory_tick(self.refresh)
        self.hub.start_advisory_tick(self.interval_s)

    async def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        self.hub.remove_advisory_hook(self.refresh)
        await self.hub.stop_advisory_tick()
        remove = getattr(self.agent_host, "remove_on_change", None)
        if remove is not None:
            remove(self._recompute)
        try:
            self.agent_host.stop_agent(AGENT_ID)
        except Exception:  # noqa: BLE001 — host may already be stopped
            pass


# ---------------------------------------------------------------------------
# the RPC service every node serves (the scatter-gather's far end)
# ---------------------------------------------------------------------------

class ClusterObsRPCService:
    """Serves this node's raw tenant windows and span rings to peers."""

    def __init__(self, view: ClusterView) -> None:
        self.view = view

    def register(self, server) -> None:
        server.register(SERVICE, {
            "tenants": self._tenants,
            "trace_spans": self._trace_spans,
            "digest": self._digest,
        })

    async def _tenants(self, payload: bytes, okey: str) -> bytes:
        hub = self.view.hub
        return json.dumps({
            "node": self.view.node_id,
            "window_s": hub.windows.window_s,
            "tenants": hub.windows.raw_snapshot() if hub.enabled else {},
        }).encode()

    async def _trace_spans(self, payload: bytes, okey: str) -> bytes:
        from .. import trace as tr
        try:
            args = json.loads(payload.decode() or "{}")
        except ValueError:
            args = {}
        tid = args.get("trace_id")
        limit = int(args.get("limit", 1000))
        spans = tr.TRACER.export(trace_id=tid, limit=limit)
        seen = {s["span_id"] for s in spans}
        for s in tr.TRACER.export(trace_id=tid, limit=limit, slow=True):
            if s["span_id"] not in seen:
                spans.append(s)
                seen.add(s["span_id"])
        return json.dumps({"node": self.view.node_id,
                           # ISSUE 7: how far back does surviving ring
                           # history reach? (None = never wrapped; the
                           # coordinator's per-trace gap annotation keys
                           # on it, not on the lifetime drop counter)
                           "wrap_horizon": tr.TRACER.ring.wrap_horizon(),
                           "spans": spans}).encode()

    async def _digest(self, payload: bytes, okey: str) -> bytes:
        return json.dumps({"node": self.view.node_id,
                           "digest": self.view.build_digest()}).encode()
