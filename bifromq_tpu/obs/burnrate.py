"""Multi-window burn-rate SLO engine (ISSUE 20, tentpole part 2).

Per-tenant **objectives** — a latency p99 target and a delivery-success
ratio — are evaluated as error-budget *burn rates* over a fast and a
slow window (the classic multi-window multi-burn-rate alerting shape):

- the **success budget** is ``1 - success_target``: the fraction of
  deliveries allowed to fail (drop/expire/shed). Its burn rate is the
  observed violation ratio divided by that budget.
- the **latency budget** is the 1% of deliveries allowed above the p99
  target. Its burn rate is the observed over-target ratio divided by
  0.01.

A tenant's burn is the worse of the two. The alert fires only when
**both** the fast and the slow window burn at or above the threshold —
the fast window gives low detection latency, the slow window keeps a
brief blip from paging — and clears (``SLO_RECOVERED``) only after the
cooldown, so a flapping tenant emits one burn/recovery pair, not a
stream.

Feeding: the e2e plane's record points land here through
``ObsHub.record_delivery`` / ``record_delivery_violation``; evaluation
runs on the hub's advisory tick (off the hot path), events ride the
broker's collector chain as ``SLO_BURN``/``SLO_RECOVERED`` and the
bounded :data:`SLO_EVENTS` journal the exporter and segment store drain.

``burning()``/``is_burning`` is the throttler/shedder advisory feed: the
load shedder treats a burning tenant like a noisy one — its QoS0 traffic
sheds first under device pressure, spending the budget where the SLO is
already lost.

Knobs (env defaults, per-tenant overridable via ``PUT /obs`` and the
starter YAML ``obs: slo:`` section): ``BIFROMQ_SLO_P99_MS``,
``BIFROMQ_SLO_SUCCESS``, ``BIFROMQ_SLO_FAST_WINDOW_S``,
``BIFROMQ_SLO_SLOW_WINDOW_S``, ``BIFROMQ_SLO_BURN_THRESHOLD``,
``BIFROMQ_SLO_COOLDOWN_S``.

Layering: must NOT import ``utils.metrics`` (import cycle).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ..plugin.events import Event, EventType
from ..utils.env import env_float
from .lag import EventJournal
from .window import WindowedCounter

# the bounded journal of burn/recovery transitions (exporter + segment
# store drain it via the usual ``since`` cursor contract)
SLO_EVENTS = EventJournal()


class SLOObjective:
    """One tenant's target pair. ``None`` fields inherit the defaults."""

    __slots__ = ("p99_ms", "success")

    def __init__(self, p99_ms: Optional[float] = None,
                 success: Optional[float] = None) -> None:
        self.p99_ms = p99_ms
        self.success = success

    def to_dict(self) -> dict:
        return {"p99_ms": self.p99_ms, "success": self.success}


class _TenantBurn:
    """One tenant's windowed budget-burn state: (total, over-latency,
    violation) counters over the fast and the slow window."""

    __slots__ = ("fast_total", "fast_lat", "fast_viol",
                 "slow_total", "slow_lat", "slow_viol",
                 "burning", "since")

    def __init__(self, fast_s: float, slow_s: float, clock) -> None:
        self.fast_total = WindowedCounter(fast_s, 5, clock)
        self.fast_lat = WindowedCounter(fast_s, 5, clock)
        self.fast_viol = WindowedCounter(fast_s, 5, clock)
        self.slow_total = WindowedCounter(slow_s, 5, clock)
        self.slow_lat = WindowedCounter(slow_s, 5, clock)
        self.slow_viol = WindowedCounter(slow_s, 5, clock)
        self.burning = False
        self.since: Optional[float] = None


def _burn(total: float, lat_bad: float, viol: float,
          success_target: float) -> float:
    """Error-budget burn rate over one window: the worse of the success
    and the latency budget spend. 1.0 = spending exactly at budget."""
    if total <= 0:
        return 0.0
    success_budget = max(1e-6, 1.0 - success_target)
    return max((viol / total) / success_budget,
               (lat_bad / total) / 0.01)


class BurnRateEngine:
    """The per-tenant multi-window burn evaluator."""

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 max_tenants: int = 512) -> None:
        self._clock = clock
        self.max_tenants = int(max_tenants)
        # window/threshold knobs resolve lazily per configure() so a
        # PUT /obs or YAML override lands without a process restart;
        # the env read happens here ONCE (hub-construction discipline)
        self.fast_window_s = max(1.0, env_float(
            "BIFROMQ_SLO_FAST_WINDOW_S", 60.0))
        self.slow_window_s = max(self.fast_window_s, env_float(
            "BIFROMQ_SLO_SLOW_WINDOW_S", 300.0))
        self.burn_threshold = max(0.1, env_float(
            "BIFROMQ_SLO_BURN_THRESHOLD", 2.0))
        self.cooldown_s = max(0.0, env_float(
            "BIFROMQ_SLO_COOLDOWN_S", 30.0))
        self.default_p99_ms = env_float("BIFROMQ_SLO_P99_MS", 250.0)
        self.default_success = min(0.99999, max(0.5, env_float(
            "BIFROMQ_SLO_SUCCESS", 0.999)))
        self._tenants: Dict[str, _TenantBurn] = {}
        self._objectives: Dict[str, SLOObjective] = {}
        self._burning: Set[str] = set()
        self._lock = threading.Lock()
        self.events = None          # IEventCollector outlet (bind_events)
        self.journal = SLO_EVENTS

    # ---------------- configuration ----------------------------------------

    def configure(self, *, fast_window_s: Optional[float] = None,
                  slow_window_s: Optional[float] = None,
                  burn_threshold: Optional[float] = None,
                  cooldown_s: Optional[float] = None,
                  p99_ms: Optional[float] = None,
                  success: Optional[float] = None) -> None:
        """Runtime reconfiguration (``PUT /obs`` / starter YAML). A
        window change rebuilds tenant state — slice rings cannot be
        resized in place."""
        rebuild = False
        if fast_window_s is not None:
            self.fast_window_s = max(1.0, float(fast_window_s))
            rebuild = True
        if slow_window_s is not None:
            self.slow_window_s = float(slow_window_s)
            rebuild = True
        self.slow_window_s = max(self.fast_window_s, self.slow_window_s)
        if burn_threshold is not None:
            self.burn_threshold = max(0.1, float(burn_threshold))
        if cooldown_s is not None:
            self.cooldown_s = max(0.0, float(cooldown_s))
        if p99_ms is not None:
            self.default_p99_ms = max(1.0, float(p99_ms))
        if success is not None:
            self.default_success = min(0.99999, max(0.5, float(success)))
        if rebuild:
            with self._lock:
                self._tenants.clear()

    def configure_tenant(self, tenant: str,
                         p99_ms: Optional[float] = None,
                         success: Optional[float] = None) -> None:
        self._objectives[tenant] = SLOObjective(
            p99_ms=float(p99_ms) if p99_ms is not None else None,
            success=(min(0.99999, max(0.5, float(success)))
                     if success is not None else None))

    def clear_tenant(self, tenant: str) -> None:
        self._objectives.pop(tenant, None)

    def objective(self, tenant: str) -> dict:
        o = self._objectives.get(tenant)
        return {"p99_ms": (o.p99_ms if o and o.p99_ms is not None
                           else self.default_p99_ms),
                "success": (o.success if o and o.success is not None
                            else self.default_success)}

    def _windows(self, tenant: str) -> _TenantBurn:
        w = self._tenants.get(tenant)
        if w is None:
            with self._lock:
                w = self._tenants.get(tenant)
                if w is None:
                    if len(self._tenants) >= self.max_tenants:
                        evict = next(iter(self._tenants))
                        self._tenants.pop(evict)
                        self._burning.discard(evict)
                    w = _TenantBurn(self.fast_window_s,
                                    self.slow_window_s, self._clock)
                    self._tenants[tenant] = w
        return w

    # ---------------- recording (hot path, via ObsHub) ----------------------

    def observe(self, tenant: str, latency_s: float) -> None:
        w = self._windows(tenant)
        w.fast_total.add(1.0)
        w.slow_total.add(1.0)
        o = self._objectives.get(tenant)
        p99_ms = (o.p99_ms if o is not None and o.p99_ms is not None
                  else self.default_p99_ms)
        if latency_s * 1000.0 > p99_ms:
            w.fast_lat.add(1.0)
            w.slow_lat.add(1.0)

    def observe_violation(self, tenant: str) -> None:
        w = self._windows(tenant)
        w.fast_total.add(1.0)
        w.slow_total.add(1.0)
        w.fast_viol.add(1.0)
        w.slow_viol.add(1.0)

    # ---------------- evaluation (advisory tick) ----------------------------

    def _burns(self, tenant: str, w: _TenantBurn) -> tuple:
        succ = self.objective(tenant)["success"]
        fast = _burn(w.fast_total.total(), w.fast_lat.total(),
                     w.fast_viol.total(), succ)
        slow = _burn(w.slow_total.total(), w.slow_lat.total(),
                     w.slow_viol.total(), succ)
        return fast, slow

    def evaluate(self) -> List[dict]:
        """Re-score every tracked tenant; emit transition events. Runs on
        the hub advisory tick — never on the delivery hot path."""
        now = self._clock()
        transitions: List[dict] = []
        for tenant in list(self._tenants):
            w = self._tenants.get(tenant)
            if w is None:
                continue
            fast, slow = self._burns(tenant, w)
            over = (fast >= self.burn_threshold
                    and slow >= self.burn_threshold)
            if over and not w.burning:
                w.burning = True
                w.since = now
                self._burning.add(tenant)
                transitions.append(self._emit(
                    EventType.SLO_BURN, tenant, fast, slow))
            elif w.burning and not over:
                # cooldown: hold the burning flag for at least
                # cooldown_s after it was raised — one pair per episode
                if w.since is None or now - w.since >= self.cooldown_s:
                    w.burning = False
                    w.since = None
                    self._burning.discard(tenant)
                    transitions.append(self._emit(
                        EventType.SLO_RECOVERED, tenant, fast, slow))
        return transitions

    def _emit(self, etype: EventType, tenant: str,
              fast: float, slow: float) -> dict:
        obj = self.objective(tenant)
        rec = self.journal.append(
            etype.value, tenant=tenant,
            fast_burn=round(fast, 3), slow_burn=round(slow, 3),
            threshold=self.burn_threshold, objective=obj,
            ts=round(time.time(), 3))
        events = self.events
        if events is not None:
            try:
                events.report(Event(etype, tenant, {
                    "fast_burn": round(fast, 3),
                    "slow_burn": round(slow, 3),
                    "threshold": self.burn_threshold}))
            except Exception:  # noqa: BLE001 — telemetry must not raise
                pass
        return rec

    # ---------------- advisory + snapshots ----------------------------------

    def burning(self) -> Set[str]:
        return set(self._burning)

    def is_burning(self, tenant: str) -> bool:
        return tenant in self._burning

    def snapshot_tenant(self, tenant: str) -> dict:
        w = self._tenants.get(tenant)
        if w is None:
            return {}
        fast, slow = self._burns(tenant, w)
        return {"objective": self.objective(tenant),
                "fast_burn": round(fast, 3),
                "slow_burn": round(slow, 3),
                "burning": w.burning,
                "fast_total": w.fast_total.total(),
                "slow_total": w.slow_total.total()}

    def snapshot(self) -> dict:
        tenants = {}
        for tenant in list(self._tenants):
            s = self.snapshot_tenant(tenant)
            if s and (s["slow_total"] or s["burning"]):
                tenants[tenant] = s
        return {"fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "burn_threshold": self.burn_threshold,
                "cooldown_s": self.cooldown_s,
                "defaults": {"p99_ms": self.default_p99_ms,
                             "success": self.default_success},
                "overrides": {t: o.to_dict()
                              for t, o in self._objectives.items()},
                "burning": sorted(self._burning),
                "tenants": tenants}

    def summary(self) -> dict:
        """Compact gossip-digest field: who burns, and the worst pair."""
        worst_t, worst = "", 0.0
        for tenant in list(self._tenants):
            w = self._tenants.get(tenant)
            if w is None:
                continue
            fast, slow = self._burns(tenant, w)
            score = min(fast, slow)      # alert condition is the min
            if score > worst:
                worst_t, worst = tenant, score
        out: dict = {"burning": sorted(self._burning)}
        if worst_t:
            out["worst"] = {"tenant": worst_t, "burn": round(worst, 3)}
        return out

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()
            self._objectives.clear()
            self._burning.clear()
