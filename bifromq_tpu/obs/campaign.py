"""Chaos-campaign observability (ISSUE 16 tentpole leg 3).

``CampaignMonitor`` turns the PR 8 continuous profiler's per-batch ring
into per-fault-domain **degradation windows** and a **blast-radius
report**: each workload step drains the records the step produced
(``ContinuousProfiler.since`` cursor — the segment store's incremental
contract, reused verbatim), bins them by degradation tag and kernel,
and correlates them with the fault labels the campaign had live at that
step. The report separates

- the **deterministic half** — per-step batch/degradation counts and
  the contiguous degradation windows per domain — which the campaign
  folds into its replay signature ("same seed + schedule ⇒ same
  blast-radius report"), from
- the **timing half** — p50/p99 step latencies inside vs outside fault
  windows — which backs the "healthy-shard p99 stays flat" acceptance
  check but is never part of the signature (wall-clock is not
  deterministic anywhere).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .profiler import _pctl


class CampaignMonitor:
    """Per-step profiler drain + degradation-window accounting for one
    chaos campaign run. Construct it right before ``ChaosCampaign.run``
    (the cursor snapshots the ring head at construction, so pre-campaign
    batches never pollute the windows)."""

    def __init__(self, profiler=None) -> None:
        if profiler is None:
            from . import OBS
            profiler = OBS.profiler
        self.profiler = profiler
        _, self._cursor, _ = profiler.since(0)
        self.steps: List[dict] = []

    # ---------------- per-step drain (called by the campaign) --------------

    def observe_step(self, step: int, active=()) -> dict:
        recs, self._cursor, missed = self.profiler.since(self._cursor)
        degraded: Dict[str, int] = {}
        kernels: Dict[str, int] = {}
        lat: List[float] = []
        for r in recs:
            if r.degraded:
                degraded[r.degraded] = degraded.get(r.degraded, 0) + 1
            kernels[r.kernel] = kernels.get(r.kernel, 0) + 1
            lat.append(r.dispatch_s + r.ready_s + r.fetch_s + r.expand_s)
        entry = {"step": step, "faults": list(active),
                 "batches": len(recs), "missed": missed,
                 "degraded": degraded, "kernels": kernels,
                 "lat_s": lat}
        self.steps.append(entry)
        return entry

    # ---------------- windows + report -------------------------------------

    def windows(self) -> List[dict]:
        """Contiguous step spans per degradation domain: one window per
        (domain, run of consecutive steps whose batches carried that
        degradation tag). The blast-radius invariant reads directly off
        these — a single hung shard must open windows ONLY for its own
        domain, and they must close when the schedule clears the
        fault."""
        out: List[dict] = []
        open_w: Dict[str, dict] = {}
        for e in self.steps:
            seen = set(e["degraded"])
            for dom in seen:
                w = open_w.get(dom)
                if w is None:
                    w = open_w[dom] = {"domain": dom,
                                       "start_step": e["step"],
                                       "end_step": e["step"],
                                       "batches": 0}
                    out.append(w)
                w["end_step"] = e["step"]
                w["batches"] += e["degraded"][dom]
            for dom in list(open_w):
                if dom not in seen:
                    del open_w[dom]     # window closed: next hit reopens
        return out

    def _lat_split(self):
        fault_lat: List[float] = []
        clean_lat: List[float] = []
        for e in self.steps:
            (fault_lat if e["faults"] else clean_lat).extend(e["lat_s"])
        return sorted(clean_lat), sorted(fault_lat)

    def p99_ratio(self) -> Optional[float]:
        """p99(step latency under live faults) / p99(fault-free) — the
        "healthy-shard p99 within 2× fault-free baseline" acceptance
        number. None when either side has no samples."""
        clean, fault = self._lat_split()
        if not clean or not fault:
            return None
        base = _pctl(clean, 0.99)
        return (_pctl(fault, 0.99) / base) if base > 0 else None

    def report(self) -> dict:
        clean, fault = self._lat_split()
        return {
            # deterministic half (folded into the campaign signature)
            "windows": self.windows(),
            "steps": [{k: e[k] for k in
                       ("step", "faults", "batches", "degraded",
                        "kernels")}
                      for e in self.steps],
            # timing half (assertion input, never signature input)
            "latency": {
                "clean_p50_ms": _pctl(clean, 0.5) * 1e3 if clean else None,
                "clean_p99_ms": _pctl(clean, 0.99) * 1e3 if clean else None,
                "fault_p50_ms": _pctl(fault, 0.5) * 1e3 if fault else None,
                "fault_p99_ms": _pctl(fault, 0.99) * 1e3 if fault else None,
                "p99_ratio": self.p99_ratio(),
            },
        }


__all__ = ["CampaignMonitor"]
