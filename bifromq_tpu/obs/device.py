"""Device-pipeline gauges (ISSUE 3, part 4).

As the match hot path moves onto the accelerator, the broker's visibility
has to follow it below the Python line: XLA recompiles (each one stalls
serving for seconds), the dispatch queue in front of the device (the
batcher's backlog is the first thing to grow when the device slows), and
device memory watermarks. Producers register weakly — a test-scoped
matcher or scheduler must not be pinned by telemetry — and the snapshot
is assembled on demand for ``/metrics`` ``"device"`` and ``bench.py``.

jax is only touched inside a guarded, TTL-cached probe: the gauges must
stay readable (reporting zeros / unavailability) when the device tunnel
is down — that is exactly when an operator is looking at them.
"""

from __future__ import annotations

import time
import weakref
from typing import Callable, Dict, Optional


class DeviceGauges:
    MEM_PROBE_TTL_S = 5.0

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._matchers: "weakref.WeakSet" = weakref.WeakSet()
        self._schedulers: "weakref.WeakSet" = weakref.WeakSet()
        self._rings: "weakref.WeakSet" = weakref.WeakSet()
        self._mem_cache: Optional[dict] = None
        self._mem_at = -1e18
        self._mem_peak_bytes = 0

    def register_matcher(self, matcher) -> None:
        """Track a TpuMatcher's compile count/time (weakly held)."""
        self._matchers.add(matcher)

    def matchers(self) -> list:
        """Live registered matchers (ISSUE 8: the capacity model walks
        their installed bases for byte accounting)."""
        return list(self._matchers)

    def register_scheduler(self, scheduler) -> None:
        """Track a BatchCallScheduler's live queue depth (weakly held)."""
        self._schedulers.add(scheduler)

    def register_ring(self, ring) -> None:
        """Track a DispatchRing's in-flight occupancy (ISSUE 6: the async
        pipeline's half of the dispatch-queue picture — batches PAST the
        batcher queue but not yet fetched; weakly held). The adaptive
        shaping signals themselves live at the sources (Batcher._adapt's
        depth-at-emit, DispatchRing.effective_floor); this surface is
        observability only."""
        self._rings.add(ring)

    @property
    def peak_memory_bytes(self) -> int:
        """High-water device memory (ISSUE 5): last probed peak, readable
        without triggering a fresh jax probe — the gossip digest refreshes
        every second and must never block on the device tunnel."""
        return self._mem_peak_bytes

    # ---------------- probes ------------------------------------------------

    def _compile_stats(self) -> Dict[str, float]:
        count = 0
        total_s = 0.0
        for m in list(self._matchers):
            count += getattr(m, "compile_count", 0)
            total_s += getattr(m, "compile_time_s", 0.0)
        return {"compile_count": count,
                "compile_time_s": round(total_s, 3)}

    def _dispatch_stats(self) -> Dict[str, float]:
        depth = inflight = batchers = 0
        cap = 0
        for sched in list(self._schedulers):
            for b in list(getattr(sched, "_batchers", {}).values()):
                batchers += 1
                depth += len(getattr(b, "_queue", ()))
                inflight += getattr(b, "_inflight", 0)
                cap = max(cap, getattr(b, "_cap", 0))
        ring_inflight = ring_waiting = ring_peak = ring_depth = 0
        ring_timeouts = ring_quarantined = 0
        for ring in list(self._rings):
            ring_inflight += getattr(ring, "in_flight", 0)
            ring_waiting += getattr(ring, "waiting", 0)
            ring_peak = max(ring_peak, getattr(ring, "peak_inflight", 0))
            ring_depth = max(ring_depth, getattr(ring, "depth", 0))
            # ISSUE 7: watchdog reclaims + quarantined orphan buffers
            ring_timeouts += getattr(ring, "timeouts_total", 0)
            q = getattr(ring, "quarantine", None)
            if q is not None:
                ring_quarantined += len(q)
        return {"dispatch_queue_depth": depth,
                "batches_in_flight": inflight,
                "batchers": batchers,
                "max_batch_cap": cap,
                # ISSUE 6: device-side pipeline occupancy (the ring holds
                # batches already dispatched to the device, distinct from
                # the batcher queue waiting in front of it)
                "ring_in_flight": ring_inflight,
                "ring_waiting": ring_waiting,
                "ring_peak_in_flight": ring_peak,
                "ring_depth": ring_depth,
                "ring_timeouts_total": ring_timeouts,
                "ring_quarantined": ring_quarantined}

    # ---------------- overload signals (ISSUE 7) ----------------------------

    def queue_pressure(self) -> float:
        """Dispatch-ring pressure for the load shedder: the worst ring's
        (in-flight + parked waiters) / depth. 0 = idle, 1.0 = a full but
        healthy pipeline, > 1 = dispatches parked behind the ring. Pure
        attribute reads — safe on the publish hot path."""
        worst = 0.0
        for ring in list(self._rings):
            depth = getattr(ring, "depth", 0) or 1
            occ = (getattr(ring, "in_flight", 0)
                   + getattr(ring, "waiting", 0)) / depth
            if occ > worst:
                worst = occ
        return worst

    def dispatch_queue_depth(self) -> int:
        """Live batcher backlog (calls enqueued, not yet emitted) summed
        across registered schedulers — the second overload signal, read
        without the memory probe."""
        depth = 0
        for sched in list(self._schedulers):
            for b in list(getattr(sched, "_batchers", {}).values()):
                depth += len(getattr(b, "_queue", ()))
        return depth

    def memory_stats(self) -> dict:
        """Public guarded memory probe (ISSUE 8: the capacity planner's
        HBM-limit source) — TTL-cached, never triggers backend init."""
        return self._memory_stats()

    def _memory_stats(self) -> dict:
        now = self._clock()
        if (self._mem_cache is not None
                and now - self._mem_at < self.MEM_PROBE_TTL_S):
            return self._mem_cache
        out: dict = {"available": False}
        try:
            # NEVER trigger backend init from a telemetry scrape: a dead
            # device tunnel makes first-time PJRT init hang uninterruptibly
            # (bench.py probes it in a subprocess for exactly this reason).
            # Only read a backend some real device work already created.
            import sys
            if "jax" not in sys.modules:
                raise LookupError("jax not loaded")
            import jax
            from jax._src import xla_bridge as _xb
            if not getattr(_xb, "_backends", None):
                raise LookupError("jax backend not initialized")
            devs = jax.local_devices()
            per_dev = []
            for d in devs:
                try:
                    ms = d.memory_stats()
                except Exception:  # noqa: BLE001 — CPU backends lack this
                    ms = None
                if ms:
                    in_use = int(ms.get("bytes_in_use", 0))
                    self._mem_peak_bytes = max(self._mem_peak_bytes,
                                               int(ms.get(
                                                   "peak_bytes_in_use",
                                                   in_use)))
                    per_dev.append({
                        "platform": d.platform,
                        "bytes_in_use": in_use,
                        "peak_bytes_in_use": int(ms.get("peak_bytes_in_use",
                                                        in_use)),
                        "bytes_limit": int(ms.get("bytes_limit", 0)),
                    })
            out = {"available": bool(per_dev),
                   "n_devices": len(devs),
                   "platform": devs[0].platform if devs else "none",
                   "peak_bytes_in_use": self._mem_peak_bytes,
                   "devices": per_dev}
        except Exception as e:  # noqa: BLE001 — tunnel down / jax absent
            out = {"available": False,
                   "error": f"{type(e).__name__}: {e}"[:120]}
        self._mem_cache = out
        self._mem_at = now
        return out

    def snapshot(self, *, memory: bool = True) -> dict:
        """The ``/metrics`` ``"device"`` section. ``memory=False`` skips
        the jax probe (hot scrape loops on a flapping tunnel)."""
        out = {**self._compile_stats(), **self._dispatch_stats()}
        if memory:
            out["memory"] = self._memory_stats()
        return out

    def reset(self) -> None:
        self._matchers = weakref.WeakSet()
        self._schedulers = weakref.WeakSet()
        self._rings = weakref.WeakSet()
        self._mem_cache = None
        self._mem_at = -1e18
        self._mem_peak_bytes = 0
