"""Bounded segment-file telemetry store (ISSUE 8 tentpole, part 3).

The push exporter ships telemetry OUT of the process; nothing so far
keeps it ON the box. For post-hoc analysis after a TPU session ends —
"what did the compile ledger and the rtt/kernel split look like in the
minutes before the tunnel dropped" — profile records, compile events and
slow spans persist into a directory of JSON-lines **segment files** with
hard retention:

- records append to ``<prefix>-<seq>.jsonl``; when the active segment
  exceeds ``max_segment_bytes`` it is sealed and a new one opens;
- at most ``max_segments`` segments are retained — the oldest are
  deleted, so disk usage is bounded by ``max_segments ×
  max_segment_bytes`` no matter how long the process runs;
- a restart re-opens the same directory, continues the sequence
  numbering, and re-applies retention — surviving records stay readable
  (``read()``) across process generations.

Writes go through the caller's thread (the ObsHub advisory tick flushes
in batches); a lock keeps concurrent appenders safe. Torn final lines
from a crash are skipped on read, never propagated.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Dict, Iterable, List, Optional


class SegmentStore:
    def __init__(self, directory: str, *, prefix: str = "obs",
                 max_segment_bytes: int = 1 << 20,
                 max_segments: int = 8) -> None:
        if max_segment_bytes <= 0 or max_segments <= 0:
            raise ValueError("segment size and count must be positive")
        self.dir = directory
        self.prefix = prefix
        self.max_segment_bytes = max_segment_bytes
        self.max_segments = max_segments
        self._lock = threading.Lock()
        self._pat = re.compile(
            rf"^{re.escape(prefix)}-(\d+)\.jsonl$")
        os.makedirs(directory, exist_ok=True)
        # restart: continue numbering after the highest surviving segment
        existing = self._segments()
        self._seq = existing[-1][0] if existing else 0
        self.records_appended = 0
        self.rotations = 0
        self.segments_dropped = 0
        self._enforce_retention()

    # ---------------- segment bookkeeping ----------------------------------

    def _segments(self) -> List[tuple]:
        """Sorted [(seq, path)] of surviving segments."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            m = self._pat.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, name)))
        out.sort()
        return out

    def _active_path(self) -> str:
        return os.path.join(self.dir, f"{self.prefix}-{self._seq}.jsonl")

    def _rotate_if_needed(self) -> bool:
        path = self._active_path()
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size >= self.max_segment_bytes:
            self._seq += 1
            self.rotations += 1
            return True
        return False

    def _enforce_retention(self) -> None:
        segs = self._segments()
        while len(segs) > self.max_segments:
            seq, path = segs.pop(0)
            try:
                os.remove(path)
                self.segments_dropped += 1
            except OSError:
                break

    # ---------------- append / read ----------------------------------------

    def append(self, record: Dict) -> None:
        self.append_many((record,))

    def append_many(self, records: Iterable[Dict]) -> int:
        """Append records as JSON lines; returns how many were written.
        One open+write per batch — the flush tick batches, so the store
        never holds a file handle across ticks (rotation and external
        cleanup stay trivial)."""
        lines = [json.dumps(r, default=str) for r in records]
        if not lines:
            return 0
        with self._lock:
            rotated = self._rotate_if_needed()
            with open(self._active_path(), "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
            self.records_appended += len(lines)
            if rotated:
                # enforce AFTER the new active segment exists, so the
                # retained count includes it (not max_segments + 1)
                self._enforce_retention()
        return len(lines)

    def read(self, *, limit: int = 0,
             type: Optional[str] = None) -> List[Dict]:  # noqa: A002
        """All surviving records oldest-first (optionally only one
        ``type``); a torn final line (crash mid-write) is skipped."""
        out: List[Dict] = []
        with self._lock:
            segs = self._segments()
        for _, path in segs:
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if type is None or rec.get("type") == type:
                            out.append(rec)
            except OSError:
                continue
        return out[-limit:] if limit > 0 else out

    def snapshot(self) -> dict:
        segs = self._segments()
        return {
            "dir": self.dir,
            "segments": len(segs),
            "active_seq": self._seq,
            "bytes": sum(os.path.getsize(p) for _, p in segs
                         if os.path.exists(p)),
            "max_segment_bytes": self.max_segment_bytes,
            "max_segments": self.max_segments,
            "records_appended": self.records_appended,
            "rotations": self.rotations,
            "segments_dropped": self.segments_dropped,
        }
