"""Sliding-window primitives for the tenant SLO layer (ISSUE 3).

``utils/metrics`` keeps monotonic counters and cumulative log2 histograms —
good for totals, useless for "which tenant is slow *right now*". Here the
same log2-bucket discipline is windowed: a ring of time slices, each an
independent bucket array; recording lands in the current slice, snapshots
merge only the slices still inside the window, and expired slices are
zeroed lazily (decay costs nothing when nothing records).

Everything takes an injectable ``clock`` (seconds, monotonic) so decay is
deterministic under a fake clock in tests; the slice index is a pure
function of the clock value.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

# THE log2 bucket discipline, shared with utils.metrics.LatencyHistogram:
# bucket i counts samples whose microsecond value has bit_length i (the
# [2^(i-1), 2^i) range), topping out around 2 minutes; percentile
# extraction returns the bucket's upper edge (conservative).
N_BUCKETS = 28      # 2^27 µs ≈ 134 s


def bucket_index(seconds: float) -> int:
    us = int(seconds * 1e6)
    i = us.bit_length() if us > 0 else 0
    return i if i < N_BUCKETS else N_BUCKETS - 1


def percentile_ms_from(buckets, p: float) -> float:
    """Upper edge (ms) of the bucket containing the p-th percentile."""
    total = sum(buckets)
    if total == 0:
        return 0.0
    target = max(1, int(total * p / 100.0 + 0.5))
    acc = 0
    for i, c in enumerate(buckets):
        acc += c
        if acc >= target:
            return (1 << i) / 1000.0
    return (1 << (N_BUCKETS - 1)) / 1000.0


class _Sliced:
    """Shared slice-ring mechanics: ``_slot(now)`` returns the current
    slice index after zeroing any slice whose epoch fell out of the
    window. ``live_slots(now)`` yields indices still inside the window."""

    def __init__(self, window_s: float, n_slices: int,
                 clock: Callable[[], float]) -> None:
        if window_s <= 0 or n_slices <= 0:
            raise ValueError("window_s and n_slices must be positive")
        self.window_s = float(window_s)
        self.n_slices = int(n_slices)
        self._span = self.window_s / self.n_slices
        self._clock = clock
        # epoch of the data each slot currently holds (-1 = empty)
        self._epochs: List[int] = [-1] * self.n_slices

    def _epoch(self, now: float) -> int:
        return int(now / self._span)

    def _slot(self, now: float) -> int:
        epoch = self._epoch(now)
        slot = epoch % self.n_slices
        if self._epochs[slot] != epoch:
            self._zero(slot)
            self._epochs[slot] = epoch
        return slot

    def live_slots(self, now: float) -> List[int]:
        epoch = self._epoch(now)
        lo = epoch - self.n_slices + 1
        return [s for s in range(self.n_slices)
                if lo <= self._epochs[s] <= epoch]

    def _zero(self, slot: int) -> None:  # pragma: no cover — overridden
        raise NotImplementedError


class WindowedCounter(_Sliced):
    """Float-valued sliding-window accumulator (rates, shares, error
    counts). ``total()`` is the sum over the live window; ``rate()``
    normalizes by the window span."""

    def __init__(self, window_s: float = 10.0, n_slices: int = 5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(window_s, n_slices, clock)
        self._vals: List[float] = [0.0] * self.n_slices

    def _zero(self, slot: int) -> None:
        self._vals[slot] = 0.0

    def add(self, v: float = 1.0) -> None:
        self._vals[self._slot(self._clock())] += v

    def total(self) -> float:
        return sum(self._vals[s] for s in self.live_slots(self._clock()))

    def rate(self) -> float:
        return self.total() / self.window_s


class WindowedLog2Histogram(_Sliced):
    """Sliding-window log2 latency histogram: per-slice bucket arrays,
    merged at snapshot time. Recording is one list-index increment in the
    current slice — same hot-path cost discipline as the cumulative
    ``LatencyHistogram``, plus one epoch check."""

    def __init__(self, window_s: float = 10.0, n_slices: int = 5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(window_s, n_slices, clock)
        self._buckets: List[List[int]] = [[0] * N_BUCKETS
                                          for _ in range(self.n_slices)]

    def _zero(self, slot: int) -> None:
        self._buckets[slot] = [0] * N_BUCKETS

    def record(self, seconds: float) -> None:
        self._buckets[self._slot(self._clock())][
            bucket_index(seconds)] += 1

    def merged(self) -> List[int]:
        out = [0] * N_BUCKETS
        for s in self.live_slots(self._clock()):
            b = self._buckets[s]
            for i in range(N_BUCKETS):
                out[i] += b[i]
        return out

    @property
    def count(self) -> int:
        return sum(self.merged())

    def percentile_ms(self, p: float,
                      merged: Optional[List[int]] = None) -> float:
        return percentile_ms_from(
            merged if merged is not None else self.merged(), p)

    def snapshot(self) -> Dict[str, float]:
        b = self.merged()
        return {"count": sum(b),
                "p50_ms": self.percentile_ms(50, b),
                "p99_ms": self.percentile_ms(99, b)}
