"""Continuous parity auditor (ISSUE 18 leg 2).

The replication tests prove arena parity; this module makes it a
*monitored production invariant*. The leader periodically folds a
chunked BLAKE2 fingerprint of each live arena set — the single-chip
route trie, every mesh shard, the retained index — into its own delta
stream as an ordinary HLC-stamped record (op ``("audit", scope, fp,
n_chunks)``, wire tag ``b"D"``). Because the record rides the stream,
every standby compares its OWN arenas at exactly the leader's cursor:
a mismatch means the byte-replay contract broke somewhere between the
last resync and this record. The standby then raises
``PARITY_DIVERGENCE``, bumps ``REPLICATION.parity_divergence_total``
and degrades to exactly one bounded resync — the same healing ladder a
sequence gap takes.

Fingerprints are order-exact by construction: a standby installs the
leader's arenas verbatim and re-applies the identical op/plan stream,
so ``node_tab``/``edge_tab``/``child_list``/``slot_kind`` must match
byte-for-byte (the property ``assert_arena_parity`` pins in tests).
The retained scope hashes the logical (tenant, topic) set instead —
the retained standby replays SET/CLEAR through its own patcher, whose
arenas are byte-identical too, but the topic set is the authoritative
contract its scans serve from.

Layering: ``obs`` must not import ``utils.metrics`` at module scope
(that module imports ``obs`` on load); the stage histogram import is
deferred into the audit call.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.env import env_float
from .lag import REPL_EVENTS

#: arenas are fed to BLAKE2 in fixed-size chunks so one audit never
#: builds a second full-table byte copy; n_chunks rides the record as a
#: cheap cross-check that both sides hashed the same table extents
CHUNK_BYTES = 1 << 20


def audit_interval_s() -> float:
    """Leader audit cadence (seconds) on the ObsHub advisory tick."""
    return max(0.05, env_float("BIFROMQ_AUDIT_INTERVAL_S", 30.0))


def _fold(h, data: bytes) -> int:
    n = 0
    for off in range(0, len(data), CHUNK_BYTES):
        h.update(data[off:off + CHUNK_BYTES])
        n += 1
    return n


def fingerprint_arenas(pt) -> Tuple[str, int]:
    """Chunked BLAKE2 over one PatchableTrie-shaped arena set, padding
    included — the replica's tables are full-array byte-identical, so
    hashing the capacity tail is both valid and allocation-free."""
    h = hashlib.blake2b(digest_size=16)
    chunks = 0
    for arr in (pt.node_tab, pt.edge_tab, pt.child_list, pt.slot_kind):
        chunks += _fold(h, np.ascontiguousarray(arr).tobytes())
    meta = repr((int(pt.n_live), sorted(pt.tenant_root.items()),
                 len(pt.matchings))).encode()
    chunks += _fold(h, meta)
    return h.hexdigest(), chunks


def fingerprint_retained(index) -> Tuple[str, int]:
    """Logical fingerprint of a RetainedIndex: the sorted (tenant,
    topic) set — exactly what the standby's replayed SET/CLEAR stream
    must reproduce."""
    from ..replication.records import _iter_trie_routes
    h = hashlib.blake2b(digest_size=16)
    chunks = 0
    for tenant in sorted(index.tries):
        topics = sorted(r.matcher.mqtt_topic_filter
                        for r in _iter_trie_routes(index.tries[tenant]))
        chunks += _fold(h, repr((tenant, topics)).encode())
    return h.hexdigest(), chunks


def fingerprint_scope(matcher, scope: str) -> Optional[Tuple[str, int]]:
    """Resolve an audit record's scope against a (replica) matcher:
    ``route`` = the single-chip base, ``mesh:<i>`` = one shard's arena.
    Returns None when the scope does not exist here (shape drift — the
    compare is skipped, never a false divergence)."""
    base = getattr(matcher, "_base_ct", None)
    if base is None:
        return None
    if scope == "route":
        return None if hasattr(base, "compiled") \
            else fingerprint_arenas(base)
    if scope.startswith("mesh:"):
        if not hasattr(base, "compiled"):
            return None
        i = int(scope.split(":", 1)[1])
        if i >= len(base.compiled):
            return None
        return fingerprint_arenas(base.compiled[i])
    return None


class ParityAuditor:
    """Leader-side audit emitter.

    ``audit_once()`` fingerprints every live arena set and emits one
    audit op per scope through the matcher's normal delta hook
    (``_emit_delta`` — emit-only: the leader does NOT patch its own
    arenas on an audit op, and ``tenant=""`` keeps the record out of
    the cache-invalidation fan-out). ``attach()`` puts the cadence on
    the ObsHub advisory tick via :func:`audit_interval_s`.
    """

    def __init__(self, matcher, *, retained_index=None,
                 retained_log=None, clock=None) -> None:
        import time
        self.matcher = matcher
        self.retained_index = retained_index
        self.retained_log = retained_log
        self._clock = clock or time.monotonic
        self._last_at: Optional[float] = None
        self.audits = 0
        self._hooked = False

    def scopes(self) -> List[str]:
        base = getattr(self.matcher, "_base_ct", None)
        if base is None:
            return []
        if hasattr(base, "compiled"):
            return [f"mesh:{i}" for i in range(len(base.compiled))]
        return ["route"]

    def audit_once(self) -> List[Tuple]:
        """Fingerprint + emit one audit record per live scope; returns
        the emitted ops (tests assert on them)."""
        import time
        from .. import trace
        from ..utils.metrics import STAGES   # deferred: import layering
        t0 = time.perf_counter()
        ops: List[Tuple] = []
        with trace.span("repl.audit", scopes=len(self.scopes())):
            base = getattr(self.matcher, "_base_ct", None)
            if base is not None:
                for scope in self.scopes():
                    fp = fingerprint_scope(self.matcher, scope)
                    if fp is None:
                        continue
                    op = ("audit", scope, fp[0], fp[1])
                    self.matcher._emit_delta("", (), op, None, False)
                    ops.append(op)
            if self.retained_index is not None \
                    and self.retained_log is not None:
                fp_hex, chunks = fingerprint_retained(self.retained_index)
                self.retained_log.append("", (),
                                         f"audit:{fp_hex}:{chunks}")
                ops.append(("audit", "retained", fp_hex, chunks))
        if ops:
            self.audits += 1
            STAGES.record("repl.audit", time.perf_counter() - t0)
            REPL_EVENTS.append("audit_emitted", scopes=[o[1] for o in ops])
        return ops

    # ---------------- advisory-tick cadence ----------------------------

    def _tick(self) -> None:
        now = self._clock()
        if self._last_at is not None \
                and now - self._last_at < audit_interval_s():
            return
        self._last_at = now
        self.audit_once()

    def attach(self) -> None:
        if not self._hooked:
            from . import OBS
            OBS.on_advisory_tick(self._tick)
            self._hooked = True

    def detach(self) -> None:
        if self._hooked:
            from . import OBS
            OBS.remove_advisory_hook(self._tick)
            self._hooked = False

    def status(self) -> Dict[str, object]:
        return {"audits": self.audits, "scopes": self.scopes(),
                "interval_s": audit_interval_s()}
