"""End-to-end delivery latency plane (ISSUE 20, tentpole part 1).

Every *delivered* message records its publish→socket-write latency here:
the HLC stamp written at ingest (``mqtt/session`` PUBLISH handling) is
read back at the delivery write and the physical-ms delta lands in a
per-(tenant, qos, path) windowed log2 histogram. Full population — no
sampling — so the distribution can back an SLO; the per-record cost is
a handful of dict probes plus one slice-ring increment (the profiler's
ring discipline, bounded <20µs and test-enforced).

Delivery **paths** attribute where the message came from:

- ``local_fanout`` — same-process fan-out (the default);
- ``remote``       — arrived over a deliverer RPC hop (cross-process
  deltas are meaningful because HLC merges on the ``request3`` header);
- ``inbox_replay`` — persistent-session inbox drain;
- ``retained``     — retained-message replay on SUBSCRIBE;
- ``shared_sub``   — shared-subscription group delivery.

The path rides :data:`DELIVERY_PATH` (a contextvar set by the remote
deliverer entry point and the inbox drain; retained/shared-sub are
decided at the send site itself).

Messages that are *not* delivered — expiries, QoS0 discards to
unwritable channels, oversize drops, receive-maximum drops, shed
publishes, inbox overflow — are counted as **SLO violations** alongside,
keyed by reason, so the burn-rate engine sees the success ratio, not
just the latency of the survivors.

Negative deltas (physical clock skew between the publishing and the
delivering process that HLC's counter bits cannot mask) are clamped to
0 at record time and counted in ``skew_clamped`` instead of silently
polluting the low buckets.

Also here:

- :class:`ShardCompletionBoard` — per-shard dispatch→ready timing rows
  for the mesh step (tentpole part 3): a hung device is *named* with its
  shard index, recent ready-latency history feeds per-shard deadline
  hints while a breaker is half-open.
- degraded-attribution map — the mesh/matcher timeout path marks which
  shard/device is degrading deliveries; ``GET /slo`` surfaces it next
  to the latency distribution it explains.
- write-buffer watermark watch — bounded per-connection time above
  ``SEND_BUFFER_HIGH_WATER`` backing the ``SLOW_CONSUMER`` event.

Layering: like the rest of ``obs`` this module must NOT import
``utils.metrics`` (that module imports ``obs`` at import time).
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.hlc import HLC
from .window import WindowedCounter, WindowedLog2Histogram

# the delivery-path attribution a record site inherits when it does not
# decide the path itself (remote RPC entry + inbox drain set it around
# their deliver calls; plain local fan-out leaves the default)
DELIVERY_PATH: contextvars.ContextVar[str] = contextvars.ContextVar(
    "bifromq_delivery_path", default="local_fanout")

PATHS = ("local_fanout", "remote", "inbox_replay", "retained",
         "shared_sub")

# violation reasons (dict keys in snapshots; bounded by construction)
VIOLATIONS = ("expired", "discard", "oversize", "recv_max", "shed",
              "deliver_error", "inbox_overflow")


class _TenantE2E:
    """One tenant's live e2e state: per-(qos, path) latency histograms
    plus per-reason violation windows."""

    __slots__ = ("hists", "violations", "viol_total", "_mk_hist",
                 "_mk_counter")

    def __init__(self, mk_counter, mk_hist) -> None:
        self.hists: Dict[Tuple[int, str], WindowedLog2Histogram] = {}
        self.violations: Dict[str, WindowedCounter] = {}
        self.viol_total = mk_counter()
        self._mk_hist = mk_hist
        self._mk_counter = mk_counter

    def hist(self, qos: int, path: str) -> WindowedLog2Histogram:
        key = (qos, path)
        h = self.hists.get(key)
        if h is None:
            h = self.hists.setdefault(key, self._mk_hist())
        return h

    def violation(self, reason: str) -> WindowedCounter:
        c = self.violations.get(reason)
        if c is None:
            c = self.violations.setdefault(reason, self._mk_counter())
        return c


class E2EPlane:
    """The windowed publish→deliver registry. Same threading contract as
    ``TenantSLO``: locked registration, GIL-atomic recording."""

    def __init__(self, *, window_s: float = 10.0, n_slices: int = 5,
                 max_tenants: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 wall_ms: Callable[[], float] = None) -> None:
        self.window_s = float(window_s)
        self.n_slices = int(n_slices)
        self.max_tenants = int(max_tenants)
        self._clock = clock
        # wall-clock ms source for the HLC delta (injectable so tests can
        # pin both ends of the subtraction)
        self._wall_ms = wall_ms or (lambda: time.time() * 1000.0)
        self._tenants: Dict[str, _TenantE2E] = {}
        self._lock = threading.Lock()
        # satellite: negative publish→deliver deltas clamped at record
        self.skew_clamped = 0
        # degraded attribution: component name -> {"reason", "since"}
        self._degraded: Dict[str, dict] = {}
        # write-buffer watermark watch: conn key -> monotonic ts the
        # buffer went above high water (bounded FIFO like tenants)
        self._over_since: Dict[str, float] = {}
        self.slow_consumer_events = 0

    def _mk_counter(self) -> WindowedCounter:
        return WindowedCounter(self.window_s, self.n_slices, self._clock)

    def _mk_hist(self) -> WindowedLog2Histogram:
        return WindowedLog2Histogram(self.window_s, self.n_slices,
                                     self._clock)

    def _windows(self, tenant: str) -> _TenantE2E:
        w = self._tenants.get(tenant)
        if w is None:
            with self._lock:
                w = self._tenants.get(tenant)
                if w is None:
                    if len(self._tenants) >= self.max_tenants:
                        self._tenants.pop(next(iter(self._tenants)))
                    w = _TenantE2E(self._mk_counter, self._mk_hist)
                    self._tenants[tenant] = w
        return w

    # ---------------- recording (hot path) ---------------------------------

    def record(self, tenant: str, qos: int, path: str,
               publish_hlc: int) -> float:
        """Fold one delivered message; returns the (clamped) latency in
        seconds. Called at the socket-write site for EVERY delivery."""
        delta_ms = self._wall_ms() - HLC.INST.physical(publish_hlc)
        if delta_ms < 0:
            # HLC merging bounds the *logical* order, not the physical
            # skew between hosts — clamp and count instead of polluting
            # the low buckets with wrapped garbage
            self.skew_clamped += 1
            delta_ms = 0.0
        seconds = delta_ms / 1000.0
        self._windows(tenant).hist(qos, path).record(seconds)
        return seconds

    def record_violation(self, tenant: str, qos: int, reason: str) -> None:
        """A message that should have been delivered was not (expiry,
        discard, drop, shed, overflow) — the SLO denominator still grows
        and the burn engine sees the failure."""
        w = self._windows(tenant)
        w.viol_total.add(1.0)
        w.violation(reason).add(1.0)

    # ---------------- degraded attribution (tentpole part 3) ----------------

    def set_degraded(self, name: str, reason: str) -> None:
        """Name a component (``mesh:shard2``, device tag…) currently
        degrading deliveries. Bounded; re-marking refreshes the reason
        but keeps the original ``since``."""
        with self._lock:
            cur = self._degraded.get(name)
            if cur is not None:
                cur["reason"] = reason
                return
            if len(self._degraded) >= 64:
                self._degraded.pop(next(iter(self._degraded)))
            self._degraded[name] = {"reason": reason,
                                    "since": round(time.time(), 3)}

    def clear_degraded(self, name: str) -> None:
        with self._lock:
            self._degraded.pop(name, None)

    def degraded(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._degraded.items()}

    # ---------------- write-buffer watermark watch (satellite) --------------

    def note_watermark(self, key: str, above: bool) -> float:
        """Track one connection's continuous time above the send-buffer
        high water mark. Returns the current seconds-above (0.0 once the
        buffer drains below). Cardinality is bounded: only connections
        currently above hold an entry."""
        now = self._clock()
        since = self._over_since.get(key)
        if above:
            if since is None:
                if len(self._over_since) >= 1024:
                    with self._lock:
                        if len(self._over_since) >= 1024:
                            self._over_since.pop(
                                next(iter(self._over_since)))
                self._over_since[key] = now
                return 0.0
            return now - since
        if since is not None:
            self._over_since.pop(key, None)
        return 0.0

    def drop_watermark(self, key: str) -> None:
        """Connection closed — forget its watermark state."""
        self._over_since.pop(key, None)

    def watermark_gauges(self) -> dict:
        now = self._clock()
        over = list(self._over_since.values())
        return {"over_high_water": len(over),
                "max_over_s": round(max((now - s for s in over),
                                        default=0.0), 3),
                "slow_consumer_events": self.slow_consumer_events}

    # ---------------- snapshots --------------------------------------------

    def snapshot_tenant(self, tenant: str) -> dict:
        w = self._tenants.get(tenant)
        if w is None:
            return {}
        paths: Dict[str, dict] = {}
        for (qos, path), h in list(w.hists.items()):
            s = h.snapshot()        # ONE merge per histogram
            if s["count"]:
                paths.setdefault(path, {})[f"qos{qos}"] = s
        violations = {}
        for reason, c in list(w.violations.items()):
            t = c.total()
            if t:
                violations[reason] = t
        out: dict = {}
        if paths:
            out["paths"] = paths
        if violations or w.viol_total.total():
            out["violations"] = violations
            out["violations_total"] = w.viol_total.total()
        return out

    def snapshot(self) -> dict:
        tenants = {}
        for tenant in list(self._tenants):
            s = self.snapshot_tenant(tenant)
            if s:
                tenants[tenant] = s
        return {"window_s": self.window_s,
                "tenants": tenants,
                "skew_clamped": self.skew_clamped,
                "degraded": self.degraded(),
                "write_buffer": self.watermark_gauges()}

    def qos_rollup(self) -> dict:
        """Per-qos p50/p99 + violation totals across every tenant/path —
        the compact shape bench.py stamps into broker-bench records."""
        from .window import N_BUCKETS, percentile_ms_from
        merged: Dict[int, List[int]] = {}
        violations = 0.0
        for w in list(self._tenants.values()):
            for (qos, _path), h in list(w.hists.items()):
                b = h.merged()
                acc = merged.setdefault(qos, [0] * N_BUCKETS)
                for i in range(N_BUCKETS):
                    acc[i] += b[i]
            violations += w.viol_total.total()
        out = {}
        for qos, b in sorted(merged.items()):
            out[f"qos{qos}"] = {"count": sum(b),
                                "p50_ms": percentile_ms_from(b, 50),
                                "p99_ms": percentile_ms_from(b, 99)}
        out["violations"] = violations
        out["skew_clamped"] = self.skew_clamped
        return out

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()
            self._degraded.clear()
            self._over_since.clear()
            self.skew_clamped = 0
            self.slow_consumer_events = 0


class _ShardRow:
    """One shard's recent completion history."""

    __slots__ = ("ready_s", "last_ready_s", "timeouts", "hung",
                 "hung_since", "hung_reason")

    def __init__(self) -> None:
        self.ready_s: List[float] = []
        self.last_ready_s = 0.0
        self.timeouts = 0
        self.hung = False
        self.hung_since: Optional[float] = None
        self.hung_reason = ""


class ShardCompletionBoard:
    """Per-shard dispatch→ready completion attribution for the mesh step
    (ISSUE 20 tentpole part 3; closes the ROADMAP replication/retained
    follow-up (d)).

    The mesh matcher's await leg reports one row per dispatched shard —
    ``note_ready`` when the shard's leaves became ready, ``note_hung``
    when its deadline lapsed — so the ``/mesh`` surface names *which*
    device stalled the collective step instead of a step-wide anonymous
    timeout. Recent ready rows feed :meth:`deadline_hint`: while a shard
    breaker is half-open its canary probes run against a deadline scaled
    to the shard's own recent completion latency, not the global knob.
    """

    HISTORY = 32

    def __init__(self) -> None:
        self._rows: Dict[int, _ShardRow] = {}
        self._lock = threading.Lock()

    def _row(self, shard: int) -> _ShardRow:
        r = self._rows.get(shard)
        if r is None:
            with self._lock:
                r = self._rows.setdefault(shard, _ShardRow())
        return r

    def note_ready(self, shard: int, dt_s: float) -> None:
        r = self._row(shard)
        r.last_ready_s = dt_s
        r.ready_s.append(dt_s)
        if len(r.ready_s) > self.HISTORY:
            del r.ready_s[: len(r.ready_s) - self.HISTORY]
        if r.hung:
            r.hung = False
            r.hung_since = None
            r.hung_reason = ""

    def note_hung(self, shard: int, reason: str = "deadline") -> None:
        r = self._row(shard)
        r.timeouts += 1
        if not r.hung:
            r.hung = True
            r.hung_since = round(time.time(), 3)
        r.hung_reason = reason

    def note_recovered(self, shard: int) -> None:
        r = self._rows.get(shard)
        if r is not None and r.hung:
            r.hung = False
            r.hung_since = None
            r.hung_reason = ""

    def hung_shards(self) -> List[int]:
        return sorted(s for s, r in self._rows.items() if r.hung)

    def deadline_hint(self, shard: int, default_s: Optional[float]
                      ) -> Optional[float]:
        """A per-shard deadline for half-open canary probes: ~4× the
        shard's worst recent ready latency, floored at 50ms, never above
        the configured default. With no history (or no default) the
        default stands — a hint must only ever tighten."""
        r = self._rows.get(shard)
        if r is None or len(r.ready_s) < 4 or default_s is None:
            return default_s
        hint = max(0.05, 4.0 * max(r.ready_s))
        return min(default_s, hint)

    def snapshot(self) -> dict:
        shards = {}
        for s, r in sorted(self._rows.items()):
            row = {"last_ready_ms": round(r.last_ready_s * 1000.0, 3),
                   "timeouts": r.timeouts,
                   "hung": r.hung}
            if r.ready_s:
                row["recent_max_ms"] = round(max(r.ready_s) * 1000.0, 3)
                row["recent_n"] = len(r.ready_s)
            if r.hung:
                row["hung_since"] = r.hung_since
                row["reason"] = r.hung_reason
            shards[str(s)] = row
        return {"shards": shards, "hung": self.hung_shards()}

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
